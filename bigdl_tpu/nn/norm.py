"""Normalization layers.

Parity: reference ``nn/BatchNormalization.scala``,
``nn/SpatialBatchNormalization.scala``, ``nn/LayerNormalization.scala``,
``nn/SpatialCrossMapLRN.scala``, ``nn/SpatialWithinChannelLRN.scala``,
``nn/Normalize.scala``, ``nn/NormalizeScale.scala``,
``nn/SpatialContrastiveNormalization.scala``,
``nn/SpatialDivisiveNormalization.scala``,
``nn/SpatialSubtractiveNormalization.scala``, ``nn/Masking.scala``.

BatchNorm running stats live in module *state* (non-trainable collection) and
the new state is returned from ``apply`` — the pure-functional analog of the
reference's mutable runningMean/runningVar buffers.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .module import Module


class BatchNormalization(Module):
    """BN over (B, C) input; reduce over batch dim (nn/BatchNormalization.scala).

    momentum semantics match the reference: running = (1-m)*running + m*batch.
    """

    _channel_axis = 1

    def __init__(self, n_output: int, eps: float = 1e-5, momentum: float = 0.1,
                 affine: bool = True, init_weight=None, init_bias=None,
                 name=None):
        super().__init__(name=name)
        self.n_output = n_output
        self.eps, self.momentum, self.affine = eps, momentum, affine
        self.init_weight, self.init_bias = init_weight, init_bias

    def _init_params(self, rng):
        if not self.affine:
            return {}
        w = (jnp.asarray(self.init_weight) if self.init_weight is not None
             else jnp.ones((self.n_output,)))
        b = (jnp.asarray(self.init_bias) if self.init_bias is not None
             else jnp.zeros((self.n_output,)))
        return {"weight": w, "bias": b}

    def _init_state(self):
        return {"running_mean": jnp.zeros((self.n_output,)),
                "running_var": jnp.ones((self.n_output,))}

    def _apply(self, params, state, x, training, rng):
        ch = self._channel_axis % x.ndim  # -1 (NHWC) → last axis
        ax = tuple(i for i in range(x.ndim) if i != ch)
        bshape = [1] * x.ndim
        bshape[ch] = self.n_output
        xf = x.astype(jnp.float32)  # stats always in f32 (bf16-safe)
        if training:
            # shifted one-pass stats: E[(x−s)²]−E[x−s]² with s = the running
            # mean (stop-gradient, free — no extra pass over x). One fused
            # read of the activation, vs jnp.var's two dependent passes (a
            # second full HBM sweep per BN layer, profiled ~20% of the
            # ResNet-50 step); the shift keeps the subtraction from
            # catastrophically cancelling when activation means are large
            # relative to their spread (plain E[x²]−E[x]² loses precision at
            # mean ≫ std even in f32). f32 accumulation keeps it bf16-safe.
            shift = lax.stop_gradient(
                state["running_mean"].astype(jnp.float32))
            xs = xf - shift.reshape(bshape)
            m1 = jnp.mean(xs, axis=ax)
            var = jnp.maximum(jnp.mean(jnp.square(xs), axis=ax)
                              - jnp.square(m1), 0.0)
            mean = m1 + shift
            n = x.size // self.n_output
            unbiased = var * n / max(n - 1, 1)
            new_state = {
                "running_mean": (1 - self.momentum) * state["running_mean"]
                + self.momentum * mean,
                "running_var": (1 - self.momentum) * state["running_var"]
                + self.momentum * unbiased,
            }
        else:
            mean, var = state["running_mean"], state["running_var"]
            new_state = state
        inv = lax.rsqrt(var + self.eps)
        y = (x - mean.reshape(bshape)) * inv.reshape(bshape)
        if self.affine:
            y = y * params["weight"].reshape(bshape) + \
                params["bias"].reshape(bshape)
        # keep activation dtype (bf16 flows through; stats stay f32)
        return y.astype(x.dtype), new_state


class SpatialBatchNormalization(BatchNormalization):
    """Per-channel BN over NCHW or NHWC (nn/SpatialBatchNormalization.scala;
    ``data_format`` mirrors the reference's DataFormat param)."""

    def __init__(self, n_output, eps=1e-5, momentum=0.1, affine=True,
                 init_weight=None, init_bias=None, data_format="NCHW",
                 name=None):
        super().__init__(n_output, eps, momentum, affine, init_weight,
                         init_bias, name=name)
        assert data_format in ("NCHW", "NHWC"), data_format
        if data_format == "NHWC":
            self._channel_axis = -1


class VolumetricBatchNormalization(BatchNormalization):
    """BN over NCDHW, per-channel."""


class LayerNormalization(Module):
    """LayerNorm over the last dim (nn/LayerNormalization.scala)."""

    def __init__(self, hidden_size: int, eps: float = 1e-6, name=None):
        super().__init__(name=name)
        self.hidden_size, self.eps = hidden_size, eps

    def _init_params(self, rng):
        return {"weight": jnp.ones((self.hidden_size,)),
                "bias": jnp.zeros((self.hidden_size,))}

    def _apply(self, params, state, x, training, rng):
        mean = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
        y = (x - mean) * lax.rsqrt(var + self.eps)
        return y * params["weight"] + params["bias"]


class SpatialCrossMapLRN(Module):
    """AlexNet-style LRN across channels (nn/SpatialCrossMapLRN.scala):
    y = x / (k + alpha/n * sum_{nearby c} x^2)^beta."""

    def __init__(self, size: int = 5, alpha: float = 1.0, beta: float = 0.75,
                 k: float = 1.0, name=None):
        super().__init__(name=name)
        self.size, self.alpha, self.beta, self.k = size, alpha, beta, k

    def _apply(self, params, state, x, training, rng):
        sq = jnp.square(x)
        half = (self.size - 1) // 2
        extra = self.size - 1 - half
        s = lax.reduce_window(sq, 0.0, lax.add, (1, self.size, 1, 1),
                              (1, 1, 1, 1),
                              [(0, 0), (half, extra), (0, 0), (0, 0)])
        denom = jnp.power(self.k + (self.alpha / self.size) * s, self.beta)
        return x / denom


class SpatialWithinChannelLRN(Module):
    """LRN within each channel over a spatial window
    (nn/SpatialWithinChannelLRN.scala)."""

    def __init__(self, size: int = 5, alpha: float = 1.0, beta: float = 0.75,
                 name=None):
        super().__init__(name=name)
        self.size, self.alpha, self.beta = size, alpha, beta

    def _apply(self, params, state, x, training, rng):
        sq = jnp.square(x)
        half = (self.size - 1) // 2
        extra = self.size - 1 - half
        s = lax.reduce_window(sq, 0.0, lax.add, (1, 1, self.size, self.size),
                              (1, 1, 1, 1),
                              [(0, 0), (0, 0), (half, extra), (half, extra)])
        denom = jnp.power(1.0 + (self.alpha / (self.size * self.size)) * s,
                          self.beta)
        return x / denom


class Normalize(Module):
    """Lp-normalise over feature dim (nn/Normalize.scala)."""

    def __init__(self, p: float = 2.0, eps: float = 1e-10, name=None):
        super().__init__(name=name)
        self.p, self.eps = p, eps

    def _norm(self, x):
        if np.isinf(self.p):
            n = jnp.max(jnp.abs(x), axis=1, keepdims=True)
        elif self.p == 2.0:
            n = jnp.sqrt(jnp.sum(jnp.square(x), axis=1, keepdims=True))
        else:
            n = jnp.power(jnp.sum(jnp.power(jnp.abs(x), self.p), axis=1,
                                  keepdims=True), 1.0 / self.p)
        return n

    def _apply(self, params, state, x, training, rng):
        return x / (self._norm(x) + self.eps)


class NormalizeScale(Module):
    """L2-normalise channels then scale by a learnable per-channel weight
    (nn/NormalizeScale.scala — SSD's conv4_3 norm)."""

    def __init__(self, p: float = 2.0, eps: float = 1e-10, scale: float = 1.0,
                 size=None, w_regularizer=None, name=None):
        super().__init__(name=name)
        self.p, self.eps, self.scale = p, eps, scale
        self.size = tuple(size) if size is not None else None

    def _init_params(self, rng):
        return {"weight": jnp.full(self.size, self.scale)}

    def _apply(self, params, state, x, training, rng):
        n = jnp.power(jnp.sum(jnp.power(jnp.abs(x), self.p), axis=1,
                              keepdims=True), 1.0 / self.p)
        y = x / (n + self.eps)
        w = params["weight"]
        if w.ndim < x.ndim:
            w = w.reshape((1,) * (x.ndim - w.ndim) + w.shape)
        return y * w


def _gaussian_2d(size):
    k = np.arange(size) - (size - 1) / 2.0
    g = np.exp(-(k ** 2) / (2.0 * (size / 4.0) ** 2))
    g2 = np.outer(g, g)
    return (g2 / g2.sum()).astype(np.float32)


class SpatialSubtractiveNormalization(Module):
    """Subtract weighted local mean (nn/SpatialSubtractiveNormalization.scala)."""

    def __init__(self, n_input_plane: int = 1, kernel=None, name=None):
        super().__init__(name=name)
        self.n_input_plane = n_input_plane
        self.kernel = (np.asarray(kernel, np.float32) if kernel is not None
                       else _gaussian_2d(9))
        if self.kernel.ndim == 1:
            self.kernel = np.outer(self.kernel, self.kernel)
        self.kernel = self.kernel / self.kernel.sum()

    def _local_mean(self, x):
        kh, kw = self.kernel.shape
        w = jnp.asarray(self.kernel)[None, None].repeat(self.n_input_plane, 0)
        mean = lax.conv_general_dilated(
            x, w, (1, 1), [(kh // 2, (kh - 1) // 2), (kw // 2, (kw - 1) // 2)],
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
            feature_group_count=self.n_input_plane)
        # edge correction: divide by the actual kernel mass inside the image
        ones = jnp.ones_like(x[:, :1])
        mass = lax.conv_general_dilated(
            ones, jnp.asarray(self.kernel)[None, None], (1, 1),
            [(kh // 2, (kh - 1) // 2), (kw // 2, (kw - 1) // 2)],
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        return mean / mass

    def _apply(self, params, state, x, training, rng):
        return x - self._local_mean(x)


class SpatialDivisiveNormalization(Module):
    """Divide by local std (nn/SpatialDivisiveNormalization.scala)."""

    def __init__(self, n_input_plane: int = 1, kernel=None,
                 threshold: float = 1e-4, thresval: float = 1e-4, name=None):
        super().__init__(name=name)
        self.sub = SpatialSubtractiveNormalization(n_input_plane, kernel)
        self.threshold, self.thresval = threshold, thresval

    def _apply(self, params, state, x, training, rng):
        local_var = self.sub._local_mean(jnp.square(x))
        local_std = jnp.sqrt(jnp.maximum(local_var, 0.0))
        mean_std = jnp.mean(local_std, axis=(2, 3), keepdims=True)
        denom = jnp.maximum(local_std, mean_std)
        denom = jnp.where(denom < self.threshold, self.thresval, denom)
        return x / denom


class SpatialContrastiveNormalization(Module):
    """Subtractive then divisive (nn/SpatialContrastiveNormalization.scala)."""

    def __init__(self, n_input_plane: int = 1, kernel=None,
                 threshold: float = 1e-4, thresval: float = 1e-4, name=None):
        super().__init__(name=name)
        self.subn = SpatialSubtractiveNormalization(n_input_plane, kernel)
        self.divn = SpatialDivisiveNormalization(n_input_plane, kernel,
                                                 threshold, thresval)

    def _apply(self, params, state, x, training, rng):
        y, _ = self.subn.apply({}, {}, x, training, rng)
        y, _ = self.divn.apply({}, {}, y, training, rng)
        return y


class Masking(Module):
    """Zero out timesteps equal to mask_value (nn/Masking.scala)."""

    def __init__(self, mask_value: float = 0.0, name=None):
        super().__init__(name=name)
        self.mask_value = mask_value

    def _apply(self, params, state, x, training, rng):
        keep = jnp.any(x != self.mask_value, axis=-1, keepdims=True)
        return x * keep.astype(x.dtype)
