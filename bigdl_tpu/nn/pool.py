"""Pooling layers.

Parity: reference ``nn/SpatialMaxPooling.scala``,
``nn/SpatialAveragePooling.scala``, ``nn/TemporalMaxPooling.scala``,
``nn/VolumetricMaxPooling.scala``, ``nn/VolumetricAveragePooling.scala``,
``nn/RoiPooling.scala``. All lower to ``lax.reduce_window`` (fused by XLA).
Ceil mode is realised by asymmetric right-padding before a VALID window.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .module import Module


def _pool_pads(size, k, stride, pad, ceil_mode):
    """Compute (lo, hi) padding for one spatial dim. ``pad == -1`` means SAME
    (keras border_mode='same'; same convention as conv.py)."""
    if pad == -1:
        out = int(np.ceil(size / stride))
        total = max(0, (out - 1) * stride + k - size)
        return (total // 2, total - total // 2), out
    if ceil_mode:
        out = int(np.ceil((size + 2 * pad - k) / stride)) + 1
        # torch convention: last window must start inside the padded input
        if pad > 0 and (out - 1) * stride >= size + pad:
            out -= 1
    else:
        out = int(np.floor((size + 2 * pad - k) / stride)) + 1
    needed = max(0, (out - 1) * stride + k - size - pad)
    return (pad, needed), out


class _Pool2D(Module):
    def __init__(self, kw, kh, dw=None, dh=None, pad_w=0, pad_h=0,
                 format="NCHW", name=None):
        super().__init__(name=name)
        assert format in ("NCHW", "NHWC"), format
        self.format = format
        self.kw, self.kh = kw, kh
        self.dw = dw if dw is not None else kw
        self.dh = dh if dh is not None else kh
        self.pad_w, self.pad_h = pad_w, pad_h
        self.ceil_mode = False

    def ceil(self):
        self.ceil_mode = True
        return self

    def floor(self):
        self.ceil_mode = False
        return self

    def _hw(self, x):
        return (x.shape[1], x.shape[2]) if self.format == "NHWC" else \
            (x.shape[-2], x.shape[-1])

    def _pads(self, x):
        h, w = self._hw(x)
        ph, _ = _pool_pads(h, self.kh, self.dh, self.pad_h, self.ceil_mode)
        pw, _ = _pool_pads(w, self.kw, self.dw, self.pad_w, self.ceil_mode)
        return ph, pw

    def _window(self, kh, kw, dh, dw, ph, pw):
        """(dims, strides, pads) laid out for this format."""
        if self.format == "NHWC":
            return ((1, kh, kw, 1), (1, dh, dw, 1),
                    [(0, 0), ph, pw, (0, 0)])
        return ((1, 1, kh, kw), (1, 1, dh, dw),
                [(0, 0), (0, 0), ph, pw])


class SpatialMaxPooling(_Pool2D):
    """nn/SpatialMaxPooling.scala (NCHW or NHWC).

    ``grad_mode``:
      * ``"exact"`` (default) — reduce_window forward; backward is XLA's
        select_and_scatter (gradient to the FIRST max, torch semantics).
      * ``"fast"`` — the forward is computed as a maximum-tree over the
        k*k shifted strided slices; identical outputs, but the backward
        autodiffs through ``jnp.maximum`` selects (scatter-free, fuses as
        elementwise on TPU — select_and_scatter is ~1.5 ms/step of the
        ResNet-50 profile). Tie-breaking differs: exact ties split the
        gradient 50/50 instead of picking the first.
    """

    def __init__(self, kw, kh, dw=None, dh=None, pad_w=0, pad_h=0,
                 format="NCHW", grad_mode: str = "exact", name=None):
        super().__init__(kw, kh, dw, dh, pad_w, pad_h, format=format,
                         name=name)
        assert grad_mode in ("exact", "fast"), grad_mode
        self.grad_mode = grad_mode

    def _fast_pool(self, x, ph, pw):
        """max over k*k shifted strided slices (scatter-free backward)."""
        if self.format == "NHWC":
            pad_cfg = [(0, 0, 0), ph + (0,), pw + (0,), (0, 0, 0)]
            hax, wax = 1, 2
        else:
            pad_cfg = [(0, 0, 0), (0, 0, 0), ph + (0,), pw + (0,)]
            hax, wax = 2, 3
        xp = lax.pad(x, jnp.asarray(-jnp.inf, x.dtype), pad_cfg)
        hp, wp = xp.shape[hax], xp.shape[wax]
        out_h = (hp - self.kh) // self.dh + 1
        out_w = (wp - self.kw) // self.dw + 1
        y = None
        for i in range(self.kh):
            for j in range(self.kw):
                sl = [slice(None)] * x.ndim
                sl[hax] = slice(i, i + (out_h - 1) * self.dh + 1, self.dh)
                sl[wax] = slice(j, j + (out_w - 1) * self.dw + 1, self.dw)
                piece = xp[tuple(sl)]
                y = piece if y is None else jnp.maximum(y, piece)
        return y

    def _apply(self, params, state, x, training, rng):
        squeeze = False
        if x.ndim == 3:
            x, squeeze = x[None], True
        ph, pw = self._pads(x)
        if self.grad_mode == "fast":
            y = self._fast_pool(x, ph, pw)
        else:
            dims, strides, pads = self._window(self.kh, self.kw, self.dh,
                                               self.dw, ph, pw)
            y = lax.reduce_window(x, -jnp.inf, lax.max, dims, strides, pads)
        return y[0] if squeeze else y


class SpatialAveragePooling(_Pool2D):
    """nn/SpatialAveragePooling.scala. count_include_pad matches reference
    default (True); ``global_pooling`` pools the whole plane."""

    def __init__(self, kw, kh, dw=None, dh=None, pad_w=0, pad_h=0,
                 global_pooling=False, ceil_mode=False,
                 count_include_pad=True, divide=True, format="NCHW",
                 name=None):
        super().__init__(kw, kh, dw, dh, pad_w, pad_h, format=format,
                         name=name)
        self.ceil_mode = ceil_mode
        self.count_include_pad = count_include_pad
        self.divide = divide
        self.global_pooling = global_pooling

    def _apply(self, params, state, x, training, rng):
        squeeze = False
        if x.ndim == 3:
            x, squeeze = x[None], True
        kh, kw = self.kh, self.kw
        dh, dw = self.dh, self.dw
        if self.global_pooling:
            kh, kw = self._hw(x)
            dh, dw = 1, 1
            ph = pw = (0, 0)
        else:
            ph, pw = self._pads(x)
        dims, strides, pads = self._window(kh, kw, dh, dw, ph, pw)
        s = lax.reduce_window(x, 0.0, lax.add, dims, strides, pads)
        if not self.divide:
            y = s
        elif self.count_include_pad:
            y = s / (kh * kw)
        else:
            ones = jnp.ones_like(x)
            cnt = lax.reduce_window(ones, 0.0, lax.add, dims, strides, pads)
            y = s / cnt
        return y[0] if squeeze else y


class TemporalMaxPooling(Module):
    """1-D max pooling over (B, T, C) (nn/TemporalMaxPooling.scala)."""

    def __init__(self, k_w: int, d_w: int = None, name=None):
        super().__init__(name=name)
        self.k_w = k_w
        self.d_w = d_w if d_w is not None else k_w

    def _apply(self, params, state, x, training, rng):
        squeeze = False
        if x.ndim == 2:
            x, squeeze = x[None], True
        y = lax.reduce_window(x, -jnp.inf, lax.max, (1, self.k_w, 1),
                              (1, self.d_w, 1), "VALID")
        return y[0] if squeeze else y


class VolumetricMaxPooling(Module):
    """nn/VolumetricMaxPooling.scala (NCDHW)."""

    def __init__(self, kt, kw, kh, dt=None, dw=None, dh=None,
                 pad_t=0, pad_w=0, pad_h=0, name=None):
        super().__init__(name=name)
        self.kt, self.kw, self.kh = kt, kw, kh
        self.dt = dt if dt is not None else kt
        self.dw = dw if dw is not None else kw
        self.dh = dh if dh is not None else kh
        self.pad_t, self.pad_w, self.pad_h = pad_t, pad_w, pad_h
        self.ceil_mode = False

    def ceil(self):
        self.ceil_mode = True
        return self

    def _apply(self, params, state, x, training, rng):
        squeeze = False
        if x.ndim == 4:
            x, squeeze = x[None], True
        t, h, w = x.shape[-3:]
        pt, _ = _pool_pads(t, self.kt, self.dt, self.pad_t, self.ceil_mode)
        ph, _ = _pool_pads(h, self.kh, self.dh, self.pad_h, self.ceil_mode)
        pw, _ = _pool_pads(w, self.kw, self.dw, self.pad_w, self.ceil_mode)
        y = lax.reduce_window(
            x, -jnp.inf, lax.max, (1, 1, self.kt, self.kh, self.kw),
            (1, 1, self.dt, self.dh, self.dw),
            [(0, 0), (0, 0), pt, ph, pw])
        return y[0] if squeeze else y


class VolumetricAveragePooling(VolumetricMaxPooling):
    """nn/VolumetricAveragePooling.scala."""

    def __init__(self, kt, kw, kh, dt=None, dw=None, dh=None,
                 pad_t=0, pad_w=0, pad_h=0, count_include_pad=True, name=None):
        super().__init__(kt, kw, kh, dt, dw, dh, pad_t, pad_w, pad_h, name=name)
        self.count_include_pad = count_include_pad

    def _apply(self, params, state, x, training, rng):
        squeeze = False
        if x.ndim == 4:
            x, squeeze = x[None], True
        t, h, w = x.shape[-3:]
        pt, _ = _pool_pads(t, self.kt, self.dt, self.pad_t, self.ceil_mode)
        ph, _ = _pool_pads(h, self.kh, self.dh, self.pad_h, self.ceil_mode)
        pw, _ = _pool_pads(w, self.kw, self.dw, self.pad_w, self.ceil_mode)
        s = lax.reduce_window(
            x, 0.0, lax.add, (1, 1, self.kt, self.kh, self.kw),
            (1, 1, self.dt, self.dh, self.dw),
            [(0, 0), (0, 0), pt, ph, pw])
        if self.count_include_pad:
            y = s / (self.kt * self.kh * self.kw)
        else:
            cnt = lax.reduce_window(
                jnp.ones_like(x), 0.0, lax.add,
                (1, 1, self.kt, self.kh, self.kw),
                (1, 1, self.dt, self.dh, self.dw),
                [(0, 0), (0, 0), pt, ph, pw])
            y = s / cnt
        return y[0] if squeeze else y


class RoiPooling(Module):
    """ROI max pooling (nn/RoiPooling.scala). Input: Table(features NCHW,
    rois (R, 5) [batchIdx, x1, y1, x2, y2] in input-pixel coords)."""

    def __init__(self, pooled_w: int, pooled_h: int, spatial_scale: float = 1.0,
                 name=None):
        super().__init__(name=name)
        self.pooled_w, self.pooled_h = pooled_w, pooled_h
        self.spatial_scale = spatial_scale

    def _apply(self, params, state, x, training, rng):
        feats, rois = x[1], x[2]
        B, C, H, W = feats.shape

        def pool_one(roi):
            bi = roi[0].astype(jnp.int32)
            x1 = jnp.round(roi[1] * self.spatial_scale)
            y1 = jnp.round(roi[2] * self.spatial_scale)
            x2 = jnp.round(roi[3] * self.spatial_scale)
            y2 = jnp.round(roi[4] * self.spatial_scale)
            rw = jnp.maximum(x2 - x1 + 1, 1.0)
            rh = jnp.maximum(y2 - y1 + 1, 1.0)
            fm = feats[bi]  # (C, H, W)
            ys = jnp.arange(H, dtype=jnp.float32)
            xs = jnp.arange(W, dtype=jnp.float32)

            def cell(ph, pw):
                hs = jnp.floor(y1 + ph * rh / self.pooled_h)
                he = jnp.ceil(y1 + (ph + 1) * rh / self.pooled_h)
                ws = jnp.floor(x1 + pw * rw / self.pooled_w)
                we = jnp.ceil(x1 + (pw + 1) * rw / self.pooled_w)
                mask = ((ys >= hs) & (ys < jnp.maximum(he, hs + 1)))[:, None] & \
                       ((xs >= ws) & (xs < jnp.maximum(we, ws + 1)))[None, :]
                masked = jnp.where(mask[None], fm, -jnp.inf)
                m = jnp.max(masked, axis=(1, 2))
                return jnp.where(jnp.isfinite(m), m, 0.0)

            grid = jnp.stack([jnp.stack([cell(ph, pw)
                                         for pw in range(self.pooled_w)], -1)
                              for ph in range(self.pooled_h)], -2)
            return grid  # (C, pooled_h, pooled_w)

        return jax.vmap(pool_one)(rois.astype(jnp.float32))
