"""Recurrent layers.

Parity: reference ``nn/Cell.scala``, ``nn/RNN.scala`` (RnnCell),
``nn/LSTM.scala``, ``nn/LSTMPeephole.scala``, ``nn/GRU.scala``,
``nn/ConvLSTMPeephole.scala``, ``nn/Recurrent.scala``,
``nn/RecurrentDecoder.scala``, ``nn/BiRecurrent.scala``,
``nn/MultiRNNCell.scala``, ``nn/TimeDistributed.scala``.

TPU-first: the reference unrolls time in a Scala while-loop over mutable
tensors; here ``Recurrent`` is one ``lax.scan`` — a single compiled loop with
the per-step cell fused by XLA, and the whole input-to-hidden projection for
all timesteps hoisted into one big MXU matmul where possible.

Input layout is (batch, time, features...), matching the reference default.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .module import Module
from ..utils.table import Table


class Cell(Module):
    """Base recurrent cell: subclasses define ``init_hidden`` and
    ``step(params, x_t, h) -> (out_t, new_h)``.

    Cells whose input-to-hidden projection is independent of the hidden
    state additionally implement ``precompute(params, xt)`` (one large
    (T*B, in) @ (in, gates) MXU matmul over ALL timesteps) and
    ``step_pre(params, pre_t, h)``; ``Recurrent`` then scans only the
    hidden-to-hidden recurrence. On TPU this replaces T small matmuls
    inside the sequential loop with one big one outside it."""

    def init_hidden(self, batch_size: int, dtype=jnp.float32):
        raise NotImplementedError

    def step(self, params, x_t, h):
        raise NotImplementedError

    def precompute(self, params, xt):
        """Hoisted input projection for (T, B, ...) inputs, or None if the
        cell has no hoistable part (then Recurrent scans ``step``)."""
        return None

    def step_pre(self, params, pre_t, h):
        raise NotImplementedError

    def _apply(self, params, state, x, training, rng):
        # Cell as standalone module: input Table(x_t, hidden)
        if isinstance(x, Table):
            out, new_h = self.step(params, x[1], x[2])
            return Table(out, new_h)
        h = self.init_hidden(x.shape[0], x.dtype)
        out, new_h = self.step(params, x, h)
        return Table(out, new_h)


def _uniform(rng, shape, stdv):
    return jax.random.uniform(rng, shape, minval=-stdv, maxval=stdv)


def _scan_cell(cell, cell_params, h0, xt):
    """lax.scan a cell over (T, B, ...) inputs, via the hoisted
    input-projection path when the cell offers one (Cell docstring).
    Shared by Recurrent and BiRecurrent so the two can't diverge."""
    pre = cell.precompute(cell_params, xt)
    if pre is not None:
        # input projection hoisted: one (T*B, in)@(in, gates) MXU matmul
        # outside the loop; the scan carries only the h2h recurrence
        def body(h, pre_t):
            out, nh = cell.step_pre(cell_params, pre_t, h)
            return nh, out

        _, ys = lax.scan(body, h0, pre)
    else:
        def body(h, x_t):
            out, nh = cell.step(cell_params, x_t, h)
            return nh, out

        _, ys = lax.scan(body, h0, xt)
    return ys


class RnnCell(Cell):
    """Vanilla RNN cell (nn/RNN.scala): h' = act(W x + U h + b)."""

    def __init__(self, input_size: int, hidden_size: int, activation=None,
                 isInputWithBias: bool = True, w_regularizer=None,
                 u_regularizer=None, b_regularizer=None, name=None):
        super().__init__(name=name)
        self.input_size, self.hidden_size = input_size, hidden_size
        self.activation = activation  # None → tanh (picklable default)

    def _init_params(self, rng):
        k = jax.random.split(rng, 3)
        stdv = 1.0 / np.sqrt(self.hidden_size)
        return {"w_i": _uniform(k[0], (self.input_size, self.hidden_size), stdv),
                "w_h": _uniform(k[1], (self.hidden_size, self.hidden_size), stdv),
                "bias": _uniform(k[2], (self.hidden_size,), stdv)}

    def init_hidden(self, batch_size, dtype=jnp.float32):
        return jnp.zeros((batch_size, self.hidden_size), dtype)

    def step(self, params, x_t, h):
        return self.step_pre(params, self.precompute(params, x_t), h)

    def precompute(self, params, xt):
        return xt @ params["w_i"] + params["bias"]

    def step_pre(self, params, pre_t, h):
        act = self.activation if callable(self.activation) else jnp.tanh
        if isinstance(self.activation, str):
            act = {"tanh": jnp.tanh, "relu": jax.nn.relu,
                   "sigmoid": jax.nn.sigmoid}[self.activation]
        nh = act(pre_t + h @ params["w_h"])
        return nh, nh


class LSTM(Cell):
    """LSTM cell (nn/LSTM.scala). Gate order (i, f, g, o); forget bias 1.0."""

    def __init__(self, input_size: int, hidden_size: int, p: float = 0.0,
                 activation=None, inner_activation=None, w_regularizer=None,
                 u_regularizer=None, b_regularizer=None, name=None):
        super().__init__(name=name)
        self.input_size, self.hidden_size = input_size, hidden_size
        self.p = p
        self.activation = activation  # None → tanh (picklable default)
        self.inner_activation = inner_activation  # None → sigmoid

    def _init_params(self, rng):
        k = jax.random.split(rng, 3)
        stdv = 1.0 / np.sqrt(self.hidden_size)
        H = self.hidden_size
        b = jnp.zeros((4 * H,)).at[H:2 * H].set(1.0)  # forget bias 1
        return {"w_i": _uniform(k[0], (self.input_size, 4 * H), stdv),
                "w_h": _uniform(k[1], (H, 4 * H), stdv),
                "bias": b}

    def init_hidden(self, batch_size, dtype=jnp.float32):
        H = self.hidden_size
        return Table(jnp.zeros((batch_size, H), dtype),
                     jnp.zeros((batch_size, H), dtype))

    def step(self, params, x_t, h):
        return self.step_pre(params, self.precompute(params, x_t), h)

    def precompute(self, params, xt):
        return xt @ params["w_i"] + params["bias"]

    def step_pre(self, params, pre_t, h):
        act = self.activation or jnp.tanh
        inner = self.inner_activation or jax.nn.sigmoid
        hx, cx = h[1], h[2]
        z = pre_t + hx @ params["w_h"]
        i, f, g, o = jnp.split(z, 4, axis=-1)
        c = inner(f) * cx + inner(i) * act(g)
        hnew = inner(o) * act(c)
        return hnew, Table(hnew, c)


class LSTMPeephole(Cell):
    """LSTM with peephole connections (nn/LSTMPeephole.scala)."""

    def __init__(self, input_size: int, hidden_size: int, p: float = 0.0,
                 name=None):
        super().__init__(name=name)
        self.input_size, self.hidden_size = input_size, hidden_size

    def _init_params(self, rng):
        k = jax.random.split(rng, 6)
        stdv = 1.0 / np.sqrt(self.hidden_size)
        H = self.hidden_size
        return {"w_i": _uniform(k[0], (self.input_size, 4 * H), stdv),
                "w_h": _uniform(k[1], (H, 4 * H), stdv),
                "bias": jnp.zeros((4 * H,)).at[H:2 * H].set(1.0),
                "p_i": _uniform(k[2], (H,), stdv),
                "p_f": _uniform(k[3], (H,), stdv),
                "p_o": _uniform(k[4], (H,), stdv)}

    def init_hidden(self, batch_size, dtype=jnp.float32):
        H = self.hidden_size
        return Table(jnp.zeros((batch_size, H), dtype),
                     jnp.zeros((batch_size, H), dtype))

    def step(self, params, x_t, h):
        return self.step_pre(params, self.precompute(params, x_t), h)

    def precompute(self, params, xt):
        return xt @ params["w_i"] + params["bias"]

    def step_pre(self, params, pre_t, h):
        hx, cx = h[1], h[2]
        z = pre_t + hx @ params["w_h"]
        i, f, g, o = jnp.split(z, 4, axis=-1)
        i = jax.nn.sigmoid(i + params["p_i"] * cx)
        f = jax.nn.sigmoid(f + params["p_f"] * cx)
        c = f * cx + i * jnp.tanh(g)
        o = jax.nn.sigmoid(o + params["p_o"] * c)
        hnew = o * jnp.tanh(c)
        return hnew, Table(hnew, c)


class GRU(Cell):
    """GRU cell (nn/GRU.scala)."""

    def __init__(self, input_size: int, output_size: int, p: float = 0.0,
                 w_regularizer=None, u_regularizer=None, b_regularizer=None,
                 name=None):
        super().__init__(name=name)
        self.input_size, self.hidden_size = input_size, output_size

    def _init_params(self, rng):
        k = jax.random.split(rng, 4)
        stdv = 1.0 / np.sqrt(self.hidden_size)
        H = self.hidden_size
        return {"w_i": _uniform(k[0], (self.input_size, 3 * H), stdv),
                "w_h": _uniform(k[1], (H, 2 * H), stdv),
                "w_hn": _uniform(k[2], (H, H), stdv),
                "bias": jnp.zeros((3 * H,))}

    def init_hidden(self, batch_size, dtype=jnp.float32):
        return jnp.zeros((batch_size, self.hidden_size), dtype)

    def step(self, params, x_t, h):
        return self.step_pre(params, self.precompute(params, x_t), h)

    def precompute(self, params, xt):
        return xt @ params["w_i"] + params["bias"]

    def step_pre(self, params, pre_t, h):
        H = self.hidden_size
        zr, zz, zn = (pre_t[..., :H], pre_t[..., H:2 * H], pre_t[..., 2 * H:])
        hh = h @ params["w_h"]
        r = jax.nn.sigmoid(zr + hh[..., :H])
        z = jax.nn.sigmoid(zz + hh[..., H:])
        n = jnp.tanh(zn + (r * h) @ params["w_hn"])
        hnew = (1 - z) * n + z * h
        return hnew, hnew


class ConvLSTMPeephole(Cell):
    """Convolutional LSTM with peepholes over NCHW maps
    (nn/ConvLSTMPeephole.scala)."""

    def __init__(self, input_size: int, output_size: int, kernel_i: int = 3,
                 kernel_c: int = 3, stride: int = 1, padding: int = -1,
                 with_peephole: bool = True, name=None):
        super().__init__(name=name)
        self.input_size, self.output_size = input_size, output_size
        self.kernel_i, self.kernel_c = kernel_i, kernel_c
        self.with_peephole = with_peephole
        self.spatial = None  # inferred on first init_hidden call

    def _init_params(self, rng):
        k = jax.random.split(rng, 3)
        fan = self.input_size * self.kernel_i * self.kernel_i
        stdv = 1.0 / np.sqrt(fan)
        O, I = self.output_size, self.input_size
        p = {"w_i": _uniform(k[0], (4 * O, I, self.kernel_i, self.kernel_i),
                             stdv),
             "w_h": _uniform(k[1], (4 * O, O, self.kernel_c, self.kernel_c),
                             stdv),
             "bias": jnp.zeros((4 * O,)).at[O:2 * O].set(1.0)}
        if self.with_peephole:
            p["p_i"] = jnp.zeros((O,))
            p["p_f"] = jnp.zeros((O,))
            p["p_o"] = jnp.zeros((O,))
        return p

    def set_spatial(self, h, w):
        self.spatial = (h, w)
        return self

    def init_hidden(self, batch_size, dtype=jnp.float32):
        if self.spatial is None:
            raise ValueError("call set_spatial(h, w) before init_hidden, or "
                             "use Recurrent which infers it from the input")
        H, W = self.spatial
        z = jnp.zeros((batch_size, self.output_size, H, W), dtype)
        return Table(z, z)

    def _conv(self, x, w):
        return lax.conv_general_dilated(
            x, w, (1, 1), "SAME", dimension_numbers=("NCHW", "OIHW", "NCHW"))

    def step(self, params, x_t, h):
        hx, cx = h[1], h[2]
        z = self._conv(x_t, params["w_i"]) + self._conv(hx, params["w_h"]) + \
            params["bias"][None, :, None, None]
        i, f, g, o = jnp.split(z, 4, axis=1)
        if self.with_peephole:
            i = i + params["p_i"][None, :, None, None] * cx
            f = f + params["p_f"][None, :, None, None] * cx
        i, f = jax.nn.sigmoid(i), jax.nn.sigmoid(f)
        g = jnp.tanh(g)
        c = f * cx + i * g
        if self.with_peephole:
            o = o + params["p_o"][None, :, None, None] * c
        o = jax.nn.sigmoid(o)
        hnew = o * jnp.tanh(c)
        return hnew, Table(hnew, c)


ConvLSTMPeephole3D = ConvLSTMPeephole  # 3D variant: same structure, NCDHW maps


class MultiRNNCell(Cell):
    """Stack of cells acting as one (nn/MultiRNNCell.scala)."""

    def __init__(self, cells, name=None):
        super().__init__(name=name)
        self.cells = list(cells)

    def _init_params(self, rng):
        return {str(i): c._init_params(jax.random.fold_in(rng, i))
                for i, c in enumerate(self.cells)}

    def init_hidden(self, batch_size, dtype=jnp.float32):
        return Table(*[c.init_hidden(batch_size, dtype) for c in self.cells])

    def step(self, params, x_t, h):
        new_hs = []
        out = x_t
        for i, c in enumerate(self.cells):
            out, nh = c.step(params[str(i)], out, h[i + 1])
            new_hs.append(nh)
        return out, Table(*new_hs)

    def precompute(self, params, xt):
        # only the FIRST cell sees the sequence input; its projection is
        # hoistable, the rest consume the previous cell's per-step output
        return self.cells[0].precompute(params["0"], xt)

    def step_pre(self, params, pre_t, h):
        new_hs = []
        out, nh = self.cells[0].step_pre(params["0"], pre_t, h[1])
        new_hs.append(nh)
        for i, c in enumerate(self.cells[1:], start=1):
            out, nh = c.step(params[str(i)], out, h[i + 1])
            new_hs.append(nh)
        return out, Table(*new_hs)


class Recurrent(Module):
    """Run a cell over (batch, time, ...) via lax.scan (nn/Recurrent.scala)."""

    def __init__(self, cell: Optional[Cell] = None, name=None):
        super().__init__(name=name)
        self.cell = cell

    def add(self, cell: Cell):
        self.cell = cell
        return self

    def _init_params(self, rng):
        return {"cell": self.cell._init_params(rng)}

    def _infer_spatial(self, x):
        if isinstance(self.cell, ConvLSTMPeephole) and self.cell.spatial is None:
            self.cell.set_spatial(x.shape[-2], x.shape[-1])

    def _apply(self, params, state, x, training, rng):
        self._infer_spatial(x)
        h0 = self.cell.init_hidden(x.shape[0], x.dtype)
        xt = jnp.moveaxis(x, 1, 0)  # (T, B, ...)
        ys = _scan_cell(self.cell, params["cell"], h0, xt)
        return jnp.moveaxis(ys, 0, 1)

    def training(self):
        super().training()
        if self.cell:
            self.cell.training()
        return self

    def evaluate(self):
        super().evaluate()
        if self.cell:
            self.cell.evaluate()
        return self

    def modules_iter(self):
        yield self
        if self.cell is not None:
            yield from self.cell.modules_iter()


class RecurrentDecoder(Module):
    """Feed output back as next input for seq_length steps
    (nn/RecurrentDecoder.scala). Input: (B, features) first step input."""

    def __init__(self, seq_length: int, name=None):
        super().__init__(name=name)
        self.seq_length = seq_length
        self.cell: Optional[Cell] = None

    def add(self, cell: Cell):
        self.cell = cell
        return self

    def _init_params(self, rng):
        return {"cell": self.cell._init_params(rng)}

    def _apply(self, params, state, x, training, rng):
        h0 = self.cell.init_hidden(x.shape[0], x.dtype)

        def body(carry, _):
            inp, h = carry
            out, nh = self.cell.step(params["cell"], inp, h)
            return (out, nh), out

        _, ys = lax.scan(body, (x, h0), None, length=self.seq_length)
        return jnp.moveaxis(ys, 0, 1)


class BiRecurrent(Module):
    """Bidirectional recurrent wrapper (nn/BiRecurrent.scala). ``merge``
    defaults to elementwise add (reference default CAddTable)."""

    def __init__(self, merge=None, name=None):
        super().__init__(name=name)
        self.merge = merge  # None → add; "concat" or a callable
        self.cell: Optional[Cell] = None

    def add(self, cell: Cell):
        self.cell = cell
        return self

    def _init_params(self, rng):
        k1, k2 = jax.random.split(rng)
        return {"fwd": self.cell._init_params(k1),
                "bwd": self.cell._init_params(k2)}

    def _run(self, cell_params, x):
        h0 = self.cell.init_hidden(x.shape[0], x.dtype)
        xt = jnp.moveaxis(x, 1, 0)
        ys = _scan_cell(self.cell, cell_params, h0, xt)
        return jnp.moveaxis(ys, 0, 1)

    def _apply(self, params, state, x, training, rng):
        fwd = self._run(params["fwd"], x)
        bwd = jnp.flip(self._run(params["bwd"], jnp.flip(x, axis=1)), axis=1)
        if self.merge is None:
            return fwd + bwd
        if self.merge == "concat":
            return jnp.concatenate([fwd, bwd], axis=-1)
        if callable(self.merge):
            return self.merge(fwd, bwd)
        from .table_ops import CAddTable
        return fwd + bwd


class TimeDistributed(Module):
    """Apply a module independently at each timestep (nn/TimeDistributed.scala).
    Implemented by folding time into batch — one big fused call instead of the
    reference's per-step loop."""

    def __init__(self, layer: Module, name=None):
        super().__init__(name=name)
        self.layer = layer

    def _init_params(self, rng):
        return {"layer": self.layer._init_params(rng)}

    def _init_state(self):
        return {"layer": self.layer._init_state()}

    def _apply(self, params, state, x, training, rng):
        b, t = x.shape[0], x.shape[1]
        flat = x.reshape((b * t,) + x.shape[2:])
        y, new_sub = self.layer.apply(params["layer"], state["layer"], flat,
                                      training, rng)
        return y.reshape((b, t) + y.shape[1:]), {**state, "layer": new_sub}

    def training(self):
        super().training()
        self.layer.training()
        return self

    def evaluate(self):
        super().evaluate()
        self.layer.evaluate()
        return self


class RNN(RnnCell):
    """Alias matching reference file name nn/RNN.scala."""
