"""Shape / slicing / resampling layers.

Parity: reference ``nn/Reshape.scala``, ``nn/View.scala``,
``nn/InferReshape.scala``, ``nn/Squeeze.scala``, ``nn/Unsqueeze.scala``,
``nn/Transpose.scala``, ``nn/Replicate.scala``, ``nn/Padding.scala``,
``nn/SpatialZeroPadding.scala``, ``nn/Narrow.scala``, ``nn/Select.scala``,
``nn/Index.scala``, ``nn/MaskedSelect.scala``, ``nn/Max.scala``,
``nn/Min.scala``, ``nn/Mean.scala``, ``nn/Sum.scala``, ``nn/Tile.scala``,
``nn/ExpandSize.scala``, ``nn/Cropping2D.scala``, ``nn/Cropping3D.scala``,
``nn/Reverse.scala``, ``nn/Pack.scala``, ``nn/UpSampling1D/2D/3D.scala``,
``nn/ResizeBilinear.scala`` (DenseToSparse moved to nn/sparse.py).

Dimension arguments are 1-based (torch convention, matching the reference).
Layers taking ``n_input_dims`` shift the dim by one automatically when a batch
dimension is present.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .module import Module
from ..utils.table import Table


def _dim0(dim: int, x, n_input_dims: int = -1) -> int:
    """1-based (maybe negative) dim → 0-based absolute axis."""
    nd = x.ndim
    if dim < 0:
        return nd + dim
    d = dim - 1
    if 0 < n_input_dims < nd:
        d += nd - n_input_dims  # batch dims present
    return d


class Reshape(Module):
    """Reshape non-batch dims (nn/Reshape.scala). ``batch_mode=None`` infers:
    if the element count of the full input matches prod(size), no batch dim."""

    def __init__(self, size: Sequence[int], batch_mode: Optional[bool] = None,
                 name=None):
        super().__init__(name=name)
        self.size = tuple(size)
        self.batch_mode = batch_mode

    def _apply(self, params, state, x, training, rng):
        n = int(np.prod(self.size))
        if self.batch_mode is False:
            return x.reshape(self.size)
        rest = int(np.prod(x.shape[1:])) if x.ndim > 1 else -1
        if self.batch_mode is True or rest == n:
            return x.reshape((x.shape[0],) + self.size)
        return x.reshape(self.size)


class View(Module):
    """nn/View.scala — reshape keeping batch when num_elements matches."""

    def __init__(self, *sizes, name=None):
        super().__init__(name=name)
        if len(sizes) == 1 and isinstance(sizes[0], (list, tuple)):
            sizes = tuple(sizes[0])
        self.sizes = tuple(sizes)

    def _apply(self, params, state, x, training, rng):
        if -1 in self.sizes:
            return x.reshape(self.sizes)
        n = int(np.prod(self.sizes))
        rest = int(np.prod(x.shape[1:])) if x.ndim > 1 else -1
        if rest == n:
            return x.reshape((x.shape[0],) + self.sizes)
        if x.size == n:
            return x.reshape(self.sizes)
        return x.reshape((-1,) + self.sizes)


class InferReshape(Module):
    """nn/InferReshape.scala — size entries: -1 infer, 0 keep input dim."""

    def __init__(self, size: Sequence[int], batch_mode: bool = False, name=None):
        super().__init__(name=name)
        self.size = tuple(size)
        self.batch_mode = batch_mode

    def _apply(self, params, state, x, training, rng):
        in_shape = x.shape[1:] if self.batch_mode else x.shape
        out = []
        for i, s in enumerate(self.size):
            if s == 0:
                out.append(in_shape[i])
            else:
                out.append(s)
        if self.batch_mode:
            return x.reshape((x.shape[0],) + tuple(out))
        return x.reshape(tuple(out))


class Squeeze(Module):
    """nn/Squeeze.scala."""

    def __init__(self, dim: Optional[int] = None, num_input_dims: int = -1,
                 name=None):
        super().__init__(name=name)
        self.dim, self.num_input_dims = dim, num_input_dims

    def _apply(self, params, state, x, training, rng):
        if self.dim is None:
            return jnp.squeeze(x)
        return jnp.squeeze(x, axis=_dim0(self.dim, x, self.num_input_dims))


class Unsqueeze(Module):
    """nn/Unsqueeze.scala — insert singleton at 1-based pos."""

    def __init__(self, pos: int, num_input_dims: int = -1, name=None):
        super().__init__(name=name)
        self.pos, self.num_input_dims = pos, num_input_dims

    def _apply(self, params, state, x, training, rng):
        d = self.pos - 1
        if 0 < self.num_input_dims < x.ndim:
            d += x.ndim - self.num_input_dims
        return jnp.expand_dims(x, d)


class Transpose(Module):
    """nn/Transpose.scala — sequence of 1-based (dim1, dim2) swaps."""

    def __init__(self, permutations, name=None):
        super().__init__(name=name)
        self.permutations = [tuple(p) for p in permutations]

    def _apply(self, params, state, x, training, rng):
        for d1, d2 in self.permutations:
            x = jnp.swapaxes(x, d1 - 1, d2 - 1)
        return x


class Replicate(Module):
    """nn/Replicate.scala — insert new dim of size n_features at ``dim``."""

    def __init__(self, n_features: int, dim: int = 1, n_dim: int = -1,
                 name=None):
        super().__init__(name=name)
        self.n_features, self.dim, self.n_dim = n_features, dim, n_dim

    def _apply(self, params, state, x, training, rng):
        d = self.dim - 1
        if 0 < self.n_dim < x.ndim:
            d += x.ndim - self.n_dim
        y = jnp.expand_dims(x, d)
        reps = [1] * y.ndim
        reps[d] = self.n_features
        return jnp.tile(y, reps)


class Padding(Module):
    """nn/Padding.scala — pad ``pad`` entries (sign = side) on dim with value."""

    def __init__(self, dim: int, pad: int, n_input_dim: int,
                 value: float = 0.0, n_index: int = 1, name=None):
        super().__init__(name=name)
        self.dim, self.pad, self.n_input_dim = dim, pad, n_input_dim
        self.value = value

    def _apply(self, params, state, x, training, rng):
        d = _dim0(self.dim, x, self.n_input_dim)
        cfg = [(0, 0)] * x.ndim
        cfg[d] = (abs(self.pad), 0) if self.pad < 0 else (0, self.pad)
        return jnp.pad(x, cfg, constant_values=self.value)


class SpatialZeroPadding(Module):
    """nn/SpatialZeroPadding.scala (NCHW; negative pad crops)."""

    def __init__(self, pad_left: int, pad_right: int = None, pad_top: int = None,
                 pad_bottom: int = None, name=None):
        super().__init__(name=name)
        if pad_right is None:
            pad_right = pad_top = pad_bottom = pad_left
        self.l, self.r, self.t, self.b = pad_left, pad_right, pad_top, pad_bottom

    def _apply(self, params, state, x, training, rng):
        def padcrop(arr, axis, lo, hi):
            if lo < 0:
                arr = jax.lax.slice_in_dim(arr, -lo, arr.shape[axis], axis=axis)
                lo = 0
            if hi < 0:
                arr = jax.lax.slice_in_dim(arr, 0, arr.shape[axis] + hi,
                                           axis=axis)
                hi = 0
            if lo or hi:
                cfg = [(0, 0)] * arr.ndim
                cfg[axis] = (lo, hi)
                arr = jnp.pad(arr, cfg)
            return arr
        x = padcrop(x, x.ndim - 2, self.t, self.b)
        x = padcrop(x, x.ndim - 1, self.l, self.r)
        return x


class Narrow(Module):
    """nn/Narrow.scala — slice [offset, offset+length) on dim (1-based offset;
    negative length means 'to end + length + 1')."""

    def __init__(self, dimension: int, offset: int, length: int = 1, name=None):
        super().__init__(name=name)
        self.dimension, self.offset, self.length = dimension, offset, length

    def _apply(self, params, state, x, training, rng):
        d = _dim0(self.dimension, x)
        start = self.offset - 1 if self.offset > 0 else x.shape[d] + self.offset
        length = self.length
        if length < 0:
            length = x.shape[d] - start + length + 1
        return jax.lax.slice_in_dim(x, start, start + length, axis=d)


class Select(Module):
    """nn/Select.scala — pick index on dim and squeeze it (1-based; negative
    index counts from the end)."""

    def __init__(self, dim: int, index: int, name=None):
        super().__init__(name=name)
        self.dim, self.index = dim, index

    def _apply(self, params, state, x, training, rng):
        d = _dim0(self.dim, x)
        i = self.index - 1 if self.index > 0 else x.shape[d] + self.index
        return jnp.take(x, i, axis=d)


class Index(Module):
    """nn/Index.scala — Table(src, indices): gather rows on dim (1-based ids)."""

    def __init__(self, dimension: int, name=None):
        super().__init__(name=name)
        self.dimension = dimension

    def _apply(self, params, state, x, training, rng):
        src, idx = x[1], x[2]
        return jnp.take(src, idx.astype(jnp.int32) - 1,
                        axis=self.dimension - 1)


class MaskedSelect(Module):
    """nn/MaskedSelect.scala — Table(src, mask) → 1-D selected values.
    Dynamic output shape: eager-only (cannot run under jit; XLA requires
    static shapes — use multiplication by mask inside compiled code instead)."""

    def _apply(self, params, state, x, training, rng):
        src, mask = x[1], x[2]
        return src[mask.astype(bool)]


class _Reduce(Module):
    def __init__(self, dimension: int = 1, n_input_dims: int = -1,
                 squeeze: bool = True, name=None):
        super().__init__(name=name)
        self.dimension, self.n_input_dims = dimension, n_input_dims
        self.squeeze = squeeze

    def _reduce(self, x, axis):
        raise NotImplementedError

    def _apply(self, params, state, x, training, rng):
        d = _dim0(self.dimension, x, self.n_input_dims)
        return self._reduce(x, d) if self.squeeze else \
            jnp.expand_dims(self._reduce(x, d), d)


class Max(_Reduce):
    """nn/Max.scala (values only, parity with forward output)."""

    def __init__(self, dim: int = 1, num_input_dims: int = -1, name=None):
        super().__init__(dim, num_input_dims, True, name=name)

    def _reduce(self, x, axis):
        return jnp.max(x, axis=axis)


class Min(_Reduce):
    def __init__(self, dim: int = 1, num_input_dims: int = -1, name=None):
        super().__init__(dim, num_input_dims, True, name=name)

    def _reduce(self, x, axis):
        return jnp.min(x, axis=axis)


class Mean(_Reduce):
    def __init__(self, dimension: int = 1, n_input_dims: int = -1,
                 squeeze: bool = True, name=None):
        super().__init__(dimension, n_input_dims, squeeze, name=name)

    def _reduce(self, x, axis):
        return jnp.mean(x, axis=axis)


class Sum(_Reduce):
    def __init__(self, dimension: int = 1, n_input_dims: int = -1,
                 size_average: bool = False, squeeze: bool = True, name=None):
        super().__init__(dimension, n_input_dims, squeeze, name=name)
        self.size_average = size_average

    def _reduce(self, x, axis):
        return jnp.mean(x, axis=axis) if self.size_average else \
            jnp.sum(x, axis=axis)


class Tile(Module):
    """nn/Tile.scala — repeat ``copies`` times along dim."""

    def __init__(self, dim: int = 1, copies: int = 2, name=None):
        super().__init__(name=name)
        self.dim, self.copies = dim, copies

    def _apply(self, params, state, x, training, rng):
        reps = [1] * x.ndim
        reps[_dim0(self.dim, x)] = self.copies
        return jnp.tile(x, reps)


class ExpandSize(Module):
    """nn/ExpandSize.scala — broadcast singleton dims to target sizes
    (-1 keeps)."""

    def __init__(self, sizes: Sequence[int], name=None):
        super().__init__(name=name)
        self.sizes = tuple(sizes)

    def _apply(self, params, state, x, training, rng):
        target = tuple(x.shape[i] if s == -1 else s
                       for i, s in enumerate(self.sizes))
        return jnp.broadcast_to(x, target)


class Cropping2D(Module):
    """nn/Cropping2D.scala (NCHW)."""

    def __init__(self, height_crop=(0, 0), width_crop=(0, 0),
                 data_format="NCHW", name=None):
        super().__init__(name=name)
        self.hc, self.wc = tuple(height_crop), tuple(width_crop)
        self.data_format = data_format

    def _apply(self, params, state, x, training, rng):
        h_ax = x.ndim - 2 if self.data_format == "NCHW" else x.ndim - 3
        w_ax = x.ndim - 1 if self.data_format == "NCHW" else x.ndim - 2
        x = jax.lax.slice_in_dim(x, self.hc[0], x.shape[h_ax] - self.hc[1],
                                 axis=h_ax)
        x = jax.lax.slice_in_dim(x, self.wc[0], x.shape[w_ax] - self.wc[1],
                                 axis=w_ax)
        return x


class Cropping3D(Module):
    """nn/Cropping3D.scala (NCDHW)."""

    def __init__(self, dim1_crop=(0, 0), dim2_crop=(0, 0), dim3_crop=(0, 0),
                 name=None):
        super().__init__(name=name)
        self.crops = [tuple(dim1_crop), tuple(dim2_crop), tuple(dim3_crop)]

    def _apply(self, params, state, x, training, rng):
        for i, (lo, hi) in enumerate(self.crops):
            ax = x.ndim - 3 + i
            x = jax.lax.slice_in_dim(x, lo, x.shape[ax] - hi, axis=ax)
        return x


class Reverse(Module):
    """nn/Reverse.scala — flip along dim."""

    def __init__(self, dimension: int = 1, is_inplace: bool = False, name=None):
        super().__init__(name=name)
        self.dimension = dimension

    def _apply(self, params, state, x, training, rng):
        return jnp.flip(x, axis=self.dimension - 1)


class Pack(Module):
    """nn/Pack.scala — stack a Table of tensors along a new 1-based dim."""

    def __init__(self, dimension: int, name=None):
        super().__init__(name=name)
        self.dimension = dimension

    def _apply(self, params, state, x, training, rng):
        items = x.to_list() if isinstance(x, Table) else [x]
        return jnp.stack(items, axis=self.dimension - 1)


class UpSampling1D(Module):
    """nn/UpSampling1D.scala — repeat timesteps (B, T, C) → (B, T*len, C)."""

    def __init__(self, length: int, name=None):
        super().__init__(name=name)
        self.length = length

    def _apply(self, params, state, x, training, rng):
        return jnp.repeat(x, self.length, axis=-2)


class UpSampling2D(Module):
    """nn/UpSampling2D.scala — nearest-neighbor (NCHW)."""

    def __init__(self, size=(2, 2), data_format="NCHW", name=None):
        super().__init__(name=name)
        self.size = tuple(size)

    def _apply(self, params, state, x, training, rng):
        x = jnp.repeat(x, self.size[0], axis=-2)
        return jnp.repeat(x, self.size[1], axis=-1)


class UpSampling3D(Module):
    """nn/UpSampling3D.scala (NCDHW)."""

    def __init__(self, size=(2, 2, 2), name=None):
        super().__init__(name=name)
        self.size = tuple(size)

    def _apply(self, params, state, x, training, rng):
        x = jnp.repeat(x, self.size[0], axis=-3)
        x = jnp.repeat(x, self.size[1], axis=-2)
        return jnp.repeat(x, self.size[2], axis=-1)


class ResizeBilinear(Module):
    """nn/ResizeBilinear.scala — bilinear resize of NCHW to (H', W')."""

    def __init__(self, output_height: int, output_width: int,
                 align_corners: bool = False, data_format="NCHW", name=None):
        super().__init__(name=name)
        self.oh, self.ow = output_height, output_width
        self.align_corners = align_corners

    def _apply(self, params, state, x, training, rng):
        method = "bilinear"
        target = x.shape[:-2] + (self.oh, self.ow)
        if self.align_corners:
            # jax.image.resize has no align_corners; emulate via scale/translate
            h, w = x.shape[-2], x.shape[-1]
            scale = ((h - 1) / max(self.oh - 1, 1), (w - 1) / max(self.ow - 1, 1))
            ys = jnp.arange(self.oh) * scale[0]
            xs = jnp.arange(self.ow) * scale[1]
            y0 = jnp.clip(jnp.floor(ys).astype(jnp.int32), 0, h - 1)
            y1 = jnp.clip(y0 + 1, 0, h - 1)
            x0 = jnp.clip(jnp.floor(xs).astype(jnp.int32), 0, w - 1)
            x1 = jnp.clip(x0 + 1, 0, w - 1)
            wy = (ys - y0)[..., :, None]
            wx = (xs - x0)[..., None, :]
            g = lambda yy, xx: x[..., yy, :][..., :, xx]
            top = g(y0, x0) * (1 - wx) + g(y0, x1) * wx
            bot = g(y1, x0) * (1 - wx) + g(y1, x1) * wx
            return top * (1 - wy) + bot * wy
        return jax.image.resize(x, target, method)


