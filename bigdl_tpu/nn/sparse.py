"""Real sparse compute: COO tensors + segment_sum kernels.

Parity: reference ``tensor/SparseTensor.scala``, ``nn/SparseLinear.scala``,
``nn/LookupTableSparse.scala``, ``nn/SparseJoinTable.scala``.

TPU-first design: a :class:`SparseTensor` is a *static-shape* COO triple
(indices ``(nnz, ndim)`` int32, values ``(nnz,)``, dense shape) registered as
a JAX pytree, so it traces through ``jit``/``vjp``/``shard_map`` like any
array. The nnz buffer size is fixed at construction — pad entries carry value
0 at index 0, which contributes nothing to the linear ops here, so no dynamic
shapes ever reach XLA. Compute lowers to ``gather`` + ``segment_sum``, the
TPU-efficient formulation of sparse×dense (one embedding-row gather feeding a
scatter-add; the MXU is not involved, which is the point — these ops exist
for wide/recommendation workloads whose feature spaces are far too wide to
densify)."""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .module import Module
from .linear import Linear, LookupTable
from ..utils.table import Table
# the Parallax-style (indices, values) gradient exchange lives with the
# other collectives; re-exported here because it is the sparse-compute
# side of the same story (DistriOptimizer's per-layer path selection
# feeds it from embedding layers — docs/DISTRIBUTED.md)
from ..parallel.allreduce import sparse_embedding_grad_allreduce  # noqa: F401,E501


def embedding_grad_rows(dense_grad, ids):
    """Extract the ``(B, H)`` per-id gradient rows a shard's LOCAL dense
    embedding gradient carries, ready for the Parallax ``(indices,
    values)`` exchange (:func:`sparse_embedding_grad_allreduce`).

    ``dense_grad`` is the ``(vocab, H)`` gradient autodiff produced on
    THIS shard — nonzero only at the rows ``ids`` touched, and row
    ``ids[i]`` already SUMS every local contribution for that id. A
    duplicated id must therefore ship exactly once: occurrences after
    the first are masked to zero via a scatter-min first-occurrence
    index (O(B + vocab) — a pairwise id compare would materialize a
    (B, B) intermediate, ~1 GB at a 32k-token shard batch)."""
    ids = ids.astype(jnp.int32)
    b = ids.shape[0]
    iota = jnp.arange(b, dtype=jnp.int32)
    first = jnp.full((dense_grad.shape[0],), b,
                     jnp.int32).at[ids].min(iota)
    keep = first[ids] == iota
    rows = jnp.take(dense_grad, ids, axis=0)
    return rows * keep[:, None].astype(rows.dtype)


class SparseTensor:
    """Static-shape COO sparse tensor (pytree: indices, values leaves)."""

    def __init__(self, indices, values, shape: Sequence[int]):
        self.indices = indices  # (nnz, ndim) int32
        self.values = values    # (nnz,)
        self.shape = tuple(int(s) for s in shape)

    @property
    def nnz(self) -> int:
        return int(self.values.shape[0])

    @property
    def ndim(self) -> int:
        return len(self.shape)

    @staticmethod
    def from_dense(arr, nnz: Optional[int] = None) -> "SparseTensor":
        """Host-side densification cut — pads the buffer to ``nnz``.

        Raises if the actual nonzero count exceeds the budget (silent
        truncation would drop data); size ``nnz`` for the worst-case batch.
        """
        a = np.asarray(arr)
        idx = np.argwhere(a != 0)
        vals = a[tuple(idx.T)]
        if nnz is None:
            nnz = len(vals)
        if len(vals) > nnz:
            raise ValueError(f"{len(vals)} nonzeros exceed nnz budget {nnz}")
        pad = nnz - len(vals)
        idx = np.concatenate([idx, np.zeros((pad, a.ndim), idx.dtype)], 0)
        vals = np.concatenate([vals, np.zeros((pad,), vals.dtype)], 0)
        return SparseTensor(jnp.asarray(idx, jnp.int32), jnp.asarray(vals),
                            a.shape)

    @staticmethod
    def coo(indices, values, shape) -> "SparseTensor":
        return SparseTensor(jnp.asarray(indices, jnp.int32),
                            jnp.asarray(values), shape)

    def to_dense(self):
        flat_shape = int(np.prod(self.shape))
        strides = np.cumprod([1] + list(self.shape[::-1]))[:-1][::-1]
        flat_idx = (self.indices * jnp.asarray(strides, jnp.int32)).sum(-1)
        out = jnp.zeros((flat_shape,), self.values.dtype)
        # padded entries all hit flat index 0 with value 0 — scatter-add is
        # safe without a mask
        out = out.at[flat_idx].add(self.values)
        return out.reshape(self.shape)

    def __repr__(self):
        return (f"SparseTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.values.dtype})")


def _st_flatten(st):
    return (st.indices, st.values), st.shape


def _st_unflatten(shape, children):
    indices, values = children
    return SparseTensor(indices, values, shape)


jax.tree_util.register_pytree_node(SparseTensor, _st_flatten, _st_unflatten)


def sparse_dense_matmul(sp: SparseTensor, dense) -> jnp.ndarray:
    """(B, I) sparse @ (I, O) dense → (B, O), via gather + segment_sum."""
    if sp.ndim != 2:
        raise ValueError("sparse_dense_matmul expects a 2-D SparseTensor")
    rows = sp.indices[:, 0]
    cols = sp.indices[:, 1]
    contrib = sp.values[:, None] * jnp.take(dense, cols, axis=0)
    return jax.ops.segment_sum(contrib, rows, num_segments=sp.shape[0])


class SparseLinear(Linear):
    """nn/SparseLinear.scala — y = sparse_x @ W^T + b.

    Accepts a :class:`SparseTensor` input (gather+segment_sum path) or a
    dense array (inherited MXU path), matching the reference's contract that
    SparseLinear only differs from Linear in the input type it takes.
    """

    def _apply(self, params, state, x, training, rng):
        if isinstance(x, SparseTensor):
            y = sparse_dense_matmul(x, params["weight"].T)
            if self.with_bias:
                y = y + params["bias"]
            return y
        return super()._apply(params, state, x, training, rng)


class LookupTableSparse(LookupTable):
    """nn/LookupTableSparse.scala — embedding_lookup_sparse.

    Input: a 2-D SparseTensor of positive (1-based) ids, or a
    ``Table(ids, weights)`` of two aligned SparseTensors. Each row's
    embeddings are combined by ``combiner``: sum, mean, or sqrtn
    (weighted variants divide by sum(w) / sqrt(sum(w^2))). ``max_norm``
    L2-clips each embedding before combining. Padded slots (id 0)
    contribute nothing.
    """

    def __init__(self, n_index: int, n_output: int, combiner: str = "sum",
                 max_norm: float = -1.0, w_regularizer=None, name=None):
        if combiner not in ("sum", "mean", "sqrtn"):
            raise ValueError(f"combiner must be sum/mean/sqrtn, "
                             f"got {combiner}")
        super().__init__(n_index, n_output, w_regularizer=w_regularizer,
                         name=name)
        self.combiner = combiner
        self.max_norm = max_norm

    def _apply(self, params, state, x, training, rng):
        if isinstance(x, Table):
            ids_sp, w_sp = x[1], x[2]
        elif isinstance(x, SparseTensor):
            ids_sp, w_sp = x, None
        else:  # dense fallback: (B, L) id matrix, 0 = padding
            ids_sp = SparseTensor.from_dense(np.asarray(x))
            w_sp = None
        if ids_sp.ndim != 2:
            raise ValueError("LookupTableSparse expects 2-D id tensors")

        ids = ids_sp.values.astype(jnp.int32)
        valid = (ids > 0).astype(params["weight"].dtype)
        idx = jnp.clip(ids - 1, 0, self.n_index - 1)  # 1-based ids
        rows = ids_sp.indices[:, 0]
        w = params["weight"]
        emb = jnp.take(w, idx, axis=0)
        if self.max_norm > 0:
            norms = jnp.linalg.norm(emb, axis=-1, keepdims=True)
            emb = emb * jnp.minimum(1.0, self.max_norm / (norms + 1e-12))
        weights = w_sp.values.astype(emb.dtype) if w_sp is not None else valid
        weights = weights * valid
        B = ids_sp.shape[0]
        summed = jax.ops.segment_sum(emb * weights[:, None], rows,
                                     num_segments=B)
        if self.combiner == "sum":
            return summed
        if self.combiner == "mean":
            denom = jax.ops.segment_sum(weights, rows, num_segments=B)
        else:  # sqrtn
            denom = jnp.sqrt(jax.ops.segment_sum(weights ** 2, rows,
                                                 num_segments=B))
        return summed / jnp.maximum(denom, 1e-12)[:, None]


class SparseJoinTable(Module):
    """nn/SparseJoinTable.scala — concat 2-D SparseTensors on ``dimension``
    (1-based; the reference supports dimension=2, feature concat)."""

    def __init__(self, dimension: int = 2, name=None):
        super().__init__(name=name)
        if dimension != 2:
            raise NotImplementedError("SparseJoinTable joins dimension 2 "
                                      "(features), matching the reference")
        self.dimension = dimension

    def _apply(self, params, state, x, training, rng):
        tensors = [x[i + 1] for i in range(len(x))] if isinstance(x, Table) \
            else list(x)
        rows = [t.shape[0] for t in tensors]
        if len(set(rows)) != 1:
            raise ValueError("SparseJoinTable inputs need equal row counts")
        offset = 0
        idx_parts, val_parts = [], []
        for t in tensors:
            if not isinstance(t, SparseTensor):
                raise TypeError("SparseJoinTable expects SparseTensors")
            shifted = t.indices.at[:, 1].add(offset)
            # keep padded entries harmless: zero-value rows may now point at
            # a shifted column, but value 0 contributes 0 downstream
            idx_parts.append(shifted)
            val_parts.append(t.values)
            offset += t.shape[1]
        return SparseTensor(jnp.concatenate(idx_parts, 0),
                            jnp.concatenate(val_parts, 0),
                            (rows[0], offset))


class DenseToSparse(Module):
    """nn/DenseToSparse.scala — densify cut; host-side conversion with a
    fixed nnz budget so the result jits downstream."""

    def __init__(self, nnz: Optional[int] = None, name=None):
        super().__init__(name=name)
        if nnz is None:
            import warnings
            warnings.warn(
                "DenseToSparse without an nnz budget sizes the COO buffer "
                "per batch — downstream jitted consumers recompile whenever "
                "the nonzero count changes; pass nnz=<worst case> for "
                "stable shapes", stacklevel=2)
        self.nnz = nnz

    def _apply(self, params, state, x, training, rng):
        return SparseTensor.from_dense(np.asarray(x), nnz=self.nnz)
