"""Speculative decoding for the LM family (beyond-reference feature).

Autoregressive decode is HBM-bandwidth bound: every generated token
re-streams all model weights (docs/MFU_ROOFLINE.md decode table).
Speculative decoding [Leviathan et al. 2023 pattern; no reference-code
counterpart — the reference's Transformer (nn/Transformer.scala) is
training-only] breaks the one-token-per-weight-stream coupling: a cheap
DRAFT model proposes ``k`` tokens one at a time, and the TARGET model
verifies all ``k`` (plus a bonus token) in ONE cached chunked forward
(``Transformer.decode_chunk``) — a single weight stream serving up to
``k+1`` emitted tokens.

This implementation is GREEDY speculative decoding, which is exactly
output-preserving: the emitted sequence is identical, token for token,
to ``model.generate(params, ..., temperature=0)`` — the draft only
changes the *schedule* of target forwards, never the result (tested
against the dense-generate oracle in tests/test_speculative.py).

Batching: acceptance is LOCKSTEP — each round accepts ``j = min`` over
the batch of the per-row agreement-prefix lengths, so a single shared
scalar cache position serves the whole batch. Per-row exactness still
holds (a row that agreed beyond ``j`` re-emits its own greedy token as
the bonus), but the expected speedup decays with batch size; B=1 (the
latency-serving case) is where speculative decoding pays.

TPU notes: the whole loop is one ``lax.while_loop`` under ``jit`` —
fixed-shape output buffer, masked variable-length emission, no host
sync per round. KV caches are never rewound: rejected positions hold
garbage that position-masked decode attention
(``Attention.decode_chunk``) never reads, and the next round's writes
overwrite them.

Exactness scope: unconditional for dense ``TransformerLM`` targets. A
``MoETransformerLM`` target is exact only while expert capacity is not
saturated — the k+1-token verify forward recomputes routing per chunk,
so tight ``capacity_factor`` can drop a token there that one-token
steps keep (the same cached-vs-full caveat documented on the MoE LM's
inference bindings).
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class SpecStats(NamedTuple):
    """Aggregate speculative statistics (returned with the ids)."""
    rounds: jnp.ndarray          # target verify forwards run
    drafted: jnp.ndarray         # draft tokens proposed (rounds * k)
    accepted: jnp.ndarray        # draft tokens accepted by the target


def speculative_generate(model, params, draft_model, draft_params,
                         prompt_ids, max_new_tokens: int, k: int = 4,
                         return_stats: bool = False):
    """Greedy speculative generation; output is exactly
    ``model.generate(params, prompt_ids, max_new_tokens)`` (greedy).

    model / draft_model: LM-mode ``nn.Transformer``s over the SAME
    vocabulary (the draft is typically far shallower). k: draft tokens
    per round. Returns (B, Tp + max_new_tokens) ids, plus a
    :class:`SpecStats` when ``return_stats``. Jit-compatible end to end.
    """
    assert model.mode == "lm" and draft_model.mode == "lm"
    assert model.vocab_size == draft_model.vocab_size, \
        "draft and target must share a vocabulary"
    assert k >= 1
    prompt_ids = jnp.asarray(prompt_ids, jnp.int32)
    B, Tp = prompt_ids.shape
    if max_new_tokens <= 0:
        return (prompt_ids, SpecStats(*([jnp.zeros((), jnp.int32)] * 3))) \
            if return_stats else prompt_ids
    # a round may overshoot the accepted length by up to k positions —
    # cap the caches (and the emit buffer) accordingly
    cap = Tp + max_new_tokens + k + 1
    assert cap <= model.max_len and cap <= draft_model.max_len, \
        (cap, model.max_len, draft_model.max_len)

    logits_t, caches_t = model.prefill(params, prompt_ids, cap)
    _, caches_d = draft_model.prefill(draft_params, prompt_ids, cap)
    first = jnp.argmax(logits_t, axis=-1).astype(jnp.int32)

    buf = jnp.zeros((B, max_new_tokens + k + 1), jnp.int32)
    buf = jax.lax.dynamic_update_slice(buf, first[:, None], (0, 0))

    def cond(c):
        return c["n"] < max_new_tokens

    def body(c):
        # --- draft phase: k+1 greedy cached steps from the last token.
        # k steps would suffice to PROPOSE d_1..d_k, but the (k+1)-th
        # step writes d_k's K/V into the draft cache: on a
        # fully-accepted round the next round starts past d_k, and a
        # k-step draft would leave a garbage hole at d_k's position that
        # poisons every later proposal (exactness would survive — the
        # target never trusts the draft — but acceptance collapses).
        def dstep(carry, _):
            tok, dc, p = carry
            lg, dc = draft_model.decode_one(draft_params, tok, p, dc)
            nxt = jnp.argmax(lg, axis=-1).astype(jnp.int32)
            return (nxt, dc, p + 1), nxt

        (_, caches_d, _), drafts = jax.lax.scan(
            dstep, (c["last"], c["caches_d"], c["pos"]), None,
            length=k + 1)
        drafts = jnp.moveaxis(drafts, 0, 1)[:, :k]        # (B, k)

        # --- verify phase: ONE chunked target forward over
        # [last, d_1..d_k]; logits row i = target's choice after
        # consuming the first i+1 of those tokens
        chunk = jnp.concatenate([c["last"][:, None], drafts], axis=1)
        lg, caches_t = model.decode_chunk(params, chunk, c["pos"],
                                          c["caches_t"])
        choices = jnp.argmax(lg, axis=-1).astype(jnp.int32)  # (B, k+1)

        # per-row agreement prefix; lockstep-min across the batch keeps
        # one shared cache position (see module docstring)
        match = (drafts == choices[:, :k]).astype(jnp.int32)
        j = jnp.min(jnp.cumprod(match, axis=1).sum(axis=1))  # scalar
        idx = jnp.arange(k + 1)
        bonus = jnp.take_along_axis(
            choices, jnp.full((B, 1), j), axis=1)[:, 0]      # (B,)
        dpad = jnp.concatenate(
            [drafts, jnp.zeros((B, 1), jnp.int32)], axis=1)  # (B, k+1)
        emit = jnp.where(idx[None, :] < j, dpad,
                         jnp.where(idx[None, :] == j,
                                   bonus[:, None], 0))
        out = jax.lax.dynamic_update_slice(c["out"], emit, (0, c["n"]))
        return dict(
            caches_t=caches_t, caches_d=caches_d, last=bonus,
            pos=c["pos"] + j + 1, n=c["n"] + j + 1, out=out,
            rounds=c["rounds"] + 1, accepted=c["accepted"] + j)

    final = jax.lax.while_loop(cond, body, dict(
        caches_t=caches_t, caches_d=caches_d, last=first,
        pos=jnp.int32(Tp), n=jnp.int32(1), out=buf,
        rounds=jnp.int32(0), accepted=jnp.int32(0)))

    ids = jnp.concatenate(
        [prompt_ids, final["out"][:, :max_new_tokens]], axis=1)
    if return_stats:
        return ids, SpecStats(rounds=final["rounds"],
                              drafted=final["rounds"] * k,
                              accepted=final["accepted"])
    return ids
