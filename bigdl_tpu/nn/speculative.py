"""Speculative decoding for the LM family (beyond-reference feature).

Autoregressive decode is HBM-bandwidth bound: every generated token
re-streams all model weights (docs/MFU_ROOFLINE.md decode table).
Speculative decoding [Leviathan et al. 2023 pattern; no reference-code
counterpart — the reference's Transformer (nn/Transformer.scala) is
training-only] breaks the one-token-per-weight-stream coupling: a cheap
DRAFT model proposes ``k`` tokens one at a time, and the TARGET model
verifies all ``k`` (plus a bonus token) in ONE cached chunked forward
(``Transformer.decode_chunk``) — a single weight stream serving up to
``k+1`` emitted tokens.

Two modes:

- ``temperature == 0`` — GREEDY speculative decoding, exactly
  output-preserving: the emitted sequence is identical, token for
  token, to ``model.generate(params, ..., temperature=0)`` — the draft
  only changes the *schedule* of target forwards, never the result
  (tested against the dense-generate oracle in
  tests/test_speculative.py).
- ``temperature > 0`` — SAMPLING speculative decoding via rejection
  sampling: proposal ``d_i`` (drawn from the draft distribution
  ``p_d``) is accepted with probability ``min(1, p_t(d_i)/p_d(d_i))``;
  on rejection the token is re-drawn from the residual
  ``max(p_t - p_d, 0)`` (renormalised), and on a fully-accepted round
  the bonus token is drawn from ``p_t`` directly. Each emitted token is
  distributed EXACTLY as target sampling at that temperature — the
  draft changes the schedule and the random-number consumption, never
  the distribution (statistically tested against the enumerated target
  marginal).

Exactness scope: unconditional for dense ``TransformerLM`` targets. A
``MoETransformerLM`` target is exact only while expert capacity is not
saturated — the k+1-token verify forward recomputes routing per chunk,
so tight ``capacity_factor`` can drop a token there that one-token
steps keep (the same cached-vs-full caveat documented on the MoE LM's
inference bindings).

Batching: acceptance is LOCKSTEP — each round accepts ``j = min`` over
the batch of the per-row acceptance-prefix lengths, so a single shared
scalar cache position serves the whole batch. Per-row correctness still
holds (a row that accepted beyond ``j`` emits its own accepted draft
token at position ``j+1``; a row that rejected there re-draws from its
own residual), but the expected speedup decays with batch size; B=1
(the latency-serving case) is where speculative decoding pays.

TPU notes: the whole loop is one ``lax.while_loop`` under ``jit`` —
fixed-shape output buffer, masked variable-length emission, no host
sync per round. KV caches are never rewound: rejected positions hold
garbage that position-masked decode attention
(``Attention.decode_chunk``) never reads, and the next round's writes
overwrite them. The sampling mode carries the draft's per-step
distribution rows ((B, k, V) f32) through the round — at bench scale
(B8, k4, V32k) that is ~4 MB, negligible next to the KV caches.
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class SpecStats(NamedTuple):
    """Aggregate speculative statistics (returned with the ids)."""
    rounds: jnp.ndarray          # target verify forwards run
    drafted: jnp.ndarray         # draft tokens proposed (rounds * k)
    accepted: jnp.ndarray        # draft tokens accepted by the target


def batched_acceptance(drafts, choices, eligible):
    """PER-ROW greedy acceptance for one batched speculative round —
    the serving scheduler's schedule (``serving/decode_scheduler.py``),
    where every row keeps its OWN acceptance length instead of the
    lockstep ``min`` the fixed-shape ``speculative_generate`` loop
    takes (the scheduler holds per-row position counters host-side, so
    rows are free to advance unevenly).

    drafts: (B, k) int32 — the draft's proposals per row;
    choices: (B, k+1) int32 — the target's own per-position token
    choices from the ONE chunked verify forward (``choices[:, i]`` is
    the target's pick after consuming ``[last, d_1..d_i]``);
    eligible: (B,) bool — rows NOT speculating this round (sampled
    rows riding the dispatch masked to one real token, padded slots)
    are forced to acceptance 0 so they emit exactly ``choices[:, 0]``.

    Returns ``(accept_len (B,), emit (B, k+1))``: row ``b`` emits
    ``emit[b, :accept_len[b]+1]`` — its accepted draft prefix plus the
    target's own choice at the first divergence (the bonus token on a
    fully-accepted round). Output-preserving by construction: every
    emitted token is one of the TARGET's choices (accepted drafts
    equal them by definition of acceptance). Runs in-program (jitted
    by the scheduler) so one readback carries both the lengths and the
    tokens."""
    drafts = drafts.astype(jnp.int32)
    choices = choices.astype(jnp.int32)
    k = drafts.shape[1]
    match = (drafts == choices[:, :k]).astype(jnp.int32)
    j = jnp.cumprod(match, axis=1).sum(axis=1)          # (B,)
    j = jnp.where(eligible, j, 0)
    bonus = jnp.take_along_axis(choices, j[:, None], axis=1)  # (B, 1)
    dpad = jnp.concatenate(
        [drafts, jnp.zeros((drafts.shape[0], 1), jnp.int32)], axis=1)
    idx = jnp.arange(k + 1)[None, :]
    emit = jnp.where(idx < j[:, None], dpad,
                     jnp.where(idx == j[:, None], bonus, 0))
    return j, emit


def speculative_generate(model, params, draft_model, draft_params,
                         prompt_ids, max_new_tokens: int, k: int = 4,
                         temperature: float = 0.0, rng=None,
                         return_stats: bool = False):
    """Speculative generation (greedy at ``temperature == 0``, rejection
    sampling above — see module docstring for the guarantees).

    model / draft_model: LM-mode ``nn.Transformer``s over the SAME
    vocabulary (the draft is typically far shallower). k: draft tokens
    per round. Returns (B, Tp + max_new_tokens) ids, plus a
    :class:`SpecStats` when ``return_stats``. Jit-compatible end to end.
    """
    assert model.mode == "lm" and draft_model.mode == "lm"
    assert model.vocab_size == draft_model.vocab_size, \
        "draft and target must share a vocabulary"
    assert k >= 1
    sampling = temperature > 0.0
    prompt_ids = jnp.asarray(prompt_ids, jnp.int32)
    B, Tp = prompt_ids.shape
    if max_new_tokens <= 0:
        return (prompt_ids, SpecStats(*([jnp.zeros((), jnp.int32)] * 3))) \
            if return_stats else prompt_ids
    # a round may overshoot the accepted length by up to k positions —
    # cap the caches (and the emit buffer) accordingly
    cap = Tp + max_new_tokens + k + 1
    assert cap <= model.max_len and cap <= draft_model.max_len, \
        (cap, model.max_len, draft_model.max_len)
    if rng is None:
        rng = jax.random.PRNGKey(0)

    logits_t, caches_t = model.prefill(params, prompt_ids, cap)
    _, caches_d = draft_model.prefill(draft_params, prompt_ids, cap)
    key0, rng = jax.random.split(rng)
    if sampling:
        first = jax.random.categorical(
            key0, logits_t.astype(jnp.float32) / temperature,
            axis=-1).astype(jnp.int32)
    else:
        first = jnp.argmax(logits_t, axis=-1).astype(jnp.int32)

    buf = jnp.zeros((B, max_new_tokens + k + 1), jnp.int32)
    buf = jax.lax.dynamic_update_slice(buf, first[:, None], (0, 0))

    def cond(c):
        return c["n"] < max_new_tokens

    def body(c):
        key, kd, ka, kr = jax.random.split(c["key"], 4)

        # --- draft phase: k+1 cached steps from the last token.
        # k steps would suffice to PROPOSE d_1..d_k, but the (k+1)-th
        # step writes d_k's K/V into the draft cache: on a
        # fully-accepted round the next round starts past d_k, and a
        # k-step draft would leave a garbage hole at d_k's position that
        # poisons every later proposal (correctness would survive — the
        # target never trusts the draft — but acceptance collapses).
        def dstep(carry, i):
            tok, dc, p = carry
            lg, dc = draft_model.decode_one(draft_params, tok, p, dc)
            if sampling:
                lf = lg.astype(jnp.float32) / temperature
                nxt = jax.random.categorical(
                    jax.random.fold_in(kd, i), lf, axis=-1)
                probs = jax.nn.softmax(lf, axis=-1)
            else:
                nxt = jnp.argmax(lg, axis=-1)
                probs = jnp.zeros((B, 0), jnp.float32)  # unused
            return (nxt.astype(jnp.int32), dc, p + 1), (nxt, probs)

        (_, caches_d, _), (drafts_all, pdraft_all) = jax.lax.scan(
            dstep, (c["last"], c["caches_d"], c["pos"]),
            jnp.arange(k + 1))
        drafts = jnp.moveaxis(drafts_all, 0, 1)[:, :k].astype(jnp.int32)

        # --- verify phase: ONE chunked target forward over
        # [last, d_1..d_k]; logits row i = the target's next-token
        # distribution after consuming the first i+1 of those tokens
        chunk = jnp.concatenate([c["last"][:, None], drafts], axis=1)
        lg, caches_t = model.decode_chunk(params, chunk, c["pos"],
                                          c["caches_t"])

        dpad = jnp.concatenate(
            [drafts, jnp.zeros((B, 1), jnp.int32)], axis=1)  # (B, k+1)
        idx = jnp.arange(k + 1)
        if sampling:
            p_t = jax.nn.softmax(
                lg.astype(jnp.float32) / temperature, axis=-1)
            p_d = jnp.moveaxis(pdraft_all, 0, 1)[:, :k]      # (B, k, V)
            d_idx = drafts[..., None]
            pt_d = jnp.take_along_axis(p_t[:, :k], d_idx, -1)[..., 0]
            pd_d = jnp.take_along_axis(p_d, d_idx, -1)[..., 0]
            u = jax.random.uniform(ka, (B, k))
            # accept iff u < p_t/p_d, written division-free (pd_d -> 0
            # limit accepts whenever the target gives the token mass)
            acc = (u * pd_d < pt_d).astype(jnp.int32)
            a_row = jnp.cumprod(acc, axis=1).sum(axis=1)     # (B,)
            j = jnp.min(a_row)
            # token at position j: accepted rows keep their draft;
            # rejected rows re-draw from the residual max(p_t-p_d, 0).
            # On a fully-accepted round (j == k) there is no proposal:
            # zeroing p_d makes the residual p_t itself — the standard
            # bonus draw — so one code path serves both cases.
            pt_j = jax.lax.dynamic_index_in_dim(p_t, j, 1, False)
            pd_j = jax.lax.dynamic_index_in_dim(
                p_d, jnp.minimum(j, k - 1), 1, False)
            pd_j = jnp.where(j == k, 0.0, pd_j)
            res = jnp.maximum(pt_j - pd_j, 0.0)
            res = jnp.where(res.sum(-1, keepdims=True) > 0, res, pt_j)
            res_tok = jax.random.categorical(
                kr, jnp.log(jnp.maximum(res, 1e-38)),
                axis=-1).astype(jnp.int32)
            draft_j = jnp.take_along_axis(
                dpad, jnp.full((B, 1), j), axis=1)[:, 0]
            nxt = jnp.where(a_row > j, draft_j, res_tok)
        else:
            choices = jnp.argmax(lg, axis=-1).astype(jnp.int32)
            match = (drafts == choices[:, :k]).astype(jnp.int32)
            a_row = jnp.cumprod(match, axis=1).sum(axis=1)
            j = jnp.min(a_row)
            # greedy: every row's token at position j is the target's
            # own argmax there (rows that matched beyond j agree with
            # their draft anyway)
            nxt = jnp.take_along_axis(
                choices, jnp.full((B, 1), j), axis=1)[:, 0]

        emit = jnp.where(idx[None, :] < j, dpad,
                         jnp.where(idx[None, :] == j, nxt[:, None], 0))
        out = jax.lax.dynamic_update_slice(c["out"], emit, (0, c["n"]))
        return dict(
            caches_t=caches_t, caches_d=caches_d, last=nxt, key=key,
            pos=c["pos"] + j + 1, n=c["n"] + j + 1, out=out,
            rounds=c["rounds"] + 1, accepted=c["accepted"] + j)

    final = jax.lax.while_loop(cond, body, dict(
        caches_t=caches_t, caches_d=caches_d, last=first, key=rng,
        pos=jnp.int32(Tp), n=jnp.int32(1), out=buf,
        rounds=jnp.int32(0), accepted=jnp.int32(0)))

    ids = jnp.concatenate(
        [prompt_ids, final["out"][:, :max_new_tokens]], axis=1)
    if return_stats:
        return ids, SpecStats(rounds=final["rounds"],
                              drafted=final["rounds"] * k,
                              accepted=final["accepted"])
    return ids
