"""Table (multi-activity) layers.

Parity: reference ``nn/CAddTable.scala`` and friends, ``nn/JoinTable.scala``,
``nn/SplitTable.scala``, ``nn/SelectTable.scala``, ``nn/NarrowTable.scala``,
``nn/FlattenTable.scala``, ``nn/MixtureTable.scala``, ``nn/DotProduct.scala``,
``nn/MM.scala``, ``nn/MV.scala``, ``nn/CrossProduct.scala``,
``nn/PairwiseDistance.scala``, ``nn/CosineDistance.scala``,
``nn/BifurcateSplitTable.scala``, ``nn/TableOperation.scala``.
"""
from __future__ import annotations

import jax.numpy as jnp

from .module import Module
from .shape_ops import _dim0
from ..utils.table import Table


class _CwiseTable(Module):
    def _combine(self, a, b):
        raise NotImplementedError

    def _apply(self, params, state, x, training, rng):
        items = x.to_list() if isinstance(x, Table) else list(x)
        out = items[0]
        for it in items[1:]:
            out = self._combine(out, it)
        return out


class CAddTable(_CwiseTable):
    def __init__(self, inplace: bool = False, name=None):
        super().__init__(name=name)

    def _combine(self, a, b):
        return a + b


class CSubTable(_CwiseTable):
    def _combine(self, a, b):
        return a - b


class CMulTable(_CwiseTable):
    def _combine(self, a, b):
        return a * b


class CDivTable(_CwiseTable):
    def _combine(self, a, b):
        return a / b


class CMaxTable(_CwiseTable):
    def _combine(self, a, b):
        return jnp.maximum(a, b)


class CMinTable(_CwiseTable):
    def _combine(self, a, b):
        return jnp.minimum(a, b)


class CAveTable(Module):
    def __init__(self, inplace: bool = False, name=None):
        super().__init__(name=name)

    def _apply(self, params, state, x, training, rng):
        items = x.to_list() if isinstance(x, Table) else list(x)
        return sum(items) / len(items)


class JoinTable(Module):
    """Concat table elements along 1-based dim (nn/JoinTable.scala)."""

    def __init__(self, dimension: int, n_input_dims: int = -1, name=None):
        super().__init__(name=name)
        self.dimension, self.n_input_dims = dimension, n_input_dims

    def _apply(self, params, state, x, training, rng):
        items = x.to_list() if isinstance(x, Table) else list(x)
        d = _dim0(self.dimension, items[0], self.n_input_dims)
        return jnp.concatenate(items, axis=d)


class SplitTable(Module):
    """Split along 1-based dim into a Table (nn/SplitTable.scala)."""

    def __init__(self, dimension: int, n_input_dims: int = -1, name=None):
        super().__init__(name=name)
        self.dimension, self.n_input_dims = dimension, n_input_dims

    def _apply(self, params, state, x, training, rng):
        d = _dim0(self.dimension, x, self.n_input_dims)
        n = x.shape[d]
        parts = [jnp.take(x, i, axis=d) for i in range(n)]
        return Table(*parts)


class BifurcateSplitTable(Module):
    """Split in half along dim (nn/BifurcateSplitTable.scala)."""

    def __init__(self, dimension: int, name=None):
        super().__init__(name=name)
        self.dimension = dimension

    def _apply(self, params, state, x, training, rng):
        d = self.dimension - 1
        half = x.shape[d] // 2
        import jax
        a = jax.lax.slice_in_dim(x, 0, half, axis=d)
        b = jax.lax.slice_in_dim(x, half, x.shape[d], axis=d)
        return Table(a, b)


class SelectTable(Module):
    """Pick the i-th (1-based) element (nn/SelectTable.scala)."""

    def __init__(self, index: int, name=None):
        super().__init__(name=name)
        self.index = index

    def _apply(self, params, state, x, training, rng):
        i = self.index if self.index > 0 else len(x) + self.index + 1
        return x[i]


class NarrowTable(Module):
    """Slice the table itself (nn/NarrowTable.scala)."""

    def __init__(self, offset: int, length: int = 1, name=None):
        super().__init__(name=name)
        self.offset, self.length = offset, length

    def _apply(self, params, state, x, training, rng):
        length = self.length
        if length < 0:
            length = len(x) - self.offset + 2 + length
        items = [x[self.offset + i] for i in range(length)]
        return Table(*items)


class FlattenTable(Module):
    """Flatten nested Tables (nn/FlattenTable.scala)."""

    def _apply(self, params, state, x, training, rng):
        out = []

        def rec(t):
            if isinstance(t, Table):
                for item in t:
                    rec(item)
            else:
                out.append(t)
        rec(x)
        return Table(*out)


class MixtureTable(Module):
    """Mixture-of-experts blend: Table(gate (B,K), experts Table/Tensor)
    (nn/MixtureTable.scala)."""

    def __init__(self, dim: int = None, name=None):
        super().__init__(name=name)
        self.dim = dim

    def _apply(self, params, state, x, training, rng):
        gate, experts = x[1], x[2]
        if isinstance(experts, Table):
            stacked = jnp.stack(experts.to_list(), axis=1)  # (B, K, ...)
        else:
            stacked = experts
        g = gate.reshape(gate.shape + (1,) * (stacked.ndim - gate.ndim))
        return jnp.sum(stacked * g, axis=1)


class DotProduct(Module):
    """Rowwise dot of Table(a, b) (nn/DotProduct.scala)."""

    def _apply(self, params, state, x, training, rng):
        a, b = x[1], x[2]
        if a.ndim == 1:
            return jnp.sum(a * b)[None]
        return jnp.sum(a * b, axis=-1)


class CrossProduct(Module):
    """Pairwise dot between every pair of table entries (nn/CrossProduct.scala)."""

    def __init__(self, num_tensor: int = 0, embedding_size: int = 0, name=None):
        super().__init__(name=name)

    def _apply(self, params, state, x, training, rng):
        items = x.to_list()
        outs = []
        for i in range(len(items)):
            for j in range(i + 1, len(items)):
                outs.append(jnp.sum(items[i] * items[j], axis=-1, keepdims=True))
        return jnp.concatenate(outs, axis=-1)


class MM(Module):
    """Matrix-matrix product of Table(a, b) (nn/MM.scala)."""

    def __init__(self, trans_a: bool = False, trans_b: bool = False, name=None):
        super().__init__(name=name)
        self.trans_a, self.trans_b = trans_a, trans_b

    def _apply(self, params, state, x, training, rng):
        a, b = x[1], x[2]
        if self.trans_a:
            a = jnp.swapaxes(a, -1, -2)
        if self.trans_b:
            b = jnp.swapaxes(b, -1, -2)
        return jnp.matmul(a, b)


class MV(Module):
    """Matrix-vector product of Table(mat, vec) (nn/MV.scala)."""

    def __init__(self, trans: bool = False, name=None):
        super().__init__(name=name)
        self.trans = trans

    def _apply(self, params, state, x, training, rng):
        m, v = x[1], x[2]
        if self.trans:
            m = jnp.swapaxes(m, -1, -2)
        return jnp.einsum("...ij,...j->...i", m, v)


class PairwiseDistance(Module):
    """Lp distance of Table(a, b) rows (nn/PairwiseDistance.scala)."""

    def __init__(self, norm: int = 2, name=None):
        super().__init__(name=name)
        self.norm = norm

    def _apply(self, params, state, x, training, rng):
        a, b = x[1], x[2]
        d = jnp.abs(a - b)
        return jnp.power(jnp.sum(jnp.power(d, self.norm), axis=-1),
                         1.0 / self.norm)


class CosineDistance(Module):
    """Cosine similarity of Table(a, b) rows (nn/CosineDistance.scala)."""

    def _apply(self, params, state, x, training, rng):
        a, b = x[1], x[2]
        num = jnp.sum(a * b, axis=-1)
        den = jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1)
        return num / jnp.maximum(den, 1e-12)


class TableOperation(Module):
    """Apply a binary op elementwise over Table(a, b), broadcasting smaller
    (nn/TableOperation.scala)."""

    def __init__(self, operation, name=None):
        super().__init__(name=name)
        self.operation = operation

    def _apply(self, params, state, x, training, rng):
        return self.operation(x[1], x[2])
