"""TreeLSTM / BinaryTreeLSTM (constituency Tree-LSTM).

Parity: reference ``nn/TreeLSTM.scala`` + ``nn/BinaryTreeLSTM.scala``
(Tai et al. 2015). The reference walks each tree with host-side recursion
(``recursiveForward``, BinaryTreeLSTM.scala:218-265), cloning leaf/composer
cells per node and sharing parameters. That shape is untraceable on TPU, so
this implementation is *level-synchronous*: every scan step applies the (one)
composer to **all** nodes at once, gathering child (c, h) from state buffers,
and commits updates only for nodes whose two children are already done. After
``depth(tree)`` steps every node has its state; the step count is a static
``max_depth`` (default: node count, the safe worst case) so the whole forward
is one ``lax.scan`` the compiler can unroll onto the MXU, and ``backward``
falls out of ``jax.vjp`` like every other module.

Tree encoding is the reference's ``TensorTree`` (BinaryTreeLSTM.scala:513):
``trees`` is (batch, nNodes, 3); columns 0,1 = left/right child node index
(1-based; 0 = none), column 2 = leaf's word index (1-based) for leaves or -1
for the root; padding rows have -1 in column 0.

Input: table ``(inputs, trees)`` with ``inputs`` (batch, nWords, inputSize).
Output: (batch, nNodes, hiddenSize) — each node's hidden state (zeros at
padding rows), exactly the reference's ``updateOutput`` layout.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .module import Module
from .init import RandomUniform

_default_init = RandomUniform()


class TreeLSTM(Module):
    """Abstract base (parity: nn/TreeLSTM.scala:25)."""

    def __init__(self, input_size: int, hidden_size: int = 150, name=None):
        super().__init__(name=name)
        self.input_size = input_size
        self.hidden_size = hidden_size


class BinaryTreeLSTM(TreeLSTM):
    """Binary constituency Tree-LSTM (nn/BinaryTreeLSTM.scala:40).

    Leaf cell (createLeafModuleWithGraph, :63):
      ``c = W_c x``; ``h = sigmoid(W_o x) * tanh(c)`` (or ``tanh(c)`` when
      ``gate_output=False``).
    Composer (createComposerWithGraph, :82): gates i, lf, rf, update (and o)
    each ``sigmoid/tanh(W_l lh + W_r rh)``; here the five gates are one fused
    (hidden → 5*hidden) pair of matmuls — mathematically identical to the
    reference's per-gate Linears, but a single MXU contraction.

    ``max_depth`` bounds the level-synchronous sweep; ``None`` uses the node
    count (safe for any tree). Balanced trees only need ~log2(nNodes).
    """

    def __init__(self, input_size: int, hidden_size: int = 150,
                 gate_output: bool = True, with_graph: bool = True,
                 max_depth: int | None = None, name=None):
        super().__init__(input_size, hidden_size, name=name)
        self.gate_output = gate_output
        self.with_graph = with_graph  # kept for API parity; same math either way
        self.max_depth = max_depth

    def _init_params(self, rng):
        h, d = self.hidden_size, self.input_size
        n_gate = 5 if self.gate_output else 4
        ks = jax.random.split(rng, 6)
        p = {
            "leaf_wc": _default_init(ks[0], (h, d), fan_in=d, fan_out=h),
            "leaf_bc": jnp.zeros((h,), jnp.float32),
            "comp_wl": _default_init(ks[1], (n_gate * h, h), fan_in=h,
                                     fan_out=n_gate * h),
            "comp_wr": _default_init(ks[2], (n_gate * h, h), fan_in=h,
                                     fan_out=n_gate * h),
            "comp_b": jnp.zeros((n_gate * h,), jnp.float32),
        }
        if self.gate_output:
            p["leaf_wo"] = _default_init(ks[3], (h, d), fan_in=d, fan_out=h)
            p["leaf_bo"] = jnp.zeros((h,), jnp.float32)
        return p

    def _leaf(self, params, x):
        # x: (..., input_size) → (c, h) each (..., hidden_size)
        c = x @ params["leaf_wc"].T + params["leaf_bc"]
        if self.gate_output:
            o = jax.nn.sigmoid(x @ params["leaf_wo"].T + params["leaf_bo"])
            hh = o * jnp.tanh(c)
        else:
            hh = jnp.tanh(c)
        return c, hh

    def _compose(self, params, lc, lh, rc, rh):
        # all (..., hidden) → (c, h)
        H = self.hidden_size
        g = lh @ params["comp_wl"].T + rh @ params["comp_wr"].T + params["comp_b"]
        i = jax.nn.sigmoid(g[..., :H])
        lf = jax.nn.sigmoid(g[..., H:2 * H])
        rf = jax.nn.sigmoid(g[..., 2 * H:3 * H])
        u = jnp.tanh(g[..., 3 * H:4 * H])
        c = i * u + lf * lc + rf * rc
        if self.gate_output:
            o = jax.nn.sigmoid(g[..., 4 * H:5 * H])
            hh = o * jnp.tanh(c)
        else:
            hh = jnp.tanh(c)
        return c, hh

    def _apply(self, params, state, x, training, rng):
        inputs, trees = (x[0], x[1]) if isinstance(x, (tuple, list)) \
            else (x[1], x[2])  # Table is 1-indexed
        inputs = jnp.asarray(inputs)
        trees = jnp.asarray(trees)
        squeeze = inputs.ndim == 2
        if squeeze:  # single sample
            inputs, trees = inputs[None], trees[None]
        n_nodes = trees.shape[1]
        depth = self.max_depth or n_nodes

        def one_tree(words, tree):
            left = tree[:, 0].astype(jnp.int32)    # 1-based, 0/-1 = none/pad
            right = tree[:, 1].astype(jnp.int32)
            leaf_idx = tree[:, 2].astype(jnp.int32)
            is_pad = left < 0
            is_leaf = (left == 0) & ~is_pad
            has_child = left > 0

            # leaves: gather word vectors (leaf_idx is 1-based into words)
            wv = jnp.take(words, jnp.clip(leaf_idx - 1, 0, words.shape[0] - 1),
                          axis=0)
            lc0, lh0 = self._leaf(params, wv)
            m = is_leaf[:, None]
            c0 = jnp.where(m, lc0, 0.0)
            h0 = jnp.where(m, lh0, 0.0)
            done0 = is_leaf

            li = jnp.clip(left - 1, 0, n_nodes - 1)
            ri = jnp.clip(right - 1, 0, n_nodes - 1)

            def step(carry, _):
                c, h, done = carry
                cc, hh = self._compose(params, c[li], h[li], c[ri], h[ri])
                ready = has_child & done[li] & done[ri] & ~done
                rm = ready[:, None]
                return (jnp.where(rm, cc, c), jnp.where(rm, hh, h),
                        done | ready), None

            (c, h, _), _ = jax.lax.scan(step, (c0, h0, done0), None,
                                        length=depth)
            return h

        out = jax.vmap(one_tree)(inputs, trees)
        return out[0] if squeeze else out


def tensor_tree(n_nodes: int):
    """Host-side helper mirroring ``TensorTree`` construction
    (BinaryTreeLSTM.scala:513): returns an (n_nodes, 3) numpy array
    initialised to padding; use ``add_child``/``mark_as_leaf``/``mark_as_root``
    semantics by writing columns directly."""
    import numpy as np
    t = np.zeros((n_nodes, 3), np.float32)
    t[:, 0] = -1.0
    return t
