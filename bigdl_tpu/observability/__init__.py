"""Unified tracing + metrics (the production-operator view of training).

The reference exposed training progress only through TensorBoard
``TrainSummary``/``ValidationSummary``; everything else — step phase
timing, collective bytes, probe latency — lived in ad-hoc dicts and
prints. This package is the one subsystem the rest of the codebase
reports into:

* ``trace`` — span-based tracer: ``with observability.span("step/dispatch"):``
  nests via a thread-local stack, stamps monotonic clocks, and survives
  exceptions (the span closes and is tagged with the error type).
* ``metrics`` — a process-global registry of counters, gauges and
  histograms (reservoir quantiles), keyed by slash-namespaced names
  (``optim/step_time``, ``collective/psum_bytes``).
* ``exporters`` — Chrome trace-event JSON (load in Perfetto /
  chrome://tracing), Prometheus text format, a bridge into the existing
  ``visualization.Summary`` event files (TensorBoard keeps working), and
  the BENCH_*.json-compatible metric-line dump shared with ``bench.py``.
* ``health`` — whether the system is ALIVE: a stall watchdog over
  per-component progress beacons (``health/stall`` events), rolling
  loss/grad-norm anomaly detectors (spikes, plateaus, NaN streaks),
  device-memory telemetry (``mem/*`` live gauges), and env-gated
  ``jax.profiler`` windows (``BIGDL_TPU_PROFILE=start:stop``).
* ``flight`` — a bounded ring of recent structured events dumped as a
  JSON crash bundle on unhandled failure; render post-mortems with
  ``tools/flight_report.py``.
* ``perf`` — what the compiled programs COST: per-program XLA
  cost/memory artifacts from every compile site (``compile/*``,
  rendered by ``tools/xla_report.py``) and live MFU / step-phase
  gauges (``perf/mfu``, ``perf/phase_*_frac``) derived from them.
* ``cluster`` — per-process metric-snapshot files merged by rank 0
  into one cluster view (step-time skew, straggler attribution joined
  with heartbeat ages); render with ``tools/cluster_report.py``.

Zero-overhead when disabled: ``span()`` returns a shared no-op context
manager and call-sites guard metric writes with ``enabled()`` — the
disabled cost in the optimizer hot loop is one module-global flag read
per phase. Enable with ``observability.enable()`` or
``BIGDL_TPU_TRACE=1`` in the environment.

Span naming convention: ``<subsystem>/<phase>`` with the subsystem as a
stable prefix (``step/``, ``eval/``, ``predict/``, ``bench/``); nested
phases extend the parent's name (``step`` > ``step/data_fetch``).
"""
from __future__ import annotations

import os as _os

from .trace import (Tracer, enable, disable, enabled, span, instant,
                    complete, get_tracer, reset)
from .metrics import (Counter, Gauge, Histogram, MetricsRegistry,
                      registry, counter, gauge, histogram)
from .exporters import (chrome_trace, write_chrome_trace, prometheus_text,
                        SummaryBridge, metrics_dump, write_metrics_dump,
                        record_bench_line)
from . import flight
from . import health
from . import perf
from . import cluster

if _os.environ.get("BIGDL_TPU_TRACE") == "1":
    enable()
