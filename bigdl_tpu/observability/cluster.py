"""Cluster-wide metric aggregation: one view of a multi-process run.

Each process periodically snapshots its metrics registry into the
(shared) flight-bundle directory — the same directory, atomic-write and
skip-half-written-files discipline the crash bundles already use — and
rank 0 merges the per-process files into ONE cluster view: per-host
step-time skew, straggler attribution joined with the
``parallel/failure`` heartbeat-age gauges, and a merged Prometheus
export. ``tools/cluster_report.py`` renders the view;
:class:`~bigdl_tpu.parallel.elastic.ElasticRunner` writes an aggregate
at every restart so a reshaped mesh keeps one coherent timeline (the
snapshot files survive the restart — the view spans the reshape).

Snapshots are a file per PROCESS, overwritten in place (atomic rename):
the merge wants each host's LATEST state, and a bounded file set means
a week-long run cannot fill the disk with telemetry. Cadence comes
from ``BIGDL_TPU_METRIC_SNAP_S`` (seconds; unset or ``0`` disables —
single-host runs opt in, multi-host launchers export it) or an
explicit ``every_s``.

Import discipline: stdlib-only at import time (the package loads
standalone in the jax-free bench parent); jax is only touched lazily
for process indices, with a safe fallback.
"""
from __future__ import annotations

import json
import logging
import os
import time
from typing import Dict, List, Optional

from . import metrics as _metrics
from . import trace as _trace
from . import flight as _flight

_LOG = logging.getLogger("bigdl_tpu.observability.cluster")

SNAPSHOT_SCHEMA = "bigdl_tpu.metric_snapshot.v1"
CLUSTER_SCHEMA = "bigdl_tpu.cluster_view.v1"

#: a process whose mean step time exceeds the cluster median by this
#: factor is attributed as a straggler in the merged view
STRAGGLER_RATIO = 1.5

#: a straggler whose heartbeat age exceeds this is flagged as dying
#: rather than merely slow (joins the ``parallel/failure`` signal)
STALE_HEARTBEAT_S = 30.0


def snapshot_interval_from_env() -> float:
    """``BIGDL_TPU_METRIC_SNAP_S`` as a float; 0.0 (disabled) on unset
    or unparsable."""
    raw = os.environ.get("BIGDL_TPU_METRIC_SNAP_S", "")
    try:
        v = float(raw) if raw else 0.0
    except ValueError:
        _LOG.warning("ignoring unparsable BIGDL_TPU_METRIC_SNAP_S=%r", raw)
        return 0.0
    return max(0.0, v)


def _process_index() -> int:
    try:
        import jax
        return jax.process_index()
    except Exception:  # noqa: BLE001 — pre-init / jax-free callers
        return 0


def snapshot_path(directory: Optional[str] = None,
                  process_index: Optional[int] = None) -> str:
    d = directory or _flight.bundle_dir()
    idx = _process_index() if process_index is None else int(process_index)
    return os.path.join(d, f"metrics_p{idx:05d}.json")


class MetricSnapshotWriter:
    """Periodic per-process metric snapshots (one overwritten file).

    ``maybe_write(step=...)`` is the hot-loop entry: one monotonic
    clock read when the cadence has not elapsed, an atomic JSON write
    when it has. Hot loops call it obs-gated; a zero/negative interval
    makes every call a no-op (the disabled configuration costs one
    comparison)."""

    def __init__(self, every_s: Optional[float] = None,
                 directory: Optional[str] = None,
                 process_index: Optional[int] = None):
        self.every_s = snapshot_interval_from_env() \
            if every_s is None else float(every_s)
        self._dir = directory or _flight.bundle_dir()
        self._idx = _process_index() if process_index is None \
            else int(process_index)
        self._last = 0.0
        self.writes = 0
        self._sections: Dict[str, object] = {}

    def add_section(self, name: str, fn) -> None:
        """Attach a named extra section to every snapshot this writer
        produces: ``fn()`` is called per write and its dict lands in the
        doc under ``name`` (the serving fleet agent publishes its
        queue-depth/inflight/prefix-summary/active-version section this
        way — the router's remote load/health signal rides the SAME
        files the cluster merge already reads). A raising provider is
        skipped for that write — telemetry never takes down the run."""
        if name in ("schema", "written_at", "pid", "process_index",
                    "step", "metrics", "final", "snapshot_file"):
            raise ValueError(f"section name {name!r} collides with a "
                             "core snapshot field")
        self._sections[name] = fn

    @property
    def enabled(self) -> bool:
        return self.every_s > 0

    def maybe_write(self, step: Optional[int] = None,
                    force: bool = False) -> Optional[str]:
        if not force:
            if self.every_s <= 0:
                return None
            now = time.monotonic()
            if now - self._last < self.every_s:
                return None
            self._last = now
        return self.write(step=step)

    def write(self, step: Optional[int] = None,
              final: bool = False) -> Optional[str]:
        """Unconditional snapshot write (atomic tmp+rename). Never
        raises — telemetry must not take down the run. ``final=True``
        is the TERMINAL write a cleanly-exiting process lands: the
        merge then knows this process FINISHED — its snapshot going
        stale afterwards is retirement, not a wedge — and the
        straggler/suspect-dead attribution skips it (a finished process
        used to read exactly like a dead one)."""
        try:
            os.makedirs(self._dir, exist_ok=True)
            path = snapshot_path(self._dir, self._idx)
            doc = {
                "schema": SNAPSHOT_SCHEMA,
                "written_at": time.time(),
                "pid": os.getpid(),
                "process_index": self._idx,
                "step": step,
                "final": bool(final),
                "metrics": _metrics.registry().snapshot(),
            }
            for name, fn in self._sections.items():
                try:
                    doc[name] = fn()
                except Exception:  # noqa: BLE001 — telemetry only
                    _LOG.exception("snapshot section %r failed", name)
            tmp = f"{path}.{os.getpid()}.tmp"
            with open(tmp, "w") as f:
                json.dump(_flight._json_safe(doc), f, default=str,
                          allow_nan=False)
            os.replace(tmp, path)
            self.writes += 1
            return path
        except Exception:  # noqa: BLE001
            _LOG.exception("metric snapshot write failed")
            return None


def read_snapshots(directory: Optional[str] = None) -> List[Dict]:
    """Every per-process snapshot under ``directory``, sorted by
    process index. Half-written or foreign files are skipped, exactly
    like the crash-bundle aggregator."""
    d = directory or _flight.bundle_dir()
    if not os.path.isdir(d):
        return []
    out = []
    for name in sorted(os.listdir(d)):
        if not (name.startswith("metrics_p") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(d, name)) as f:
                doc = json.load(f)
        except Exception:  # noqa: BLE001 — a dying peer's torn write
            continue
        if doc.get("schema") != SNAPSHOT_SCHEMA:
            continue
        doc["snapshot_file"] = name
        out.append(doc)
    out.sort(key=lambda s: s.get("process_index", 0))
    return out


def _metric_value(snap: Dict, name: str):
    m = snap.get("metrics", {}).get(name)
    if not isinstance(m, dict):
        return None
    if m.get("type") == "histogram":
        return m.get("mean")
    v = m.get("value")
    return v if isinstance(v, (int, float)) else None


#: fleet-tier latency histograms surfaced in the merged view (ISSUE
#: 19 satellite): KV-handoff wall time in the disaggregated pool and
#: controller spawn-to-register launch latency
FLEET_HISTOGRAMS = ("serve/fleet_handoff_ms", "serve/fleet_spawn_ms")


def _merge_fleet_histograms(snaps: List[Dict]) -> Dict[str, Dict]:
    """Cross-process merge of the fleet latency histograms: counts sum,
    means combine count-weighted, max is the max, and the merged "p99"
    is the worst per-process p99 (conservative — true cross-process
    quantiles would need the raw samples, which snapshots drop)."""
    out: Dict[str, Dict] = {}
    for name in FLEET_HISTOGRAMS:
        count = 0
        total = 0.0
        mx = None
        p99 = None
        for s in snaps:
            m = s.get("metrics", {}).get(name)
            if not isinstance(m, dict) or m.get("type") != "histogram":
                continue
            c = m.get("count") or 0
            if not c:
                continue
            count += c
            total += m.get("sum") or 0.0
            if isinstance(m.get("max"), (int, float)):
                mx = m["max"] if mx is None else max(mx, m["max"])
            q = (m.get("quantiles") or {}).get("0.99")
            if isinstance(q, (int, float)):
                p99 = q if p99 is None else max(p99, q)
        if count:
            out[name] = {"count": count, "mean": total / count,
                         "max": mx, "p99_worst_proc": p99}
    return out


def aggregate(directory: Optional[str] = None,
              now: Optional[float] = None) -> Optional[Dict]:
    """Merge the per-process snapshots into one cluster view:

    * per-process rows — step, mean step time, throughput, heartbeat
      age, snapshot age; a serving-fleet process's row also carries a
      trimmed ``serving`` summary (role, queue depth, inflight, active
      version) from the section its agent publishes;
    * **step-time skew** — slowest/median mean-step-time ratio across
      processes (the number that says the mesh is dragging);
    * **straggler attribution** — processes above
      ``STRAGGLER_RATIO`` × median, each joined with its heartbeat age
      (a straggler whose heartbeat is ALSO stale is dying, not slow);
    * a ``fleet`` section when any process recorded the fleet latency
      histograms (KV handoff, elastic spawn).

    Returns None when there is nothing to merge."""
    snaps = read_snapshots(directory)
    if not snaps:
        return None
    now = time.time() if now is None else now
    rows = []
    for s in snaps:
        step_time = _metric_value(s, "optim/step_time")
        hb_age = _metric_value(s, "failure/last_beat_age_s")
        row = {
            "process_index": s.get("process_index", 0),
            "pid": s.get("pid"),
            "step": s.get("step"),
            "step_time_mean_s": step_time,
            "throughput": _metric_value(s, "optim/throughput"),
            "heartbeat_age_s": hb_age,
            "snapshot_age_s": round(max(0.0, now - s.get("written_at", now)),
                                    3),
            "snapshot_file": s.get("snapshot_file"),
            "final": bool(s.get("final", False)),
        }
        serving = s.get("serving")
        if isinstance(serving, dict):
            row["serving"] = {
                k: serving.get(k) for k in
                ("role", "queue_depth", "inflight", "pending",
                 "active_version")
                if serving.get(k) is not None}
        rows.append(row)
    # finished (final:true) processes are retired, not slow: their
    # frozen means must not distort the LIVE cluster's median/skew
    # either — several fast finishers dragging the median down would
    # falsely push a healthy live process over the straggler ratio
    times = sorted(r["step_time_mean_s"] for r in rows
                   if not r["final"]
                   and isinstance(r["step_time_mean_s"], (int, float))
                   and r["step_time_mean_s"] > 0)
    skew = None
    median = None
    stragglers = []
    if times:
        import statistics
        median = statistics.median(times)
        slowest = times[-1]
        skew = slowest / median if median > 0 else None
        for r in rows:
            if r["final"]:
                # a cleanly-finished process (terminal final:true
                # snapshot) is retired, not slow: its frozen mean and
                # ever-growing heartbeat age would otherwise read as a
                # suspect-dead straggler forever (ISSUE 15 satellite)
                continue
            st = r["step_time_mean_s"]
            if isinstance(st, (int, float)) and median > 0 and \
                    st > STRAGGLER_RATIO * median:
                stragglers.append({
                    "process_index": r["process_index"],
                    "step_time_mean_s": st,
                    "vs_median": round(st / median, 3),
                    "heartbeat_age_s": r["heartbeat_age_s"],
                    "suspect_dead": isinstance(
                        r["heartbeat_age_s"], (int, float))
                    and r["heartbeat_age_s"] > STALE_HEARTBEAT_S,
                })
    view = {
        "schema": CLUSTER_SCHEMA,
        "written_at": now,
        "n_processes": len(rows),
        "step_time_median_s": median,
        "step_time_skew": round(skew, 4) if skew is not None else None,
        "stragglers": stragglers,
        "processes": rows,
    }
    fleet = _merge_fleet_histograms(snaps)
    if fleet:
        view["fleet"] = fleet
    return view


def write_aggregate(directory: Optional[str] = None,
                    out: Optional[str] = None,
                    context: Optional[Dict] = None) -> Optional[str]:
    """Rank-0 merge artifact: write the cluster view (atomic), mirror
    the headline numbers into the local registry
    (``cluster/step_time_skew``, ``cluster/stragglers``,
    ``cluster/processes``) and return the path. Never raises; None when
    there is nothing to merge."""
    try:
        view = aggregate(directory)
        if view is None:
            return None
        if context:
            view["context"] = dict(context)
        reg = _metrics.registry()
        if view["step_time_skew"] is not None:
            reg.gauge("cluster/step_time_skew").set(view["step_time_skew"])
        reg.gauge("cluster/stragglers").set(len(view["stragglers"]))
        reg.gauge("cluster/processes").set(view["n_processes"])
        d = directory or _flight.bundle_dir()
        if out is None:
            out = os.path.join(
                d, f"cluster_view_{int(view['written_at'] * 1000)}.json")
        tmp = out + ".tmp"
        with open(tmp, "w") as f:
            json.dump(_flight._json_safe(view), f, indent=1, default=str,
                      allow_nan=False)
        os.replace(tmp, out)
        return out
    except Exception:  # noqa: BLE001
        _LOG.exception("cluster aggregate failed")
        return None


def latest_aggregate(directory: Optional[str] = None) -> Optional[str]:
    d = directory or _flight.bundle_dir()
    if not os.path.isdir(d):
        return None
    views = [os.path.join(d, f) for f in os.listdir(d)
             if f.startswith("cluster_view_") and f.endswith(".json")]
    return max(views, key=os.path.getmtime) if views else None


def prometheus_cluster_text(view: Dict, prefix: str = "bigdl_cluster") \
        -> str:
    """The merged view in Prometheus text exposition format, one series
    per process labelled ``{process="<idx>"}`` — the fleet dashboard's
    scrape target."""
    lines = [f"# HELP {prefix}_step_time_mean_s per-process mean step "
             f"time (s)",
             f"# TYPE {prefix}_step_time_mean_s gauge"]
    for r in view.get("processes", []):
        idx = r.get("process_index", 0)
        for key, metric in (("step_time_mean_s", "step_time_mean_s"),
                            ("throughput", "throughput"),
                            ("heartbeat_age_s", "heartbeat_age_s"),
                            ("snapshot_age_s", "snapshot_age_s")):
            v = r.get(key)
            if isinstance(v, (int, float)):
                lines.append(
                    f'{prefix}_{metric}{{process="{idx}"}} {float(v)!r}')
    skew = view.get("step_time_skew")
    if isinstance(skew, (int, float)):
        lines.append(f"{prefix}_step_time_skew {float(skew)!r}")
    lines.append(f"{prefix}_stragglers "
                 f"{float(len(view.get('stragglers', [])))!r}")
    lines.append(f"{prefix}_processes "
                 f"{float(view.get('n_processes', 0))!r}")
    return "\n".join(lines) + "\n"


def default_writer() -> MetricSnapshotWriter:
    """A writer on the env-configured cadence — what the optimizer and
    serving hot loops tick (no-op unless ``BIGDL_TPU_METRIC_SNAP_S`` is
    set)."""
    return MetricSnapshotWriter()


# re-exported convenience for hot-loop call sites
def enabled() -> bool:
    return _trace.enabled()
