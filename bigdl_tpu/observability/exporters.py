"""Exporters: Chrome trace-event JSON, Prometheus text, TensorBoard
bridge, and the BENCH_*.json-compatible metric-line dump.

Formats:

* **Chrome trace**: the ``traceEvents`` array of complete ("ph": "X")
  events documented in the Trace Event Format spec — loads in Perfetto
  and chrome://tracing. Timestamps are microseconds relative to the
  tracer epoch (monotonic), one ``tid`` per recording thread.
* **Prometheus**: text exposition format; histograms export as
  ``summary`` (quantile labels) since reservoir quantiles, not fixed
  buckets, is what the Histogram keeps.
* **SummaryBridge**: mirrors registry values into an existing
  ``visualization.Summary`` so TensorBoard dashboards keep working with
  zero new infra.
* **metrics_dump / record_bench_line**: the ``{"metric", "value",
  "unit", ...}`` line schema bench.py has always printed — now the
  registry speaks it both ways, so bench results and runtime metrics
  share one schema.
"""
from __future__ import annotations

import json
import re
from typing import Dict, List, Optional

from .metrics import Counter, Gauge, Histogram, MetricsRegistry
from .metrics import registry as _default_registry
from .trace import Tracer, get_tracer

# ------------------------------------------------------------------ chrome

def chrome_trace(tracer: Optional[Tracer] = None,
                 process_name: str = "bigdl_tpu") -> Dict:
    """Trace-event JSON object (dict); dump with json.dump or
    :func:`write_chrome_trace`."""
    tracer = tracer or get_tracer()
    epoch = tracer.epoch_ns
    events = [{
        "name": "process_name", "ph": "M", "pid": 0, "tid": 0,
        "args": {"name": process_name},
    }]
    tids = {}
    for sp in tracer.events():
        # compact per-thread tids (thread idents are huge opaque ints)
        tid = tids.setdefault(sp.tid, len(tids))
        ev = {
            "name": sp.name,
            "cat": sp.name.split("/", 1)[0],
            "ph": "X",
            # clamp: a span that straddled a reset() started before the
            # re-stamped epoch; never emit negative timestamps
            "ts": max(0.0, (sp.start_ns - epoch) / 1e3),
            "dur": sp.duration_ns / 1e3,
            "pid": 0,
            "tid": tid,
        }
        if sp.args:
            ev["args"] = dict(sp.args)
        events.append(ev)
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"dropped_events": tracer.dropped}}


def write_chrome_trace(path: str, tracer: Optional[Tracer] = None) -> str:
    with open(path, "w") as f:
        json.dump(chrome_trace(tracer), f)
    return path


# -------------------------------------------------------------- prometheus

def _prom_name(name: str) -> str:
    """Prometheus metric charset: [a-zA-Z_:][a-zA-Z0-9_:]*."""
    out = re.sub(r"[^a-zA-Z0-9_:]", "_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def _fmt(v: float) -> str:
    if v != v:
        return "NaN"
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    return repr(float(v))


def prometheus_text(reg: Optional[MetricsRegistry] = None,
                    prefix: str = "bigdl") -> str:
    """Text exposition format. Counters keep their value as-is (callers
    count events or bytes); histograms export as proper summaries with
    ``quantile="0.5|0.9|0.99"`` labels plus _sum/_count/_min/_max. Every
    family gets a ``# HELP`` line carrying the registry name and unit.
    A live gauge whose callback raises exports NaN (and bumps
    ``obs/gauge_fn_errors``) instead of aborting the scrape."""
    reg = reg or _default_registry()
    lines: List[str] = []
    for inst in reg.instruments():
        base = _prom_name(f"{prefix}_{inst.name}" if prefix else inst.name)
        unit = f" ({inst.unit})" if inst.unit else ""
        lines.append(f"# HELP {base} {inst.name}{unit}")
        if isinstance(inst, Counter):
            lines.append(f"# TYPE {base} counter")
            lines.append(f"{base} {_fmt(inst.value)}")
        elif isinstance(inst, Gauge):
            lines.append(f"# TYPE {base} gauge")
            lines.append(f"{base} {_fmt(inst.value)}")
        elif isinstance(inst, Histogram):
            lines.append(f"# TYPE {base} summary")
            for q, v in sorted(inst.quantiles((0.5, 0.9, 0.99)).items()):
                lines.append(f'{base}{{quantile="{q}"}} {_fmt(v)}')
            lines.append(f"{base}_sum {_fmt(inst.total)}")
            lines.append(f"{base}_count {inst.count}")
            if inst.count:
                lines.append(f"{base}_min {_fmt(inst.min)}")
                lines.append(f"{base}_max {_fmt(inst.max)}")
    return "\n".join(lines) + "\n"


# ------------------------------------------------------------- tensorboard

class SummaryBridge:
    """Mirror selected registry metrics into a ``visualization.Summary``.

    ``flush(step)`` writes one scalar per counter/gauge and
    mean/p50/p99 scalars per histogram, under ``obs/<name>`` tags —
    the existing event-file reader (``Summary.read_scalar``) sees them
    like any other scalar, so TensorBoard keeps working without a new
    backend. ``metrics=None`` bridges everything; pass an iterable of
    registry names to select."""

    def __init__(self, summary, reg: Optional[MetricsRegistry] = None,
                 metrics: Optional[List[str]] = None,
                 tag_prefix: str = "obs/"):
        self.summary = summary
        self.reg = reg or _default_registry()
        self.metrics = set(metrics) if metrics is not None else None
        self.tag_prefix = tag_prefix

    def flush(self, step: int):
        n = 0
        for inst in self.reg.instruments():
            if self.metrics is not None and inst.name not in self.metrics:
                continue
            tag = self.tag_prefix + inst.name
            if isinstance(inst, Histogram):
                if not inst.count:
                    continue
                qs = inst.quantiles((0.5, 0.99))
                self.summary.add_scalar(tag + "/mean", inst.mean, step)
                self.summary.add_scalar(tag + "/p50", qs[0.5], step)
                self.summary.add_scalar(tag + "/p99", qs[0.99], step)
                n += 3
            else:
                self.summary.add_scalar(tag, inst.value, step)
                n += 1
        return n


# ------------------------------------------------------- bench-line schema

def record_bench_line(line: Dict, reg: Optional[MetricsRegistry] = None):
    """Feed one bench.py result line ({"metric", "value", "unit", ...})
    into the registry as a gauge named ``bench/<metric>``; vs_baseline
    and mfu side-values get their own gauges."""
    reg = reg or _default_registry()
    name = line.get("metric")
    if not name or not isinstance(line.get("value"), (int, float)):
        return
    reg.gauge(f"bench/{name}", unit=line.get("unit", "")).set(line["value"])
    # stale_cache rides along as a 1.0 gauge (bool is an int): a metrics
    # dump built from a cached re-serve must carry the mark, so nothing
    # downstream (perf gate, round files) can mistake it for fresh
    for extra in ("vs_baseline", "mfu", "input_wait_frac", "superstep_k",
                  "dispatches", "compile_cache_hits",
                  "compile_cache_misses", "queue_wait_p99_ms",
                  "assemble_p99_ms", "dispatch_p99_ms", "stale_cache"):
        if isinstance(line.get(extra), (int, float)):
            reg.gauge(f"bench/{name}/{extra}").set(float(line[extra]))


def metrics_dump(reg: Optional[MetricsRegistry] = None) -> List[Dict]:
    """The registry rendered as BENCH_*.json-compatible metric lines:
    one ``{"metric", "value", "unit", "kind"}`` dict per instrument
    (histograms add count/mean/p50/p99). ``bench/``-namespaced gauges
    round-trip to exactly the line bench.py printed."""
    reg = reg or _default_registry()
    out = []
    for inst in reg.instruments():
        line = {"metric": inst.name, "unit": inst.unit}
        if isinstance(inst, Histogram):
            qs = inst.quantiles((0.5, 0.99))
            line.update(kind="histogram", value=inst.mean,
                        count=inst.count, p50=qs[0.5], p99=qs[0.99])
        elif isinstance(inst, Counter):
            line.update(kind="counter", value=inst.value)
        else:
            line.update(kind="gauge", value=inst.value)
        out.append(line)
    return out


def write_metrics_dump(path: str,
                       reg: Optional[MetricsRegistry] = None) -> str:
    with open(path, "w") as f:
        json.dump(metrics_dump(reg), f, indent=1)
    return path
