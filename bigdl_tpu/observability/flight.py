"""Flight recorder: a bounded ring of recent structured events, dumped
as a JSON **crash bundle** on unhandled failure.

A remote TPU run that dies at step 48,312 leaves nothing but a
traceback; the questions an operator actually asks — what were the last
losses, which epoch/batch was in flight, had the stager stalled, what
did the metrics look like — need the state *leading up to* the crash.
The recorder keeps exactly that: a fixed-capacity ring (default 512)
of structured events that hot loops append to when observability is
enabled (one dict + deque append per event; the ring never grows), and
that :func:`dump_crash_bundle` snapshots together with the metrics
registry, the tail of the span trace, and environment provenance.

Writers: the optimizer records one ``step`` event per resolved loss
(with epoch/iteration provenance), plus ``epoch`` / ``checkpoint`` /
``nan`` markers; the serving engine records per-batch dispatch events;
every ``health/*`` event (stalls, anomalies, profiler windows) lands
here too. ``Optimizer.optimize()`` and the serving batcher dump a
bundle on unhandled failure; ``tools/flight_report.py`` renders a
bundle as a human post-mortem.

Bundle schema (``schema`` = ``bigdl_tpu.flight_bundle.v1``)::

    {
      "schema":  "bigdl_tpu.flight_bundle.v1",
      "written_at": <unix seconds>, "written_at_iso": <UTC ISO8601>,
      "pid": <int>,
      "error":   {"type", "message", "traceback"} | null,
      "context": {<caller-provided provenance: component, epoch,
                   neval, seed, ...>},
      "events":  [{"t": <unix s>, "kind": "...", ...}, ...]  # the ring
      "metrics": <MetricsRegistry.snapshot()>,
      "spans":   [{"name", "start_us", "dur_us", "tid", "args"}, ...],
      "env":     {"jax", "backend", "devices", "process_index"}
    }

Disabled observability means a disabled recorder: :func:`record`
returns after one flag read, the ring stays empty, and no bundle is
written.
"""
from __future__ import annotations

import datetime
import json
import logging
import math
import os
import tempfile
import threading
import time
import traceback as _traceback
from collections import deque
from typing import Dict, List, Optional

from . import metrics as _metrics
from . import trace as _trace

_LOG = logging.getLogger("bigdl_tpu.observability.flight")

SCHEMA = "bigdl_tpu.flight_bundle.v1"

#: spans included in a bundle (the TAIL of the trace — most recent)
BUNDLE_SPAN_TAIL = 64


class FlightRecorder:
    """Fixed-capacity ring of structured events (thread-safe)."""

    def __init__(self, capacity: int = 512):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._ring: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._total = 0

    def record(self, kind: str, **fields):
        """Append one event (hot path: gated on the observability flag
        BEFORE building the dict — disabled cost is one flag read)."""
        if not _trace.enabled():
            return
        ev = {"t": time.time(), "kind": kind}
        ev.update(fields)
        with self._lock:
            self._ring.append(ev)
            self._total += 1

    def events(self) -> List[Dict]:
        with self._lock:
            return list(self._ring)

    @property
    def total_recorded(self) -> int:
        """Events ever recorded (>= len(events()) once the ring wraps)."""
        with self._lock:
            return self._total

    def clear(self):
        with self._lock:
            self._ring.clear()
            self._total = 0


_recorder = FlightRecorder()


def recorder() -> FlightRecorder:
    return _recorder


def record(kind: str, **fields):
    """Module-level hot-path entry: append to the process recorder."""
    _recorder.record(kind, **fields)


def reset():
    _recorder.clear()


def _env_info() -> Dict:
    try:
        import jax
        return {"jax": jax.__version__,
                "backend": jax.default_backend(),
                "devices": jax.device_count(),
                "process_index": jax.process_index()}
    except Exception as e:  # post-mortem must work even if jax is wedged
        return {"error": f"{type(e).__name__}: {e}"}


def _span_tail(n: int = BUNDLE_SPAN_TAIL) -> List[Dict]:
    tracer = _trace.get_tracer()
    epoch = tracer.epoch_ns
    out = []
    for sp in tracer.events()[-n:]:
        ev = {"name": sp.name,
              "start_us": max(0.0, (sp.start_ns - epoch) / 1e3),
              "dur_us": sp.duration_ns / 1e3,
              "tid": sp.tid}
        if sp.args:
            ev["args"] = dict(sp.args)
        out.append(ev)
    return out


def crash_bundle(error: Optional[BaseException] = None,
                 context: Optional[Dict] = None) -> Dict:
    """Assemble the post-mortem dict (see module docstring for the
    schema). Pure snapshot — no file IO; :func:`dump_crash_bundle`
    writes it."""
    err = None
    if error is not None:
        err = {"type": type(error).__name__,
               "message": str(error),
               "traceback": "".join(_traceback.format_exception(
                   type(error), error, error.__traceback__))}
    now = time.time()
    try:  # compiled-program provenance (optional key — absent pre-PR-7
        # bundles and degraded environments stay schema-valid)
        from . import perf as _perf
        programs = _perf.artifacts_snapshot()
    except Exception:  # noqa: BLE001 — the post-mortem must still land
        programs = []
    return {
        "schema": SCHEMA,
        "written_at": now,
        "written_at_iso": datetime.datetime.fromtimestamp(
            now, datetime.timezone.utc).isoformat(),
        "pid": os.getpid(),
        "error": err,
        "context": dict(context or {}),
        "events": _recorder.events(),
        "metrics": _metrics.registry().snapshot(),
        "spans": _span_tail(),
        "programs": programs,
        "env": _env_info(),
    }


def bundle_dir() -> str:
    """Where bundles land: ``BIGDL_TPU_FLIGHT_DIR`` or a per-user temp
    subdirectory (never the CWD — a crash must not litter a checkout)."""
    return (os.environ.get("BIGDL_TPU_FLIGHT_DIR")
            or os.path.join(tempfile.gettempdir(), "bigdl_tpu_flight"))


def _json_safe(obj):
    """Recursively replace non-finite floats with their string names.
    A NaN post-mortem is the recorder's headline use case, and
    ``json.dump``'s default emits bare ``NaN``/``Infinity`` tokens —
    Python reads those back but jq / JSON.parse / strict parsers reject
    the whole bundle, which is exactly where a REMOTE bundle gets
    inspected."""
    if isinstance(obj, float):
        if math.isfinite(obj):
            return obj
        return "NaN" if obj != obj else \
            ("Infinity" if obj > 0 else "-Infinity")
    if isinstance(obj, dict):
        return {k: _json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_json_safe(v) for v in obj]
    return obj


def dump_crash_bundle(error: Optional[BaseException] = None,
                      context: Optional[Dict] = None,
                      path: Optional[str] = None) -> Optional[str]:
    """Write the crash bundle as strict JSON (atomic tmp+rename) and
    return its path. NEVER raises — the post-mortem writer must not
    mask the crash it is documenting (failures are logged and return
    None)."""
    try:
        bundle = _json_safe(crash_bundle(error, context))
        if path is None:
            d = bundle_dir()
            os.makedirs(d, exist_ok=True)
            path = os.path.join(
                d, f"flight_{os.getpid()}_{int(time.time() * 1000)}.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            # default=str: span args / context may carry numpy scalars,
            # shapes, device reprs — a post-mortem keeps them as text
            # rather than refusing to serialize. allow_nan=False backs
            # the strict-JSON guarantee (_json_safe already replaced
            # every non-finite float this module produces).
            json.dump(bundle, f, indent=1, default=str, allow_nan=False)
        os.replace(tmp, path)
        _LOG.warning("crash bundle written: %s (%d events)", path,
                     len(bundle["events"]))
        return path
    except Exception:
        _LOG.exception("failed to write crash bundle")
        return None


AGGREGATE_SCHEMA = "bigdl_tpu.flight_aggregate.v1"


def aggregate_bundles(directory: Optional[str] = None,
                      out: Optional[str] = None) -> Optional[str]:
    """Merge every per-process crash bundle under ``directory`` (default
    :func:`bundle_dir`) into ONE rank-0 post-mortem artifact and return
    its path. In a multi-host failure each process dumps its own bundle
    into the (shared) flight dir; the elastic restarter calls this on
    process 0 before resuming, so the operator triages a single file —
    bundles sorted by (process_index, written_at), the newest error per
    process surfaced in a ``summary`` header. Never raises; returns
    None when there is nothing to aggregate. Each aggregate covers only
    bundles NEWER than the previous aggregate (and aggregates of
    aggregates are skipped): repeated elastic restarts on a shared
    flight dir each get a post-mortem of THEIR failure, not an
    ever-growing re-embedding of every failure before it."""
    try:
        d = directory or bundle_dir()
        if not os.path.isdir(d):
            return None
        last_agg = 0.0  # watermark: newest existing aggregate
        for name in os.listdir(d):
            if name.startswith("flight_aggregate") and \
                    name.endswith(".json"):
                try:
                    last_agg = max(last_agg, float(
                        name.rsplit("_", 1)[1].split(".")[0]) / 1000.0)
                except (IndexError, ValueError):
                    pass
        bundles = []
        for name in sorted(os.listdir(d)):
            if not (name.startswith("flight_") and name.endswith(".json")) \
                    or name.startswith("flight_aggregate"):
                continue
            try:
                with open(os.path.join(d, name)) as f:
                    b = json.load(f)
            except Exception:
                continue  # half-written by a dying peer — skip, don't die
            if b.get("written_at", 0) <= last_agg:
                continue  # already folded into an earlier post-mortem
            if b.get("schema", "").startswith("bigdl_tpu.flight_bundle"):
                b["bundle_file"] = name
                bundles.append(b)
        if not bundles:
            return None
        bundles.sort(key=lambda b: (b.get("env", {}).get("process_index", 0),
                                    b.get("written_at", 0)))
        summary = []
        for b in bundles:
            err = b.get("error") or {}
            summary.append({
                "process_index": b.get("env", {}).get("process_index"),
                "pid": b.get("pid"),
                "bundle_file": b.get("bundle_file"),
                "error_type": err.get("type"),
                "error_message": err.get("message"),
                "context": b.get("context", {}),
            })
        now = time.time()
        agg = {"schema": AGGREGATE_SCHEMA, "written_at": now,
               "written_at_iso": datetime.datetime.fromtimestamp(
                   now, datetime.timezone.utc).isoformat(),
               "n_bundles": len(bundles), "summary": summary,
               "bundles": bundles}
        if out is None:
            out = os.path.join(d, f"flight_aggregate_{int(now * 1000)}.json")
        tmp = out + ".tmp"
        with open(tmp, "w") as f:
            json.dump(_json_safe(agg), f, indent=1, default=str,
                      allow_nan=False)
        os.replace(tmp, out)
        _LOG.warning("aggregated %d crash bundles into %s",
                     len(bundles), out)
        return out
    except Exception:
        _LOG.exception("failed to aggregate crash bundles")
        return None
