"""Health layer: is the system ALIVE, not just how fast is it.

PR 1's tracer/metrics tell an operator where the time goes; nothing
tells them whether anything is still happening. A hung stager thread, a
NaN streak, an HBM leak, or a serving batcher wedged mid-dispatch all
present today as "no output" — on a remote TPU tunnel that is
indistinguishable from a slow step until someone attaches a debugger.
This module turns those silences into structured, typed events:

* **Stall watchdog** — long-running components (the optimizer step
  loop, the :class:`~bigdl_tpu.optim.staging.BatchStager` worker, the
  serving batcher, the heartbeat prober) register progress
  :class:`Beacon` s and ``pulse()`` them as they make progress. One
  monitor thread watches every beacon; a beacon quiet past its deadline
  fires a ``health/stall`` event (instant span + ``health/stall``
  counter + flight-recorder entry + optional callback), once, and
  re-arms when progress resumes (``health/stall_recovered``).
* **Anomaly detectors** — :class:`SeriesMonitor` watches a host scalar
  series the loop ALREADY syncs (the per-step loss; grad norms if a
  caller syncs them) and flags spikes (``health/loss_spike``: value
  beyond ``spike_sigma`` rolling deviations), plateaus
  (``health/plateau``: no relative improvement for ``plateau_window``
  steps) and NaN/Inf streaks (``health/nan_streak``) with step
  provenance. Zero extra readbacks: it consumes the float the sync
  policy resolved anyway, including the superstep ``[K]`` vector replay.
* **Device-memory telemetry** — live gauges ``mem/device_live_bytes``
  / ``mem/device_peak_bytes`` computed at export-read time from
  ``device.memory_stats()``; backends without memory stats (jaxlib CPU)
  degrade gracefully: the gauges are simply never registered.
* **Profiler windows** — ``BIGDL_TPU_PROFILE=start:stop`` (step
  numbers) arms a :class:`ProfilerWindow`: the optimizer ticks it per
  step and it brackets ``jax.profiler`` start/stop around that step
  range, emitting ``health/profile_start``/``health/profile_stop``
  instants so the profile correlates to span timelines.

Everything is gated on ``observability.enabled()`` at registration
time: :func:`beacon` returns a shared no-op when disabled, so the hot
loops keep one attribute call and nothing else.
"""
from __future__ import annotations

import logging
import math
import os
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from . import flight
from . import metrics as _metrics
from . import trace as _trace

_LOG = logging.getLogger("bigdl_tpu.observability.health")

WATCHDOG_THREAD_NAME = "bigdl_tpu-health-watchdog"

#: registered event listeners: each is called with the event dict
listeners: List[Callable[[Dict], None]] = []


def default_stall_deadline() -> float:
    """Seconds of beacon silence before a stall fires when the caller
    does not pass a deadline. ``BIGDL_TPU_STALL_S`` overrides (a slow
    remote compile can legitimately silence a loop for minutes);
    ``BIGDL_TPU_STALL_S=0`` disables the watchdog entirely
    (:func:`beacon` returns the no-op beacon for non-positive
    deadlines)."""
    try:
        return float(os.environ.get("BIGDL_TPU_STALL_S", "600"))
    except ValueError:
        return 600.0


def emit(kind: str, **fields) -> Dict:
    """One structured health event, fanned out to every sink: an
    ``health/<kind>`` instant span (visible on the trace timeline), a
    ``health/<kind>`` counter, a flight-recorder entry, and the
    registered :data:`listeners`. Returns the event dict (also when
    observability is disabled — unit tests inspect it; the sinks are
    only written when enabled)."""
    event = {"kind": f"health/{kind}"}
    event.update(fields)
    if _trace.enabled():
        _trace.instant(f"health/{kind}", **fields)
        _metrics.counter(f"health/{kind}").inc()
        flight.record(f"health/{kind}", **fields)
    for fn in list(listeners):
        try:
            fn(event)
        except Exception:  # a broken listener must not break the loop
            _LOG.exception("health listener failed for %s", event["kind"])
    return event


class listen:
    """Scoped health-event listener: ``with listen(fn):`` registers
    ``fn`` with :data:`listeners` for the block and ALWAYS unregisters
    on exit, so a finished consumer's hook never outlives it. For
    observing events fired by other components (the watchdog thread's
    ``health/stall``, a peer's ``health/straggler``) in tests and
    external supervisors; the in-process remediation policy gets its
    signals directly (the beacon's stall callback, the event lists
    ``SeriesMonitor.observe`` returns), not through listeners."""

    def __init__(self, fn: Callable[[Dict], None]):
        self._fn = fn

    def __enter__(self):
        listeners.append(self._fn)
        return self._fn

    def __exit__(self, exc_type, exc, tb):
        try:
            listeners.remove(self._fn)
        except ValueError:
            pass  # reset() cleared the registry mid-scope
        return False


# ---------------------------------------------------------------- watchdog

class Beacon:
    """One component's progress signal. ``pulse()`` is the hot-path
    call: a monotonic clock read and two attribute writes — no lock, no
    allocation (the watchdog thread reads the timestamp racily, which
    is fine: a torn read is at worst one check interval of slack)."""

    __slots__ = ("name", "deadline_s", "on_stall", "_last_pulse",
                 "_pulses", "_stalled", "_rearmed")

    def __init__(self, name: str, deadline_s: float,
                 on_stall: Optional[Callable[["Beacon", float], None]] = None):
        if deadline_s <= 0:
            raise ValueError(f"deadline_s must be > 0, got {deadline_s}")
        self.name = name
        self.deadline_s = float(deadline_s)
        self.on_stall = on_stall
        self._last_pulse = time.monotonic()
        self._pulses = 0
        self._stalled = False
        self._rearmed = False

    def pulse(self):
        """Record progress (hot path — cheap and lock-free)."""
        self._last_pulse = time.monotonic()
        self._pulses += 1
        if self._stalled or self._rearmed:
            # close the episode: every health/stall (including re-armed
            # re-probes) pairs with exactly one stall_recovered
            self._stalled = self._rearmed = False
            emit("stall_recovered", component=self.name,
                 pulses=self._pulses)

    def rearm(self):
        """Reset the stall latch WITHOUT claiming progress: no pulse is
        counted, but the age clock restarts so the NEXT silent deadline
        emits a fresh ``health/stall`` (and re-runs ``on_stall``). For
        stall handlers that classified an episode as transient and must
        be called again if it persists — a wedged component will never
        pulse its own latch clear, and the monitor skips latched
        beacons. The episode stays OPEN: real progress later still
        emits the paired ``stall_recovered``."""
        self._last_pulse = time.monotonic()
        self._stalled = False
        self._rearmed = True

    @property
    def age_s(self) -> float:
        return time.monotonic() - self._last_pulse

    @property
    def pulses(self) -> int:
        return self._pulses

    @property
    def stalled(self) -> bool:
        return self._stalled

    def close(self):
        """Unregister from the watchdog (idempotent). A finished loop's
        beacon must not page on a run that simply ended."""
        _watchdog.unregister(self)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False

    def __repr__(self):
        return (f"Beacon({self.name!r}, deadline={self.deadline_s}s, "
                f"pulses={self._pulses}, stalled={self._stalled})")


class _NullBeacon:
    """Shared no-op beacon for the disabled path (mirrors trace's
    ``_NULL_SPAN`` pattern: hot loops keep the calls inline)."""

    __slots__ = ()
    name = "<null>"
    deadline_s = float("inf")
    age_s = 0.0
    pulses = 0
    stalled = False

    def pulse(self):
        return None

    def rearm(self):
        return None

    def close(self):
        return None

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False


NULL_BEACON = _NullBeacon()


class Watchdog:
    """One monitor thread over every registered beacon. The check
    interval adapts to the tightest deadline (deadline/4, clamped to
    [20ms, 5s]) so a test's 200ms deadline and a production run's
    10-minute one are both detected within ~1.25x their deadline. The
    thread starts with the first beacon and exits when the last one
    closes — no idle daemon outlives a run."""

    def __init__(self):
        self._beacons: set = set()
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._wake = threading.Event()

    def register(self, beacon: Beacon):
        with self._lock:
            self._beacons.add(beacon)
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, name=WATCHDOG_THREAD_NAME, daemon=True)
                self._thread.start()
        self._wake.set()

    def unregister(self, beacon: Beacon):
        with self._lock:
            self._beacons.discard(beacon)
            drained = not self._beacons
        if drained:
            self._wake.set()  # exit promptly — don't sleep out the poll

    def beacons(self) -> List[Beacon]:
        with self._lock:
            return list(self._beacons)

    def poke(self):
        """Wake the monitor thread so it recomputes its check interval
        now — callers that TIGHTEN a live beacon's deadline (the step
        loop dropping its startup compile grace) use this so detection
        latency follows the new deadline, not the old poll cadence."""
        self._wake.set()

    def reset(self):
        """Drop every beacon (tests); the monitor thread then exits on
        its next wakeup."""
        with self._lock:
            self._beacons.clear()
        self._wake.set()

    def _run(self):
        while True:
            with self._lock:
                if not self._beacons:
                    self._thread = None
                    return
                beacons = list(self._beacons)
            interval = min(b.deadline_s for b in beacons) / 4.0
            interval = min(max(interval, 0.02), 5.0)
            for b in beacons:
                if b._stalled:
                    continue
                age = b.age_s
                if age > b.deadline_s:
                    b._stalled = True
                    emit("stall", component=b.name, age_s=round(age, 3),
                         deadline_s=b.deadline_s, pulses=b._pulses)
                    if b.on_stall is not None:
                        try:
                            b.on_stall(b, age)
                        except Exception:
                            _LOG.exception(
                                "on_stall callback failed for %s", b.name)
            self._wake.wait(interval)
            self._wake.clear()


_watchdog = Watchdog()


def watchdog() -> Watchdog:
    return _watchdog


def beacon(name: str, deadline_s: Optional[float] = None,
           on_stall: Optional[Callable] = None):
    """Register a progress beacon with the process watchdog. Returns
    the shared no-op beacon when observability is disabled — or when
    the effective deadline is non-positive (``BIGDL_TPU_STALL_S=0``,
    the documented watchdog off-switch) — so hot loops call
    ``beacon.pulse()`` unconditionally at zero cost."""
    if not _trace.enabled():
        return NULL_BEACON
    deadline = (deadline_s if deadline_s is not None
                else default_stall_deadline())
    if deadline <= 0:
        return NULL_BEACON
    b = Beacon(name, deadline, on_stall)
    _watchdog.register(b)
    return b


def watchdog_threads_alive() -> int:
    """Live watchdog monitor threads (tests assert 0 after shutdown)."""
    return sum(1 for t in threading.enumerate()
               if t.name == WATCHDOG_THREAD_NAME and t.is_alive())


# ------------------------------------------------------ anomaly detectors

class SeriesMonitor:
    """Rolling anomaly detector over an already-synced scalar series.

    Fed host floats the loop resolved anyway (loss via the sync policy,
    grad norm if a caller syncs one) — this class never touches a
    device array, so it adds no readbacks. Detection rules:

    * **NaN/Inf streak**: ``nan_streak`` consecutive non-finite values
      fire ``health/nan_streak`` once (re-armed by a finite value). A
      single NaN under ``nan_policy='skip'`` is routine; a streak means
      the run is diverging.
    * **Spike**: a finite value beyond ``mean + spike_sigma * std`` of
      the rolling window (after ``min_points`` observations) fires
      ``health/loss_spike`` — loss explosions and data poisoning both
      look like this.
    * **Plateau**: no relative improvement of at least ``plateau_rel``
      over the best value for ``plateau_window`` steps fires
      ``health/plateau`` — recurring, once per FULL stale window (a
      flat run keeps reporting every ``plateau_window`` steps; a new
      best resets the clock) — the signal an LR schedule or an
      early-stop/plateau-counting policy wants.

    Running mean/variance are maintained incrementally (O(1) per
    observation) over a bounded window, so a million-step run costs the
    same as a hundred-step one.
    """

    def __init__(self, name: str = "loss", window: int = 64,
                 spike_sigma: float = 8.0, min_points: int = 16,
                 plateau_window: int = 200, plateau_rel: float = 1e-3,
                 nan_streak: int = 3):
        if window < 2:
            raise ValueError(f"window must be >= 2, got {window}")
        self.name = name
        self.window = window
        self.spike_sigma = float(spike_sigma)
        self.min_points = max(2, int(min_points))
        self.plateau_window = int(plateau_window)
        self.plateau_rel = float(plateau_rel)
        self.nan_streak = int(nan_streak)
        self._vals: deque = deque(maxlen=window)
        self._sum = 0.0
        self._sumsq = 0.0
        self._streak = 0
        self._best = math.inf
        self._best_step: Optional[int] = None
        self._plateau_step = None  # step of the last plateau event

    def observe(self, value, step: int) -> List[Dict]:
        """Feed one already-resolved host scalar; returns the health
        events it fired (also emitted through :func:`emit`)."""
        events = []
        if not math.isfinite(value):
            self._streak += 1
            if self._streak == self.nan_streak:
                events.append(emit(
                    "nan_streak", monitor=self.name, step=step,
                    streak=self._streak))
            return events
        if self._streak:
            self._streak = 0
        n = len(self._vals)
        if n >= self.min_points:
            mean = self._sum / n
            var = max(self._sumsq / n - mean * mean, 0.0)
            std = math.sqrt(var)
            if std > 0.0 and value > mean + self.spike_sigma * std:
                events.append(emit(
                    f"{self.name}_spike", monitor=self.name, step=step,
                    value=value, mean=round(mean, 6), std=round(std, 6),
                    sigma=round((value - mean) / std, 2)))
        if (self._best_step is None
                or value < self._best - abs(self._best) * self.plateau_rel):
            self._best = value
            self._best_step = step
            self._plateau_step = None
        else:
            # recurring, one event per FULL stale window (never per
            # step): consumers that count plateaus — repeated LR cuts,
            # RemediationPolicy.early_stop_plateaus — need a flat run to
            # keep reporting, and a one-shot detector could never reach
            # a count of 2 without an improvement in between
            anchor = (self._plateau_step if self._plateau_step is not None
                      else self._best_step)
            if step - anchor >= self.plateau_window:
                self._plateau_step = step
                events.append(emit(
                    "plateau", monitor=self.name, step=step,
                    best=self._best, best_step=self._best_step,
                    stale_steps=step - self._best_step))
        if n == self._vals.maxlen:
            old = self._vals[0]
            self._sum -= old
            self._sumsq -= old * old
        self._vals.append(value)
        self._sum += value
        self._sumsq += value * value
        return events


# ------------------------------------------------- device-memory telemetry

_mem_available: Optional[bool] = None  # None = not probed yet


def _device_memory_stats():
    """Per-device ``memory_stats()`` dicts, or None when the backend
    lacks them (missing method, raises, or returns None — jaxlib CPU)."""
    import jax
    out = []
    for d in jax.local_devices():
        fn = getattr(d, "memory_stats", None)
        if fn is None:
            return None
        try:
            st = fn()
        except Exception:
            return None
        if not isinstance(st, dict) or "bytes_in_use" not in st:
            return None
        out.append(st)
    return out or None


def memory_stats_available() -> bool:
    """Probe once whether the backend reports device memory."""
    global _mem_available
    if _mem_available is None:
        try:
            _mem_available = _device_memory_stats() is not None
        except Exception:
            _mem_available = False
    return _mem_available


def sample_device_memory() -> Optional[Dict[str, float]]:
    """One aggregate sample across local devices:
    ``{"live_bytes", "peak_bytes", "devices"}`` — or None when the
    backend has no memory stats."""
    if not memory_stats_available():
        return None
    stats = _device_memory_stats()
    if stats is None:
        return None
    return {
        "live_bytes": float(sum(s.get("bytes_in_use", 0) for s in stats)),
        "peak_bytes": float(sum(
            s.get("peak_bytes_in_use", s.get("bytes_in_use", 0))
            for s in stats)),
        "devices": float(len(stats)),
    }


def ensure_memory_telemetry() -> bool:
    """Register ``mem/device_live_bytes`` / ``mem/device_peak_bytes``
    as LIVE gauges (computed at export-read time — an exporter scraping
    a hung loop still sees current HBM numbers). Returns whether the
    backend supports it; no gauges are registered when it does not, so
    dashboards never show a dead-zero memory row."""
    if not memory_stats_available():
        return False
    reg = _metrics.registry()
    if reg.get("mem/device_live_bytes") is not None:
        return True

    def live() -> float:
        s = sample_device_memory()
        return s["live_bytes"] if s else float("nan")

    def peak() -> float:
        s = sample_device_memory()
        return s["peak_bytes"] if s else float("nan")

    reg.gauge("mem/device_live_bytes", unit="bytes").set_fn(live)
    reg.gauge("mem/device_peak_bytes", unit="bytes").set_fn(peak)
    return True


# ------------------------------------------------------- profiler windows

class ProfilerWindow:
    """Bracket ``jax.profiler`` start/stop around a step range.

    The optimizer ticks this once per step (host-side counter compare —
    no sync); the window starts the trace when ``step >= start_step``
    and stops it when ``step >= stop_step``, emitting
    ``health/profile_start`` / ``health/profile_stop`` instants with
    the step number so the device profile correlates to the span
    timeline. Profiler failures (missing plugin, unwritable dir) are
    logged once and disable the window — they never kill training."""

    def __init__(self, start_step: int, stop_step: int, out_dir: str):
        if stop_step <= start_step:
            raise ValueError(
                f"profiler window needs start < stop, got "
                f"{start_step}:{stop_step}")
        self.start_step = int(start_step)
        self.stop_step = int(stop_step)
        self.out_dir = out_dir
        self.active = False
        self.failed = False
        self.done = False

    def maybe_tick(self, step: int):
        """Hot-path tick: two int compares when idle. Ticks arrive at
        step-loop granularity — superstep fusion ticks only at
        superstep boundaries — so a window narrower than the tick
        stride can be jumped over entirely; that is reported loudly
        (warning + ``health/profile_skipped``), never silently."""
        if self.failed or self.done:
            return
        if not self.active:
            if step >= self.stop_step:
                self.done = True
                _LOG.warning(
                    "profiler window %d:%d skipped — the step counter "
                    "jumped to %d without entering it (window narrower "
                    "than the superstep/tick stride?)",
                    self.start_step, self.stop_step, step)
                emit("profile_skipped", step=step,
                     start_step=self.start_step, stop_step=self.stop_step)
            elif step >= self.start_step:
                self._start(step)
        elif step >= self.stop_step:
            self._stop(step)

    def _start(self, step: int):
        try:
            import jax
            os.makedirs(self.out_dir, exist_ok=True)
            jax.profiler.start_trace(self.out_dir)
        except Exception as e:
            self.failed = True
            _LOG.warning("profiler window disabled: start_trace failed: %s",
                         e)
            return
        self.active = True
        emit("profile_start", step=step, dir=self.out_dir,
             stop_step=self.stop_step)

    def _stop(self, step: int):
        try:
            import jax
            jax.profiler.stop_trace()
        except Exception as e:
            self.failed = True
            _LOG.warning("profiler window: stop_trace failed: %s", e)
            return
        finally:
            self.active = False
            self.done = True
        emit("profile_stop", step=step, dir=self.out_dir)

    def close(self):
        """Stop a still-open trace (run ended inside the window)."""
        if self.active:
            self._stop(self.stop_step)


def profiler_window_from_env(env=None) -> Optional[ProfilerWindow]:
    """Parse ``BIGDL_TPU_PROFILE=start:stop`` (global step numbers) and
    ``BIGDL_TPU_PROFILE_DIR`` (default ``/tmp/bigdl_tpu_profile``) into
    a :class:`ProfilerWindow`; None when unset or malformed (malformed
    specs log a warning rather than killing the run)."""
    env = env if env is not None else os.environ
    spec = env.get("BIGDL_TPU_PROFILE")
    if not spec:
        return None
    try:
        start_s, stop_s = spec.split(":", 1)
        window = ProfilerWindow(
            int(start_s), int(stop_s),
            env.get("BIGDL_TPU_PROFILE_DIR", "/tmp/bigdl_tpu_profile"))
    except (ValueError, TypeError) as e:
        _LOG.warning("ignoring malformed BIGDL_TPU_PROFILE=%r (%s); "
                     "expected start:stop step numbers", spec, e)
        return None
    return window


def reset():
    """Test hook: drop every beacon (stops the watchdog thread), clear
    listeners, and forget the memory-stats probe."""
    global _mem_available
    _watchdog.reset()
    del listeners[:]
    _mem_available = None
