"""Process-global metrics registry: counters, gauges, histograms.

Instruments are cheap plain-Python objects; the registry is the single
place exporters look. Names are slash-namespaced
(``optim/step_time``, ``collective/psum_bytes``) — the Prometheus
exporter sanitizes them to its charset.

Call-sites guard writes with ``observability.enabled()``; the
instruments themselves do not check the flag (so tests and the bench
pipeline can record through the registry unconditionally when they mean
to).
"""
from __future__ import annotations

import random
import threading
import zlib
from typing import Callable, Dict, List, Optional


class Counter:
    """Monotonic accumulator (events, bytes)."""

    __slots__ = ("name", "unit", "value")

    def __init__(self, name: str, unit: str = ""):
        self.name = name
        self.unit = unit
        self.value = 0.0

    def inc(self, n: float = 1.0):
        self.value += n
        return self


class Gauge:
    """Last-write-wins point-in-time value (queue depth, throughput) — or,
    via :meth:`set_fn`, a value computed at READ time (heartbeat age: the
    number must keep growing while the loop that would have updated it is
    hung, which a write-time gauge cannot do)."""

    __slots__ = ("name", "unit", "_value", "_fn")

    def __init__(self, name: str, unit: str = ""):
        self.name = name
        self.unit = unit
        self._value = 0.0
        self._fn: Optional[Callable[[], float]] = None

    def set(self, v: float):
        self._fn = None
        self._value = float(v)
        return self

    def set_fn(self, fn: Callable[[], float]):
        """Make the gauge live: exporters call ``fn()`` at read time."""
        self._fn = fn
        return self

    @property
    def value(self) -> float:
        if self._fn is not None:
            try:
                return float(self._fn())
            except Exception:
                # a raising callback must not break snapshot()/
                # prometheus_text for every OTHER instrument: this gauge
                # reads NaN (distinguishable from any real value), the
                # failure is counted, and the export proceeds. The error
                # counter lives in the DEFAULT registry regardless of
                # which registry owns the gauge — one place to alert on.
                _registry.counter("obs/gauge_fn_errors").inc()
                return float("nan")
        return self._value


class Histogram:
    """Streaming distribution with reservoir-sampled quantiles.

    Exact count/sum/min/max; quantiles come from a fixed-size uniform
    reservoir (Vitter's algorithm R) so a million-step run costs the
    same memory as a hundred-step one. The reservoir RNG is seeded per
    instrument for reproducible tests.
    """

    __slots__ = ("name", "unit", "count", "total", "min", "max",
                 "_reservoir", "_cap", "_rng", "_lock")

    def __init__(self, name: str, unit: str = "", reservoir_size: int = 1024):
        self.name = name
        self.unit = unit
        self.count = 0
        self.total = 0.0
        self.min = float("inf")
        self.max = float("-inf")
        self._cap = reservoir_size
        self._reservoir: List[float] = []
        # crc32, not hash(): PYTHONHASHSEED randomizes str hashes per
        # process, and the seed must be stable across runs
        self._rng = random.Random(zlib.crc32(name.encode()))
        self._lock = threading.Lock()

    def observe(self, v: float):
        v = float(v)
        with self._lock:
            self.count += 1
            self.total += v
            if v < self.min:
                self.min = v
            if v > self.max:
                self.max = v
            if len(self._reservoir) < self._cap:
                self._reservoir.append(v)
            else:
                j = self._rng.randrange(self.count)
                if j < self._cap:
                    self._reservoir[j] = v
        return self

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    def quantile(self, q: float) -> float:
        """Nearest-rank quantile over the reservoir (0 when empty)."""
        with self._lock:
            if not self._reservoir:
                return 0.0
            s = sorted(self._reservoir)
        idx = min(len(s) - 1, max(0, int(q * len(s))))
        return s[idx]

    def quantiles(self, qs=(0.5, 0.9, 0.99)) -> Dict[float, float]:
        with self._lock:
            s = sorted(self._reservoir)
        if not s:
            return {q: 0.0 for q in qs}
        return {q: s[min(len(s) - 1, max(0, int(q * len(s))))] for q in qs}


class MetricsRegistry:
    """Name → instrument, typed getters, one lock around creation."""

    def __init__(self):
        self._instruments: Dict[str, object] = {}
        self._lock = threading.Lock()

    def _get(self, name: str, cls, **kw):
        inst = self._instruments.get(name)
        if inst is None:
            with self._lock:
                inst = self._instruments.get(name)
                if inst is None:
                    inst = cls(name, **kw)
                    self._instruments[name] = inst
        if not isinstance(inst, cls):
            raise TypeError(
                f"metric {name!r} already registered as "
                f"{type(inst).__name__}, requested {cls.__name__}")
        return inst

    def counter(self, name: str, unit: str = "") -> Counter:
        return self._get(name, Counter, unit=unit)

    def gauge(self, name: str, unit: str = "") -> Gauge:
        return self._get(name, Gauge, unit=unit)

    def histogram(self, name: str, unit: str = "",
                  reservoir_size: int = 1024) -> Histogram:
        return self._get(name, Histogram, unit=unit,
                         reservoir_size=reservoir_size)

    def get(self, name: str):
        return self._instruments.get(name)

    def names(self) -> List[str]:
        return sorted(self._instruments)

    def instruments(self) -> List[object]:
        with self._lock:
            return [self._instruments[n] for n in sorted(self._instruments)]

    def reset(self):
        with self._lock:
            self._instruments.clear()

    def snapshot(self) -> Dict[str, Dict]:
        """Plain-dict view, one entry per instrument (for logs / JSON)."""
        out = {}
        for inst in self.instruments():
            if isinstance(inst, Histogram):
                out[inst.name] = {
                    "type": "histogram", "unit": inst.unit,
                    "count": inst.count, "sum": inst.total,
                    "mean": inst.mean,
                    "min": inst.min if inst.count else 0.0,
                    "max": inst.max if inst.count else 0.0,
                    "quantiles": {str(q): v
                                  for q, v in inst.quantiles().items()},
                }
            elif isinstance(inst, Counter):
                out[inst.name] = {"type": "counter", "unit": inst.unit,
                                  "value": inst.value}
            else:
                out[inst.name] = {"type": "gauge", "unit": inst.unit,
                                  "value": inst.value}
        return out


_registry = MetricsRegistry()


def registry() -> MetricsRegistry:
    return _registry


def counter(name: str, unit: str = "") -> Counter:
    return _registry.counter(name, unit)


def gauge(name: str, unit: str = "") -> Gauge:
    return _registry.gauge(name, unit)


def histogram(name: str, unit: str = "") -> Histogram:
    return _registry.histogram(name, unit)
