"""Performance introspection: what the compiled programs COST.

The span/health layers say *that* a step is slow; this module says *how
far from the hardware ceiling* it is. Three pieces:

* **Compiled-program analytics** — every compile site (the optimizer's
  train step, the evaluator/predictor forwards, the serving warmup
  buckets) routes its ``jax.jit`` through :class:`InstrumentedJit`,
  which AOT-lowers and compiles each distinct input-shape signature
  explicitly and records a :class:`CompiledArtifact` into the process
  :class:`ArtifactRegistry`: XLA's own ``cost_analysis()`` FLOPs /
  bytes-accessed, ``memory_analysis()`` argument/output/temp bytes,
  compile wall time, input shapes, and compile-cache provenance
  (hit/miss deltas from the persistent-cache counters). Backends whose
  executables lack the analysis APIs degrade to a shape-and-timing-only
  artifact — never an error. ``tools/xla_report.py`` renders the
  registry (per-program table + HBM headroom).
* **Live MFU** — :func:`note_step` divides the artifact's model FLOPs
  by the step wall time the loop already measures and by the device's
  peak FLOP/s (:func:`peak_flops`, env-overridable with
  ``BIGDL_TPU_PEAK_FLOPS``), publishing ``perf/mfu`` (last dispatch),
  ``perf/mfu_mean`` (run-cumulative), ``perf/model_flops_per_s`` and a
  host-vs-dispatch-vs-device step-phase decomposition
  (``perf/phase_*_frac``) from the phase times the spans already
  stamp. Pure host-side arithmetic on numbers the loop already has —
  zero new device readbacks, ``check_no_sync`` clean.
* **Artifact export** — :func:`dump_artifacts` writes the registry (+
  the ``mem/*`` gauges for headroom context) as strict JSON next to
  the flight bundles, which is what ``tools/xla_report.py`` and the
  crash bundle consume.

Import discipline: like the rest of ``bigdl_tpu.observability`` this
module is stdlib-only at import time (the bench parent loads the
package standalone without jax); jax is imported lazily inside the
functions that need it.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Dict, List, Optional

from . import metrics as _metrics
from . import trace as _trace

_LOG = logging.getLogger("bigdl_tpu.observability.perf")

ARTIFACT_SCHEMA = "bigdl_tpu.xla_programs.v1"

# bf16 peak FLOP/s per chip by device_kind substring (public specs).
# Ordered: first substring match wins (v5p before v5). The ONE table
# bench.py's offline MFU and the live perf/mfu gauge share — they must
# never disagree about the ceiling.
PEAK_FLOPS_TABLE = (
    ("v6", 918.0e12), ("v5p", 459.0e12), ("v5", 197.0e12),
    ("v4", 275.0e12), ("v3", 123.0e12), ("v2", 46.0e12),
)

#: assumed ceiling when the device kind matches nothing (v5e, the
#: BASELINE target platform). CPU smoke runs land here too — MFU on CPU
#: is only meaningful relative to an explicit BIGDL_TPU_PEAK_FLOPS.
DEFAULT_PEAK_FLOPS = 197.0e12


def peak_flops(device_kind: str = "") -> float:
    """Peak FLOP/s for ``device_kind``. ``BIGDL_TPU_PEAK_FLOPS`` (a
    float, e.g. ``1e12``) overrides the table — the knob the CPU smoke
    tests and non-TPU backends use to make MFU well-defined."""
    env = os.environ.get("BIGDL_TPU_PEAK_FLOPS")
    if env:
        try:
            return float(env)
        except ValueError:
            _LOG.warning("ignoring unparsable BIGDL_TPU_PEAK_FLOPS=%r", env)
    dk = (device_kind or "").lower()
    for sub, f in PEAK_FLOPS_TABLE:
        if sub in dk:
            return f
    return DEFAULT_PEAK_FLOPS


def analyze_compiled(compiled) -> Dict[str, float]:
    """Best-effort extraction of XLA's cost/memory analysis from an AOT
    ``jax.stages.Compiled``. Every field is optional: a backend (or jax
    version) without the API contributes nothing, never an exception —
    the artifact then records shapes and compile time only."""
    out: Dict[str, float] = {}
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if isinstance(ca, dict):
            for src, dst in (("flops", "flops"),
                             ("bytes accessed", "bytes_accessed"),
                             ("transcendentals", "transcendentals")):
                v = ca.get(src)
                if isinstance(v, (int, float)) and v >= 0:
                    out[dst] = float(v)
    except Exception:  # noqa: BLE001 — analytics must never break a build
        pass
    try:
        ma = compiled.memory_analysis()
        for src, dst in (
                ("argument_size_in_bytes", "argument_bytes"),
                ("output_size_in_bytes", "output_bytes"),
                ("temp_size_in_bytes", "temp_bytes"),
                ("alias_size_in_bytes", "alias_bytes"),
                ("generated_code_size_in_bytes", "generated_code_bytes")):
            v = getattr(ma, src, None)
            if isinstance(v, (int, float)) and v >= 0:
                out[dst] = float(v)
    except Exception:  # noqa: BLE001
        pass
    return out


class CompiledArtifact:
    """One compiled XLA program, as the introspection plane sees it."""

    __slots__ = ("name", "kind", "input_shapes", "steps_per_program",
                 "compile_seconds", "analysis", "cache_hits",
                 "cache_misses", "backend", "device_kind", "created_at",
                 "degraded")

    def __init__(self, name: str, kind: str, input_shapes: List[str],
                 steps_per_program: int = 1, compile_seconds: float = 0.0,
                 analysis: Optional[Dict[str, float]] = None,
                 cache_hits: int = 0, cache_misses: int = 0,
                 backend: str = "", device_kind: str = "",
                 degraded: Optional[str] = None):
        self.name = name
        self.kind = kind
        self.input_shapes = list(input_shapes)
        self.steps_per_program = int(steps_per_program)
        self.compile_seconds = float(compile_seconds)
        self.analysis = dict(analysis or {})
        self.cache_hits = int(cache_hits)
        self.cache_misses = int(cache_misses)
        self.backend = backend
        self.device_kind = device_kind
        self.created_at = time.time()
        self.degraded = degraded

    @property
    def flops(self) -> Optional[float]:
        return self.analysis.get("flops")

    @property
    def flops_per_step(self) -> Optional[float]:
        f = self.analysis.get("flops")
        if f is None:
            return None
        return f / max(1, self.steps_per_program)

    def resident_bytes(self) -> Optional[float]:
        """Device-memory footprint of one execution (arguments + outputs
        + temporaries) — what ``tools/xla_report.py`` holds against the
        ``mem/device_peak_bytes`` gauge for HBM headroom."""
        keys = ("argument_bytes", "output_bytes", "temp_bytes")
        if not any(k in self.analysis for k in keys):
            return None
        return sum(self.analysis.get(k, 0.0) for k in keys)

    def to_dict(self) -> Dict:
        return {
            "name": self.name, "kind": self.kind,
            "input_shapes": list(self.input_shapes),
            "steps_per_program": self.steps_per_program,
            "compile_seconds": round(self.compile_seconds, 6),
            "analysis": dict(self.analysis),
            "cache_hits": self.cache_hits,
            "cache_misses": self.cache_misses,
            "backend": self.backend, "device_kind": self.device_kind,
            "created_at": self.created_at,
            "degraded": self.degraded,
        }

    def __repr__(self):
        f = self.flops
        return (f"CompiledArtifact({self.name!r}, kind={self.kind!r}, "
                f"flops={f if f is not None else 'n/a'}, "
                f"compile={self.compile_seconds:.3f}s)")


class ArtifactRegistry:
    """Process-wide list of compiled-program artifacts (thread-safe).

    Recording also mirrors aggregates into the metrics registry —
    ``compile/programs``, ``compile/wall_s`` (histogram),
    ``compile/flops_last`` / ``compile/resident_bytes_last`` gauges —
    so the Prometheus/bench exporters see compile activity without a
    second collection path."""

    def __init__(self):
        self._artifacts: List[CompiledArtifact] = []
        self._lock = threading.Lock()

    def record(self, artifact: CompiledArtifact) -> CompiledArtifact:
        with self._lock:
            self._artifacts.append(artifact)
        reg = _metrics.registry()
        reg.counter("compile/programs").inc()
        reg.histogram("compile/wall_s", unit="s").observe(
            artifact.compile_seconds)
        if artifact.degraded:
            reg.counter("compile/degraded").inc()
        if artifact.flops is not None:
            reg.gauge("compile/flops_last", unit="flops").set(artifact.flops)
        rb = artifact.resident_bytes()
        if rb is not None:
            reg.gauge("compile/resident_bytes_last", unit="bytes").set(rb)
        return artifact

    def artifacts(self) -> List[CompiledArtifact]:
        with self._lock:
            return list(self._artifacts)

    def latest(self, name: str) -> Optional[CompiledArtifact]:
        with self._lock:
            for a in reversed(self._artifacts):
                if a.name == name:
                    return a
        return None

    def by_name(self) -> Dict[str, List[CompiledArtifact]]:
        out: Dict[str, List[CompiledArtifact]] = {}
        for a in self.artifacts():
            out.setdefault(a.name, []).append(a)
        return out

    def clear(self):
        with self._lock:
            self._artifacts.clear()


_registry = ArtifactRegistry()


def registry() -> ArtifactRegistry:
    return _registry


def reset():
    """Clear artifacts AND the live-MFU accumulators (tests)."""
    _registry.clear()
    _steps.reset()


def _backend_info():
    """(backend, device_kind) — lazy jax, never raises (the bench parent
    and pure-host tests must be able to record artifacts jax-free)."""
    try:
        import jax
        dev = jax.devices()[0]
        return jax.default_backend(), getattr(dev, "device_kind", "")
    except Exception:  # noqa: BLE001
        return "", ""


def _shape_strs(args) -> List[str]:
    """Flat ``shape:dtype`` strings for an argument tuple (lazy jax)."""
    try:
        import jax
        leaves = jax.tree_util.tree_leaves(args)
    except Exception:  # noqa: BLE001
        return []
    out = []
    for l in leaves[:64]:  # bound: a big param tree is provenance noise
        shape = getattr(l, "shape", ())
        dtype = getattr(l, "dtype", type(l).__name__)
        out.append(f"{tuple(shape)}:{dtype}")
    if len(leaves) > 64:
        out.append(f"... +{len(leaves) - 64} more leaves")
    return out


def _cache_counters():
    reg = _metrics.registry()
    return (reg.counter("engine/compile_cache_hits").value,
            reg.counter("engine/compile_cache_misses").value)


def record_compiled(name: str, kind: str, compiled=None, *,
                    compile_seconds: float = 0.0, input_shapes=None,
                    steps_per_program: int = 1, cache_hits: int = 0,
                    cache_misses: int = 0,
                    degraded: Optional[str] = None) -> CompiledArtifact:
    """Record one compiled program into the process registry (and the
    ``compile/*`` metrics). ``compiled`` may be None (degraded sites)."""
    analysis = analyze_compiled(compiled) if compiled is not None else {}
    if compiled is not None and not analysis and degraded is None:
        degraded = "cost/memory analysis unavailable on this backend"
    backend, device_kind = _backend_info()
    return _registry.record(CompiledArtifact(
        name, kind, input_shapes or [], steps_per_program=steps_per_program,
        compile_seconds=compile_seconds, analysis=analysis,
        cache_hits=cache_hits, cache_misses=cache_misses,
        backend=backend, device_kind=device_kind, degraded=degraded))


class InstrumentedJit:
    """AOT-compiling wrapper around a ``jax.jit``-ed function: the same
    call surface, but every distinct input-shape signature is lowered +
    compiled EXPLICITLY (``fn.lower(*args).compile()``) so its XLA cost
    and memory analysis land in the artifact registry — the jit call
    path gives no public handle on its executables.

    * One compile per signature, exactly like jit's own cache (and it
      shares the persistent compilation cache, so a warm process pays
      tracing only).
    * ``key_argnums`` bounds the per-call keying cost: compile sites
      whose parameter trees are shape-stable for the life of the
      function (the optimizer step: params/opt-state never change
      shape, only the batch does) key on the data arguments alone.
    * **Graceful degradation is total**: any failure to lower, compile
      or run the AOT executable permanently falls back to the plain jit
      path for this wrapper (recording a degraded artifact) — the
      introspection plane must never be able to break training.
    * When observability is disabled the wrapper IS the plain jit call
      — one flag read of overhead, no artifacts (PR-1 contract: the
      disabled path stays bulletproof and free).
    """

    def __init__(self, jit_fn, *, name: str, kind: str,
                 key_argnums: Optional[tuple] = None,
                 steps_per_program=1):
        self._jit = jit_fn
        self.name = name
        self.kind = kind
        self.key_argnums = tuple(key_argnums) if key_argnums else None
        #: int, or ``callable(args) -> int`` resolved at compile time —
        #: a clamped superstep compiles a separate program with FEWER
        #: steps than the configured K, and its artifact must say so
        self.steps_per_program = steps_per_program \
            if callable(steps_per_program) else int(steps_per_program)
        self._compiled: Dict[tuple, object] = {}
        self._artifacts: Dict[tuple, CompiledArtifact] = {}
        #: artifact of the program the LAST __call__ executed — what an
        #: MFU caller must read (a clamped superstep runs a different
        #: program than the full-K dispatch; "latest by name" would lie)
        self.last_artifact: Optional[CompiledArtifact] = None
        #: True when the last __call__ paid a compile — its wall time
        #: measures XLA, not the model; MFU accounting must skip it
        self.last_call_compiled = False
        self._broken = False
        self._lock = threading.Lock()

    def _key(self, args) -> Optional[tuple]:
        try:
            import jax
            src = args if self.key_argnums is None else \
                tuple(args[i] for i in self.key_argnums)
            return tuple(
                (tuple(getattr(l, "shape", ())),
                 str(getattr(l, "dtype", type(l).__name__)))
                for l in jax.tree_util.tree_leaves(src))
        except Exception:  # noqa: BLE001
            return None

    def _steps(self, args) -> int:
        if not callable(self.steps_per_program):
            return self.steps_per_program
        try:
            return int(self.steps_per_program(args))
        except Exception:  # noqa: BLE001 — provenance, never a failure
            return 1

    def _compile(self, key, args):
        h0, m0 = _cache_counters()
        t0 = time.perf_counter()
        compiled = self._jit.lower(*args).compile()
        dt = time.perf_counter() - t0
        h1, m1 = _cache_counters()
        art = record_compiled(
            self.name, self.kind, compiled,
            compile_seconds=dt, input_shapes=_shape_strs(args),
            steps_per_program=self._steps(args),
            cache_hits=int(h1 - h0), cache_misses=int(m1 - m0))
        with self._lock:
            self._compiled[key] = compiled
            self._artifacts[key] = art
        return compiled

    def __call__(self, *args):
        if self._broken or not _trace.enabled():
            return self._jit(*args)
        key = self._key(args)
        if key is None:
            return self._jit(*args)
        compiled = self._compiled.get(key)
        self.last_artifact = self._artifacts.get(key)
        self.last_call_compiled = False
        if compiled is None:
            try:
                self.last_call_compiled = True
                compiled = self._compile(key, args)
                self.last_artifact = self._artifacts.get(key)
            except Exception as e:  # noqa: BLE001 — degrade, never break
                self._broken = True
                record_compiled(
                    self.name, self.kind, None, input_shapes=_shape_strs(args),
                    steps_per_program=self._steps(args),
                    degraded=f"AOT lower/compile failed: "
                             f"{type(e).__name__}: {e}")
                _LOG.warning("%s: AOT introspection disabled (%s: %s)",
                             self.name, type(e).__name__, e)
                return self._jit(*args)
        try:
            return compiled(*args)
        except (TypeError, ValueError) as e:
            # argument/layout validation raises BEFORE execution — the
            # donated buffers are still alive, so re-running through the
            # jit path is safe; the AOT strictness is this wrapper's own
            # doing, so it degrades permanently
            self._broken = True
            _LOG.warning(
                "%s: AOT executable rejected its arguments (%s: %s); "
                "falling back to the jit path", self.name,
                type(e).__name__, e)
            return self._jit(*args)
        # anything else (XlaRuntimeError: device OOM, dead collective,
        # tunnel loss) propagates UNTOUCHED: the buffers may already be
        # donated — a silent jit re-run would trip 'Array has been
        # deleted' and bury the real error the Tier-2 FaultPolicy's
        # classify_failure needs to see — and the failure is the
        # device's, not the AOT path's, so the wrapper stays armed

    def compiled_shape_count(self) -> int:
        return len(self._compiled)


def instrument_jit(jit_fn, *, name: str, kind: str,
                   key_argnums: Optional[tuple] = None,
                   steps_per_program: int = 1) -> InstrumentedJit:
    """Wrap an already-``jax.jit``-ed function for artifact capture."""
    return InstrumentedJit(jit_fn, name=name, kind=kind,
                           key_argnums=key_argnums,
                           steps_per_program=steps_per_program)


# ------------------------------------------------------------------ MFU

class _StepPerf:
    """Run-cumulative live-MFU bookkeeping (host floats only)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._total_flops = 0.0
        self._total_wall = 0.0
        self._peak = None       # resolved lazily, re-resolved on env change
        self._peak_env = None   # the override value the cache was built for

    def reset(self):
        with self._lock:
            self._total_flops = 0.0
            self._total_wall = 0.0
            self._peak = None
            self._peak_env = None

    def peak(self) -> float:
        # one lazy device_kind lookup per process; re-resolved whenever
        # the BIGDL_TPU_PEAK_FLOPS override CHANGES — including being
        # unset (a smoke-phase override must not leak into the real
        # measurement later in the same process)
        env = os.environ.get("BIGDL_TPU_PEAK_FLOPS")
        if self._peak is None or env != self._peak_env:
            _, dk = _backend_info()
            self._peak = peak_flops(dk)
            self._peak_env = env
        return self._peak

    def note(self, flops: Optional[float], wall_s: float,
             host_s: Optional[float] = None,
             dispatch_s: Optional[float] = None):
        """``wall_s`` is the FULL iteration wall (fetch + dispatch +
        resolve) — the throughput definition of MFU (delivered FLOPs
        per second of wall clock, the same denominator bench.py's
        timed loop uses). Under ``async``/``window:K`` the dispatch
        call alone returns in microseconds while the device still
        computes; dividing by that sliver would read MFU orders of
        magnitude HIGH exactly when the run is host-bound, inverting
        the signal. The iteration wall is ≥ the device time under
        every sync policy, so the gauge can only under-claim, never
        flatter."""
        if flops is None or wall_s <= 0:
            return
        peak = self.peak()
        with self._lock:
            self._total_flops += flops
            self._total_wall += wall_s
            tf, tw = self._total_flops, self._total_wall
        reg = _metrics.registry()
        reg.gauge("perf/model_flops_per_s", unit="flops/s").set(
            flops / wall_s)
        reg.gauge("perf/mfu").set(flops / wall_s / peak)
        reg.gauge("perf/mfu_mean").set(tf / tw / peak)
        reg.counter("perf/model_flops", unit="flops").inc(flops)
        if host_s is not None and dispatch_s is not None:
            # host = producing/fetching the batch, dispatch = enqueueing
            # the program, device = the remainder of the iteration
            # (dominated by the loss-resolution wait on device compute)
            total = max(wall_s, 1e-12)
            device_s = max(wall_s - host_s - dispatch_s, 0.0)
            reg.gauge("perf/phase_host_frac").set(host_s / total)
            reg.gauge("perf/phase_dispatch_frac").set(dispatch_s / total)
            reg.gauge("perf/phase_device_frac").set(device_s / total)


_steps = _StepPerf()


def note_step(artifact, wall_s: float,
              host_s: Optional[float] = None,
              dispatch_s: Optional[float] = None):
    """Publish the live MFU gauges for one completed dispatch:
    ``artifact`` is the :class:`CompiledArtifact` of the program that
    just ran (an :class:`InstrumentedJit`'s ``last_artifact``), or a
    registry name to look up the newest by. ``wall_s`` is the FULL
    iteration wall the loop already measured (see :meth:`_StepPerf.
    note` for why the dispatch sliver alone would lie under async
    policies). Pure host arithmetic — no device access of any kind.
    Quietly does nothing when the artifact is missing or carries no
    FLOPs (degraded backend): a gauge that silently lies is worse than
    one that is absent."""
    art = _registry.latest(artifact) if isinstance(artifact, str) \
        else artifact
    if art is None:
        return
    _steps.note(art.flops, wall_s, host_s=host_s, dispatch_s=dispatch_s)


# ---------------------------------------------------------------- export

def artifacts_snapshot() -> List[Dict]:
    return [a.to_dict() for a in _registry.artifacts()]


def dump_artifacts(path: Optional[str] = None) -> Optional[str]:
    """Write the artifact registry (+ the ``mem/*`` gauges for HBM
    headroom context) as strict JSON; returns the path. Defaults into
    the flight-bundle directory (``xla_programs_<pid>.json``). Never
    raises — export is advisory."""
    try:
        from . import flight as _flight
        if path is None:
            d = _flight.bundle_dir()
            os.makedirs(d, exist_ok=True)
            path = os.path.join(d, f"xla_programs_{os.getpid()}.json")
        mem = {name: inst for name, inst in
               _metrics.registry().snapshot().items()
               if name.startswith("mem/") or name.startswith("compile/")}
        doc = {
            "schema": ARTIFACT_SCHEMA,
            "written_at": time.time(),
            "pid": os.getpid(),
            "programs": artifacts_snapshot(),
            "metrics": mem,
        }
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(_flight._json_safe(doc), f, indent=1, default=str,
                      allow_nan=False)
        os.replace(tmp, path)
        return path
    except Exception:  # noqa: BLE001
        _LOG.exception("failed to dump compiled-program artifacts")
        return None
