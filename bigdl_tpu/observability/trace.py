"""Span-based tracer: nested, thread-safe, monotonic, exception-safe.

Design constraints, in order:

1. **Disabled cost is one flag read.** The hot-path spelling is
   ``with span("step/dispatch"):`` — when tracing is off that call
   returns a shared immutable no-op context manager; no allocation, no
   clock read, no lock. The training loop keeps the instrumentation
   inline at all times (no conditional code paths to bit-rot).
2. **Monotonic clocks.** Spans stamp ``time.perf_counter_ns()``; wall
   clocks (NTP steps, suspend) must never produce negative durations in
   a trace.
3. **Thread-correct nesting.** Each thread owns its span stack
   (``threading.local``) so the async checkpoint writer or a prefetch
   thread nests its own spans without corrupting the main loop's stack.
   Finished spans land in one shared list (CPython list.append is
   atomic; the exporters snapshot under the tracer lock).
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional


class Span:
    """One finished (or open) span. Times are perf_counter nanoseconds."""

    __slots__ = ("name", "start_ns", "end_ns", "tid", "depth", "args")

    def __init__(self, name: str, start_ns: int, tid: int, depth: int,
                 args: Optional[Dict] = None):
        self.name = name
        self.start_ns = start_ns
        self.end_ns: Optional[int] = None
        self.tid = tid
        self.depth = depth
        self.args = args

    @property
    def duration_ns(self) -> int:
        return (self.end_ns or self.start_ns) - self.start_ns

    def __repr__(self):
        return (f"Span({self.name!r}, dur={self.duration_ns / 1e6:.3f}ms, "
                f"depth={self.depth})")


class _SpanHandle:
    """Context manager that closes its span exactly once, exception or
    not; an exception tags the span (``error: ExcType``) instead of
    leaking an open span on the stack."""

    __slots__ = ("_tracer", "_span")

    def __init__(self, tracer: "Tracer", sp: Span):
        self._tracer = tracer
        self._span = sp

    def annotate(self, **kw):
        """Attach key/values to the live span (shows up in the Chrome
        trace ``args`` pane)."""
        if self._span.args is None:
            self._span.args = {}
        self._span.args.update(kw)
        return self

    @property
    def duration_s(self) -> float:
        """Elapsed seconds (final after ``__exit__``) — lets call-sites
        feed a histogram from the SAME clock reads the span made instead
        of timing the interval twice."""
        return self._span.duration_ns / 1e9

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            self.annotate(error=exc_type.__name__)
        self._tracer._finish(self._span)
        return False


class _NullSpan:
    """Shared no-op handle for the disabled path (and a safe annotate)."""

    __slots__ = ()

    duration_s = 0.0

    def annotate(self, **kw):
        return self

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False


_NULL_SPAN = _NullSpan()


class Tracer:
    def __init__(self, max_events: int = 1_000_000):
        # max_events bounds memory on multi-hour runs: once full the
        # tracer drops new spans (and counts the drops) rather than OOM
        self.max_events = max_events
        self.dropped = 0
        self._events: List[Span] = []
        self._tls = threading.local()
        self._lock = threading.Lock()
        self._epoch_ns = time.perf_counter_ns()

    # -- recording -------------------------------------------------------
    def _stack(self) -> List[Span]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def span(self, name: str, **args) -> _SpanHandle:
        st = self._stack()
        sp = Span(name, time.perf_counter_ns(), threading.get_ident(),
                  len(st), args or None)
        st.append(sp)
        return _SpanHandle(self, sp)

    def _append(self, sp: Span):
        # lock: reset() clears the list + re-stamps the epoch; an append
        # racing it would land a pre-epoch span (negative export ts).
        # The ONE capacity gate for every recording path (finish/
        # instant/complete) — the drop policy must not fork per path.
        with self._lock:
            if len(self._events) >= self.max_events:
                self.dropped += 1
                return
            self._events.append(sp)

    def _finish(self, sp: Span):
        sp.end_ns = time.perf_counter_ns()
        st = self._stack()
        # exception-safe even if an inner handle leaked: pop through to
        # this span rather than corrupting the depth bookkeeping
        while st and st[-1] is not sp:
            st.pop()
        if st:
            st.pop()
        self._append(sp)

    def complete(self, name: str, start_ns: int, end_ns: int,
                 tid: Optional[int] = None, **args):
        """Record an already-finished span from caller-supplied
        ``perf_counter_ns`` stamps (depth 0) — for intervals whose
        start predates the recording call, e.g. a serving request's
        queue wait measured from its enqueue stamp when its batch is
        finally cut. Bypasses the nesting stack. ``tid`` defaults to
        the current thread; pass a synthetic (e.g. negative) id when
        several retro spans OVERLAP — complete events on one tid are
        nested-by-containment in the trace format and in
        ``tools/trace_report.py``, so overlapping siblings must each
        ride their own virtual lane to keep self-times honest."""
        sp = Span(name, int(start_ns),
                  threading.get_ident() if tid is None else tid, 0,
                  args or None)
        sp.end_ns = int(end_ns)
        self._append(sp)

    def instant(self, name: str, **args):
        """Zero-duration marker event (nan skips, trigger fires)."""
        sp = Span(name, time.perf_counter_ns(), threading.get_ident(),
                  len(self._stack()), args or None)
        sp.end_ns = sp.start_ns
        self._append(sp)

    # -- reading ---------------------------------------------------------
    def events(self) -> List[Span]:
        with self._lock:
            return list(self._events)

    def reset(self):
        with self._lock:
            self._events.clear()
            self.dropped = 0
            self._epoch_ns = time.perf_counter_ns()

    @property
    def epoch_ns(self) -> int:
        """perf_counter origin for relative timestamps in exports."""
        return self._epoch_ns


# -- process-global state ------------------------------------------------
_enabled = False
_tracer = Tracer()


def get_tracer() -> Tracer:
    return _tracer


def enable():
    global _enabled
    _enabled = True


def disable():
    global _enabled
    _enabled = False


def enabled() -> bool:
    return _enabled


def reset():
    """Clear collected spans (and the shared registry's owner does its
    own reset; this touches only the tracer)."""
    _tracer.reset()


def span(name: str, **args):
    """Module-level hot-path entry: a real span when enabled, the shared
    no-op handle when not."""
    if not _enabled:
        return _NULL_SPAN
    return _tracer.span(name, **args)


def instant(name: str, **args):
    if _enabled:
        _tracer.instant(name, **args)


def complete(name: str, start_ns: int, end_ns: int,
             tid: Optional[int] = None, **args):
    """Record a retrospective span from explicit ``perf_counter_ns``
    stamps (no-op when disabled). See :meth:`Tracer.complete` for the
    ``tid`` contract on overlapping spans."""
    if _enabled:
        _tracer.complete(name, start_ns, end_ns, tid=tid, **args)
