"""TF-style operation layer (parity: reference ``nn/ops/*.scala`` ~70 ops +
``nn/tf/*.scala``).

Each op is a small ``Module`` whose forward is a jnp expression — XLA fuses
them; there is no per-op kernel dispatch like the reference's per-Operation
``updateOutput``. Multi-input ops take a Table/list input (same convention as
``CAddTable``). Feature-column ops that are inherently host-side string
processing (CategoricalColVocaList, CrossCol, MkString, Substr) run on numpy
object arrays outside jit, mirroring how the reference runs them on the Spark
driver side rather than in MKL kernels.
"""
from .ops import *  # noqa: F401,F403
from .ops import __all__  # noqa: F401
