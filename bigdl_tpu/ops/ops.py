"""TF-style operation modules.

Parity: reference ``nn/ops/`` (Equal.scala, Gather.scala, Select.scala,
Tile.scala, TopK.scala, OneHot.scala, SegmentSum.scala, BucketizedCol.scala,
...) and ``nn/tf/`` (Shape.scala, StridedSlice.scala, SplitAndSelect.scala,
Log1p.scala, ...). Each op lowers to one or a few jnp/lax expressions that
XLA fuses — none of the reference's per-op Scala updateOutput kernels.

Conventions:
  * multi-input ops take a ``Table`` or list (like ``nn.CAddTable``);
  * axis arguments are 0-based here (TF convention) — the reference's nn/ops
    layer is 0-based too, unlike its 1-based Torch-style nn layer;
  * ops whose reference semantics are host-side string processing
    (CategoricalColVocaList, CrossCol, MkString, Substr) accept numpy object
    arrays and run un-jitted, as data-pipeline stages.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..nn.module import Module
from ..utils.table import Table


def _items(x):
    return x.to_list() if isinstance(x, Table) else \
        (list(x) if isinstance(x, (list, tuple)) else [x])


class Operation(Module):
    """Base class: inference-style op (nn/ops/Operation.scala — ops there
    have no backward; here most are jax-differentiable anyway)."""

    def _op(self, *xs):
        raise NotImplementedError

    def _apply(self, params, state, x, training, rng):
        return self._op(*_items(x))


def _unary(fn, doc_ref):
    class _Op(Operation):
        def _op(self, a):
            return fn(a)
    _Op.__doc__ = doc_ref
    return _Op


def _binary(fn, doc_ref):
    class _Op(Operation):
        def _op(self, a, b):
            return fn(a, b)
    _Op.__doc__ = doc_ref
    return _Op


# -- comparison (nn/ops/Equal.scala, Greater.scala, ...) --------------------

Equal = _binary(lambda a, b: a == b, "nn/ops/Equal.scala")
NotEqual = _binary(lambda a, b: a != b, "nn/ops/NotEqual.scala")
Greater = _binary(lambda a, b: a > b, "nn/ops/Greater.scala")
GreaterEqual = _binary(lambda a, b: a >= b, "nn/ops/GreaterEqual.scala")
Less = _binary(lambda a, b: a < b, "nn/ops/Less.scala")
LessEqual = _binary(lambda a, b: a <= b, "nn/ops/LessEqual.scala")


class ApproximateEqual(Operation):
    """nn/ops/ApproximateEqual.scala — |a - b| < tolerance."""

    def __init__(self, tolerance: float = 1e-5, name=None):
        super().__init__(name=name)
        self.tolerance = tolerance

    def _op(self, a, b):
        return jnp.abs(a - b) < self.tolerance


# -- logical (nn/ops/LogicalAnd.scala, ...) ---------------------------------

LogicalAnd = _binary(jnp.logical_and, "nn/ops/LogicalAnd.scala")
LogicalOr = _binary(jnp.logical_or, "nn/ops/LogicalOr.scala")
LogicalNot = _unary(jnp.logical_not, "nn/ops/LogicalNot.scala")


class _Reduction(Operation):
    """Base for All/Any/Sum/Prod/Max: second input (or ctor arg) gives the
    reduction indices, TF-style."""

    _fn = None

    def __init__(self, axis=None, keep_dims: bool = False, name=None):
        super().__init__(name=name)
        self.axis, self.keep_dims = axis, keep_dims

    def _op(self, a, axis=None):
        ax = self.axis if axis is None else \
            tuple(int(i) for i in np.asarray(axis).reshape(-1))
        if isinstance(ax, int):
            ax = (ax,)
        return type(self)._fn(a, axis=ax, keepdims=self.keep_dims)


class All(_Reduction):
    """nn/ops/All.scala"""
    _fn = staticmethod(jnp.all)


class Any(_Reduction):
    """nn/ops/Any.scala"""
    _fn = staticmethod(jnp.any)


class Sum(_Reduction):
    """nn/ops/Sum.scala"""
    _fn = staticmethod(jnp.sum)


class Prod(_Reduction):
    """nn/ops/Prod.scala"""
    _fn = staticmethod(jnp.prod)


class Max(_Reduction):
    """nn/ops/Max.scala"""
    _fn = staticmethod(jnp.max)


class Min(_Reduction):
    """tf Min (reference folds into Max.scala pattern)"""
    _fn = staticmethod(jnp.min)


class Mean(_Reduction):
    """tf Mean (nn/ops reduction family)"""
    _fn = staticmethod(jnp.mean)


# -- elementwise math (nn/ops/Exp.scala, Floor.scala, ...) ------------------

Exp = _unary(jnp.exp, "nn/ops/Exp.scala")
Expm1 = _unary(jnp.expm1, "nn/ops/Expm1.scala")
Log1p = _unary(jnp.log1p, "nn/tf/Log1p.scala")
Floor = _unary(jnp.floor, "nn/ops/Floor.scala")
Ceil = _unary(jnp.ceil, "nn/ops/Ceil.scala")
Round = _unary(jnp.round, "nn/ops/Round.scala")
Rint = _unary(jnp.rint, "nn/ops/Rint.scala")
Sign = _unary(jnp.sign, "nn/ops/Sign.scala")
Inv = _unary(lambda a: 1.0 / a, "nn/ops/Inv.scala (reciprocal)")
Erf = _unary(jax.scipy.special.erf, "nn/ops/Erf.scala")
Erfc = _unary(jax.scipy.special.erfc, "nn/ops/Erfc.scala")
Lgamma = _unary(jax.scipy.special.gammaln, "nn/ops/Lgamma.scala")
Digamma = _unary(jax.scipy.special.digamma, "nn/ops/Digamma.scala")
IsFinite = _unary(jnp.isfinite, "nn/ops/IsFinite.scala")
IsInf = _unary(jnp.isinf, "nn/ops/IsInf.scala")
IsNan = _unary(jnp.isnan, "nn/ops/IsNan.scala")

Pow = _binary(jnp.power, "nn/ops/Pow.scala")
Maximum = _binary(jnp.maximum, "nn/ops/Maximum.scala")
Minimum = _binary(jnp.minimum, "nn/ops/Minimum.scala")
FloorDiv = _binary(jnp.floor_divide, "nn/ops/FloorDiv.scala")
FloorMod = _binary(jnp.mod, "nn/ops/FloorMod.scala")
Mod = _binary(jnp.mod, "nn/ops/Mod.scala")
TruncateDiv = _binary(
    lambda a, b: jnp.trunc(a / b).astype(a.dtype), "nn/ops/TruncateDiv.scala")
SquaredDifference = _binary(
    lambda a, b: jnp.square(a - b), "nn/ops/SquaredDifference.scala")


# -- shape/metadata (nn/tf/Shape.scala, nn/ops/Rank.scala) ------------------

Shape = _unary(lambda a: jnp.asarray(a.shape, jnp.int32), "nn/tf/Shape.scala")
Rank = _unary(lambda a: jnp.asarray(a.ndim, jnp.int32), "nn/ops/Rank.scala")


class Cast(Operation):
    """nn/ops/Cast.scala"""

    def __init__(self, dtype, name=None):
        super().__init__(name=name)
        self.dtype = jnp.dtype(dtype)

    def _op(self, a):
        return a.astype(self.dtype)


# -- array ops --------------------------------------------------------------

class Gather(Operation):
    """nn/ops/Gather.scala — gather rows of ``params`` along ``axis`` by
    integer ``indices``. Lowers to one XLA gather (jnp.take)."""

    def __init__(self, axis: int = 0, name=None):
        super().__init__(name=name)
        self.axis = axis

    def _op(self, params_t, indices):
        return jnp.take(params_t, indices.astype(jnp.int32), axis=self.axis)


class Select(Operation):
    """nn/ops/Select.scala — elementwise cond ? x : y."""

    def _op(self, cond, x, y):
        return jnp.where(cond, x, y)


class Slice(Operation):
    """nn/ops/Slice.scala — static begin/size slice."""

    def __init__(self, begin, size, name=None):
        super().__init__(name=name)
        self.begin = [int(b) for b in begin]
        self.size = [int(s) for s in size]

    def _op(self, a):
        idx = tuple(
            slice(b, a.shape[i] if s == -1 else b + s)
            for i, (b, s) in enumerate(zip(self.begin, self.size)))
        return a[idx]


class StridedSlice(Operation):
    """nn/tf/StridedSlice.scala — static begin/end/strides with shrink mask."""

    def __init__(self, begin, end, strides=None, shrink_axis_mask: int = 0,
                 begin_mask: int = 0, end_mask: int = 0, name=None):
        super().__init__(name=name)
        self.begin = [int(b) for b in begin]
        self.end = [int(e) for e in end]
        self.strides = [int(s) for s in (strides or [1] * len(self.begin))]
        self.shrink = shrink_axis_mask
        self.begin_mask, self.end_mask = begin_mask, end_mask

    def _op(self, a):
        idx = []
        for d in range(len(self.begin)):
            b = None if (self.begin_mask >> d) & 1 else self.begin[d]
            e = None if (self.end_mask >> d) & 1 else self.end[d]
            if (self.shrink >> d) & 1:
                idx.append(self.begin[d])
            else:
                idx.append(slice(b, e, self.strides[d]))
        return a[tuple(idx)]


class Tile(Operation):
    """nn/ops/Tile.scala — second input (or ctor) gives multiples."""

    def __init__(self, multiples=None, name=None):
        super().__init__(name=name)
        self.multiples = multiples

    def _op(self, a, multiples=None):
        m = self.multiples if multiples is None else \
            [int(x) for x in np.asarray(multiples).reshape(-1)]
        return jnp.tile(a, m)


class OneHot(Operation):
    """nn/ops/OneHot.scala — indices → one-hot on a new last (or given) axis."""

    def __init__(self, depth: int, on_value: float = 1.0,
                 off_value: float = 0.0, axis: int = -1, name=None):
        super().__init__(name=name)
        self.depth, self.axis = depth, axis
        self.on_value, self.off_value = on_value, off_value

    def _op(self, indices):
        oh = jax.nn.one_hot(indices.astype(jnp.int32), self.depth,
                            axis=self.axis)
        return oh * (self.on_value - self.off_value) + self.off_value


class TopK(Operation):
    """nn/ops/TopK.scala — returns Table(values, indices)."""

    def __init__(self, k: int, sorted: bool = True, name=None):
        super().__init__(name=name)
        self.k = k

    def _op(self, a):
        v, i = jax.lax.top_k(a, self.k)
        return Table(v, i.astype(jnp.int32))


class InTopK(Operation):
    """nn/ops/InTopK.scala — targets ∈ top-k(predictions) per row."""

    def __init__(self, k: int, name=None):
        super().__init__(name=name)
        self.k = k

    def _op(self, predictions, targets):
        _, idx = jax.lax.top_k(predictions, self.k)
        return jnp.any(idx == targets.astype(jnp.int32)[:, None], axis=-1)


class ArgMax(Operation):
    """nn/ops/ArgMax.scala — axis from ctor or second input."""

    def __init__(self, axis: int = 0, name=None):
        super().__init__(name=name)
        self.axis = axis

    def _op(self, a, axis=None):
        ax = self.axis if axis is None else int(np.asarray(axis).reshape(()))
        return jnp.argmax(a, axis=ax).astype(jnp.int32)


class BatchMatMul(Operation):
    """nn/ops/BatchMatMul.scala — batched matmul with optional adjoints.
    One XLA dot_general → MXU."""

    def __init__(self, adj_x: bool = False, adj_y: bool = False, name=None):
        super().__init__(name=name)
        self.adj_x, self.adj_y = adj_x, adj_y

    def _op(self, x, y):
        if self.adj_x:
            x = jnp.swapaxes(x, -1, -2)
        if self.adj_y:
            y = jnp.swapaxes(y, -1, -2)
        return jnp.matmul(x, y)


class SegmentSum(Operation):
    """nn/ops/SegmentSum.scala — jax.ops.segment_sum (the TPU-native sparse
    reduction; also the building block of the sparse layer family)."""

    def __init__(self, num_segments=None, name=None):
        super().__init__(name=name)
        self.num_segments = num_segments

    def _op(self, data, segment_ids):
        n = self.num_segments
        if n is None:
            n = int(np.asarray(segment_ids).max()) + 1  # host-side like ref
        return jax.ops.segment_sum(data, segment_ids.astype(jnp.int32),
                                   num_segments=n)


class Pad(Operation):
    """nn/ops/Pad.scala — constant padding, paddings as (ndim, 2)."""

    def __init__(self, paddings, constant_value: float = 0.0, name=None):
        super().__init__(name=name)
        self.paddings = [tuple(int(x) for x in p) for p in np.asarray(paddings)]
        self.constant_value = constant_value

    def _op(self, a):
        return jnp.pad(a, self.paddings, constant_values=self.constant_value)


class ExpandDims(Operation):
    """tf ExpandDims (reference folds into array ops)"""

    def __init__(self, axis: int, name=None):
        super().__init__(name=name)
        self.axis = axis

    def _op(self, a):
        return jnp.expand_dims(a, self.axis)


class SplitAndSelect(Operation):
    """nn/tf/SplitAndSelect.scala — split along a dim, return one piece."""

    def __init__(self, dim: int, index: int, num_split: int, name=None):
        super().__init__(name=name)
        self.dim, self.index, self.num_split = dim, index, num_split

    def _op(self, a):
        return jnp.split(a, self.num_split, axis=self.dim)[self.index]


class InvertPermutation(Operation):
    """nn/tf/ArrayOps.scala InvertPermutation"""

    def _op(self, p):
        return jnp.argsort(p.astype(jnp.int32)).astype(jnp.int32)


class Pack(Operation):
    """tf Pack/Stack (nn/tf/ArrayOps family) — stack inputs on ``axis``."""

    def __init__(self, axis: int = 0, name=None):
        super().__init__(name=name)
        self.axis = axis

    def _op(self, *xs):
        return jnp.stack(list(xs), axis=self.axis)


class Split(Operation):
    """tf Split — equal split along ``axis``; returns a Table of pieces."""

    def __init__(self, num_split: int, axis: int = 0, name=None):
        super().__init__(name=name)
        self.num_split, self.axis = num_split, axis

    def _op(self, a):
        return Table(*jnp.split(a, self.num_split, axis=self.axis))


class Unpack(Operation):
    """tf Unpack/Unstack — split along ``axis`` and squeeze it; Table out."""

    def __init__(self, num: int, axis: int = 0, name=None):
        super().__init__(name=name)
        self.num, self.axis = num, axis

    def _op(self, a):
        pieces = jnp.split(a, self.num, axis=self.axis)
        return Table(*[jnp.squeeze(p, axis=self.axis) for p in pieces])


class ResizeBilinear(Operation):
    """nn/ops/ResizeBilinear.scala — NHWC bilinear resize via jax.image
    (lowers to XLA gather/dot, TPU-tiled)."""

    def __init__(self, output_height: int, output_width: int,
                 align_corners: bool = False, data_format: str = "NHWC",
                 name=None):
        super().__init__(name=name)
        self.oh, self.ow = output_height, output_width
        self.align_corners = align_corners
        self.data_format = data_format

    def _op(self, a):
        nhwc = self.data_format == "NHWC"
        if not nhwc:
            a = jnp.transpose(a, (0, 2, 3, 1))
        b, h, w, c = a.shape
        if self.align_corners and h > 1 and w > 1:
            # align_corners: endpoints map to endpoints
            ys = jnp.linspace(0, h - 1, self.oh)
            xs = jnp.linspace(0, w - 1, self.ow)
            y0 = jnp.clip(jnp.floor(ys).astype(jnp.int32), 0, h - 2)
            x0 = jnp.clip(jnp.floor(xs).astype(jnp.int32), 0, w - 2)
            wy = (ys - y0)[None, :, None, None]
            wx = (xs - x0)[None, None, :, None]
            g00 = a[:, y0][:, :, x0]
            g01 = a[:, y0][:, :, x0 + 1]
            g10 = a[:, y0 + 1][:, :, x0]
            g11 = a[:, y0 + 1][:, :, x0 + 1]
            out = (g00 * (1 - wy) * (1 - wx) + g01 * (1 - wy) * wx +
                   g10 * wy * (1 - wx) + g11 * wy * wx)
        else:
            out = jax.image.resize(a, (b, self.oh, self.ow, c), "bilinear")
        if not nhwc:
            out = jnp.transpose(out, (0, 3, 1, 2))
        return out


class Dilation2D(Operation):
    """nn/ops/Dilation2D.scala — grayscale morphological dilation: NHWC input,
    (kh, kw, C) filter; out = max over window of (input + filter). Lowered to
    a reduce_window per tap-free formulation via lax.reduce_window is not
    expressible (filter varies per tap), so use explicit patch extraction —
    static shapes, VPU-friendly."""

    def __init__(self, strides, rates, padding: str = "SAME", name=None):
        super().__init__(name=name)
        self.strides = [int(s) for s in strides]
        self.rates = [int(r) for r in rates]
        self.padding = padding

    def _op(self, a, filt):
        kh, kw, c = filt.shape
        sh, sw = self.strides[1], self.strides[2]
        rh, rw = self.rates[1], self.rates[2]
        eff_kh, eff_kw = (kh - 1) * rh + 1, (kw - 1) * rw + 1
        b, h, w, _ = a.shape
        if self.padding == "SAME":
            oh = -(-h // sh)
            ow = -(-w // sw)
            ph = max(0, (oh - 1) * sh + eff_kh - h)
            pw = max(0, (ow - 1) * sw + eff_kw - w)
            a = jnp.pad(a, ((0, 0), (ph // 2, ph - ph // 2),
                            (pw // 2, pw - pw // 2), (0, 0)),
                        constant_values=-jnp.inf)
        else:
            oh = (h - eff_kh) // sh + 1
            ow = (w - eff_kw) // sw + 1
        outs = []
        for i in range(kh):
            for j in range(kw):
                patch = a[:, i * rh:i * rh + (oh - 1) * sh + 1:sh,
                          j * rw:j * rw + (ow - 1) * sw + 1:sw, :]
                outs.append(patch + filt[i, j])
        return functools.reduce(jnp.maximum, outs)


# -- losses / misc ----------------------------------------------------------

class L2Loss(Operation):
    """nn/ops/L2Loss.scala — sum(x^2) / 2."""

    def _op(self, a):
        return jnp.sum(jnp.square(a)) / 2.0


class CrossEntropy(Operation):
    """nn/ops/CrossEntropy.scala — per-row softmax cross entropy from
    (logits, one-hot labels)."""

    def _op(self, logits, labels):
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.sum(labels * logp, axis=-1)


class RandomUniform(Operation):
    """nn/ops/RandomUniform.scala — shape input → uniform sample. Uses the
    module rng (functional: pass rng through apply)."""

    def __init__(self, minval: float = 0.0, maxval: float = 1.0, seed=None,
                 name=None):
        super().__init__(name=name)
        self.minval, self.maxval, self.seed = minval, maxval, seed

    def _apply(self, params, state, x, training, rng):
        shape = tuple(int(s) for s in np.asarray(_items(x)[0]).reshape(-1))
        if rng is None:
            rng = jax.random.PRNGKey(self.seed or 0)
        return jax.random.uniform(rng, shape, minval=self.minval,
                                  maxval=self.maxval)


class TruncatedNormal(Operation):
    """nn/ops/TruncatedNormal.scala — shape input → truncated normal."""

    def __init__(self, mean: float = 0.0, stddev: float = 1.0, seed=None,
                 name=None):
        super().__init__(name=name)
        self.mean, self.stddev, self.seed = mean, stddev, seed

    def _apply(self, params, state, x, training, rng):
        shape = tuple(int(s) for s in np.asarray(_items(x)[0]).reshape(-1))
        if rng is None:
            rng = jax.random.PRNGKey(self.seed or 0)
        return self.mean + self.stddev * jax.random.truncated_normal(
            rng, -2.0, 2.0, shape)


class ModuleToOperation(Operation):
    """nn/ops/ModuleToOperation.scala — wrap any nn module as an op."""

    def __init__(self, module: Module, name=None):
        super().__init__(name=name)
        self.module = module

    def _init_params(self, rng):
        return self.module._init_params(rng)

    def _init_state(self):
        return self.module._init_state()

    def _apply(self, params, state, x, training, rng):
        return self.module._apply(params, state, x, training, rng)


class TensorOp(Operation):
    """nn/ops/TensorOp.scala — chainable pointwise transform built from a
    function; ``TensorOp.exp().add(1.0)`` style composition."""

    def __init__(self, fn=None, name=None):
        super().__init__(name=name)
        self.fn = fn or (lambda t: t)

    def _op(self, a):
        return self.fn(a)

    def _chain(self, g):
        f = self.fn
        return TensorOp(lambda t: g(f(t)), name=self.name)

    def add(self, v):
        return self._chain(lambda t: t + v)

    def sub(self, v):
        return self._chain(lambda t: t - v)

    def mul(self, v):
        return self._chain(lambda t: t * v)

    def div(self, v):
        return self._chain(lambda t: t / v)

    def exp(self):
        return self._chain(jnp.exp)

    def log(self):
        return self._chain(jnp.log)

    def abs(self):
        return self._chain(jnp.abs)

    def sqrt(self):
        return self._chain(jnp.sqrt)

    def square(self):
        return self._chain(jnp.square)

    def pow(self, p):
        return self._chain(lambda t: jnp.power(t, p))


# -- feature-column ops (recommender pipelines) -----------------------------

class BucketizedCol(Operation):
    """nn/ops/BucketizedCol.scala — numeric → bucket index by boundaries."""

    def __init__(self, boundaries, name=None):
        super().__init__(name=name)
        self.boundaries = jnp.asarray(boundaries, jnp.float32)

    def _op(self, a):
        return jnp.searchsorted(self.boundaries, a.astype(jnp.float32),
                                side="right").astype(jnp.int32)


class CategoricalColHashBucket(Operation):
    """nn/ops/CategoricalColHashBucket.scala — string/int column → stable
    hash bucket. Host-side (numpy object arrays), like the reference's
    driver-side feature columns."""

    def __init__(self, hash_bucket_size: int, name=None):
        super().__init__(name=name)
        self.hash_bucket_size = hash_bucket_size

    def _op(self, a):
        import zlib
        arr = np.asarray(a)
        flat = [zlib.crc32(str(x).encode()) % self.hash_bucket_size
                for x in arr.reshape(-1)]
        return jnp.asarray(np.array(flat, np.int32).reshape(arr.shape))


class CategoricalColVocaList(Operation):
    """nn/ops/CategoricalColVocaList.scala — vocabulary lookup with optional
    OOV buckets. Host-side."""

    def __init__(self, vocab, default_value: int = -1, num_oov_buckets: int = 0,
                 name=None):
        super().__init__(name=name)
        self.vocab = {v: i for i, v in enumerate(vocab)}
        self.default_value = default_value
        self.num_oov_buckets = num_oov_buckets

    def _op(self, a):
        import zlib
        arr = np.asarray(a)
        n = len(self.vocab)

        def lookup(x):
            key = x if isinstance(x, str) else str(x)
            if key in self.vocab:
                return self.vocab[key]
            if self.num_oov_buckets > 0:
                return n + zlib.crc32(key.encode()) % self.num_oov_buckets
            return self.default_value
        flat = [lookup(x) for x in arr.reshape(-1)]
        return jnp.asarray(np.array(flat, np.int32).reshape(arr.shape))


class CrossCol(Operation):
    """nn/ops/CrossCol.scala — hash-cross of several sparse columns.
    Host-side; inputs are equal-length columns."""

    def __init__(self, hash_bucket_size: int, name=None):
        super().__init__(name=name)
        self.hash_bucket_size = hash_bucket_size

    def _op(self, *cols):
        import zlib
        arrs = [np.asarray(c) for c in cols]
        out = []
        for row in zip(*[a.reshape(-1) for a in arrs]):
            key = "_X_".join(str(x) for x in row)
            out.append(zlib.crc32(key.encode()) % self.hash_bucket_size)
        return jnp.asarray(np.array(out, np.int32).reshape(arrs[0].shape))


class IndicatorCol(Operation):
    """nn/ops/IndicatorCol.scala — category indices → multi-hot row."""

    def __init__(self, feat_len: int, name=None):
        super().__init__(name=name)
        self.feat_len = feat_len

    def _op(self, a):
        oh = jax.nn.one_hot(a.astype(jnp.int32), self.feat_len)
        if oh.ndim > 2:
            oh = jnp.max(oh, axis=-2)
        return oh


class Kv2Tensor(Operation):
    """nn/ops/Kv2Tensor.scala — 'k:v,k:v' strings → dense row. Host-side."""

    def __init__(self, kv_delimiter: str = ",", item_delimiter: str = ":",
                 feat_len: int = 0, name=None):
        super().__init__(name=name)
        self.kv_delimiter, self.item_delimiter = kv_delimiter, item_delimiter
        self.feat_len = feat_len

    def _op(self, a):
        arr = np.asarray(a).reshape(-1)
        out = np.zeros((len(arr), self.feat_len), np.float32)
        for r, s in enumerate(arr):
            for item in str(s).split(self.kv_delimiter):
                if not item:
                    continue
                k, _, v = item.partition(self.item_delimiter)
                idx = int(k)
                if 0 <= idx < self.feat_len:
                    out[r, idx] = float(v or 0.0)
        return jnp.asarray(out)


class MkString(Operation):
    """nn/ops/MkString.scala — join a row's values into one string.
    Host-side; returns a numpy object array."""

    def __init__(self, str_delimiter: str = ",", name=None):
        super().__init__(name=name)
        self.str_delimiter = str_delimiter

    def _op(self, a):
        arr = np.asarray(a)
        return np.array([self.str_delimiter.join(str(x) for x in row)
                         for row in arr.reshape(arr.shape[0], -1)],
                        dtype=object)


class Substr(Operation):
    """nn/ops/Substr.scala — substring of a string column. Host-side."""

    def __init__(self, pos: int, length: int, name=None):
        super().__init__(name=name)
        self.pos, self.length = pos, length

    def _op(self, a):
        arr = np.asarray(a)
        return np.array([str(x)[self.pos:self.pos + self.length]
                         for x in arr.reshape(-1)],
                        dtype=object).reshape(arr.shape)


class RangeOps(Operation):
    """nn/ops/RangeOps.scala — Table(start, limit, delta) → arange.

    The bounds must be concrete (host values or consts): the output length
    is data-dependent, which XLA cannot trace — same restriction the
    graph loader resolves by const-folding Range nodes."""

    def _op(self, start, limit, delta):
        return jnp.arange(int(np.asarray(start)), int(np.asarray(limit)),
                          int(np.asarray(delta)))


class DepthwiseConv2D(Operation):
    """nn/ops/DepthwiseConv2D.scala — Table(input, filter) depthwise conv.

    ``filter`` uses the TF layout (kh, kw, in_channels, channel_multiplier);
    output channels = in_channels * channel_multiplier."""

    def __init__(self, stride_w=1, stride_h=1, pad_w=0, pad_h=0,
                 data_format="NHWC", name=None):
        super().__init__(name=name)
        self.stride_w, self.stride_h = stride_w, stride_h
        self.pad_w, self.pad_h = pad_w, pad_h
        assert data_format in ("NHWC", "NCHW"), data_format
        self.data_format = data_format

    def _op(self, x, w):
        from jax import lax
        kh, kw, cin, mult = w.shape
        fmt = self.data_format
        pads = [(self.pad_h, self.pad_h), (self.pad_w, self.pad_w)]
        if fmt == "NHWC":
            # HWIO with I=1 and O grouped cin-major (matches group count)
            rhs, spec = w.reshape(kh, kw, 1, cin * mult), "HWIO"
        else:
            rhs = jnp.transpose(w, (2, 3, 0, 1)).reshape(cin * mult, 1,
                                                         kh, kw)
            spec = "OIHW"
        return lax.conv_general_dilated(
            x, rhs, (self.stride_h, self.stride_w), pads,
            dimension_numbers=(fmt, spec, fmt),
            feature_group_count=cin)


__all__ = [
    "Operation", "RangeOps", "DepthwiseConv2D",
    "Compare", "Assert", "NoOp", "ControlDependency", "BiasAdd",
    "TensorModuleWrapper",
    "Equal", "NotEqual", "ApproximateEqual", "Greater",
    "GreaterEqual", "Less", "LessEqual", "LogicalAnd", "LogicalOr",
    "LogicalNot", "All", "Any", "Sum", "Prod", "Max", "Min", "Mean",
    "Exp", "Expm1", "Log1p", "Floor", "Ceil", "Round", "Rint", "Sign",
    "Inv", "Erf", "Erfc", "Lgamma", "Digamma", "IsFinite", "IsInf",
    "IsNan", "Pow", "Maximum", "Minimum", "FloorDiv", "FloorMod", "Mod",
    "TruncateDiv", "SquaredDifference", "Shape", "Rank", "Cast", "Gather",
    "Select", "Slice", "StridedSlice", "Tile", "OneHot", "TopK", "InTopK",
    "ArgMax", "BatchMatMul", "SegmentSum", "Pad", "ExpandDims",
    "SplitAndSelect", "InvertPermutation", "Pack", "Split", "Unpack",
    "ResizeBilinear", "Dilation2D",
    "L2Loss", "CrossEntropy", "RandomUniform", "TruncatedNormal",
    "ModuleToOperation", "TensorOp", "BucketizedCol",
    "CategoricalColHashBucket", "CategoricalColVocaList", "CrossCol",
    "IndicatorCol", "Kv2Tensor", "MkString", "Substr",
]


class Compare(Operation):
    """Abstract base of the comparison ops (nn/ops/Compare.scala) — kept
    for API parity; concrete subclasses implement ``_cmp``."""

    def _cmp(self, a, b):
        raise NotImplementedError

    def _op(self, a, b):
        return self._cmp(jnp.asarray(a), jnp.asarray(b))


class Assert(Operation):
    """nn/tf/Assert — eager-checks a concrete predicate, passes data
    through. Under jit the check is skipped (XLA has no host asserts);
    DynamicGraph/eager paths enforce it."""

    def _op(self, pred, *data):
        import jax.errors
        try:
            ok = bool(np.asarray(pred).all())
        except (jax.errors.TracerArrayConversionError,
                jax.errors.ConcretizationTypeError):
            # traced under jit — no concrete value; the check is skipped
            # (XLA has no host asserts). Any OTHER error in evaluating the
            # predicate must surface, not silently disable the assertion.
            return data[0] if len(data) == 1 else Table(*data)
        if not ok:  # a plain `assert` would be stripped under python -O
            raise ValueError("Assert op failed")
        return data[0] if len(data) == 1 else Table(*data)


class NoOp(Operation):
    """nn/tf/NoOp — control-dependency placeholder; identity."""

    def _op(self, *xs):
        return xs[0] if xs else jnp.zeros(())


class ControlDependency(NoOp):
    """nn/tf/ControlDependency — on XLA, data dependencies ARE the
    schedule; this passes its first input through unchanged."""


class BiasAdd(Operation):
    """nn/tf/BiasAdd — add a 1-D bias over the trailing (channel) dim."""

    def _op(self, x, bias):
        return x + jnp.asarray(bias).reshape(
            (1,) * (jnp.asarray(x).ndim - 1) + (-1,))


class TensorModuleWrapper(ModuleToOperation):
    """nn/tf/TensorModuleWrapper — alias of ModuleToOperation here (both
    lift a TensorModule into the op world)."""
