from .optim_method import (OptimMethod, SGD, Adam, ParallelAdam, AdamW, Adagrad,
                           Adadelta, Adamax, RMSprop, Ftrl, LarsSGD, LBFGS,
                           LearningRateSchedule, Default, Poly, Step,
                           MultiStep, EpochStep, EpochDecay, NaturalExp,
                           Exponential, Warmup, CosineAnnealing, SequentialSchedule, Regime,
                           EpochSchedule, Plateau, EpochDecayWithWarmUp)
from .regularizer import (Regularizer, L1Regularizer, L2Regularizer,
                          L1L2Regularizer)
from .trigger import (Trigger, every_epoch, several_iteration, max_epoch,
                      max_iteration, max_score, min_loss, and_, or_,
                      EveryEpoch, SeveralIteration, MaxEpoch, MaxIteration,
                      MaxScore, MinLoss, TriggerAnd, TriggerOr)
from .validation import (ValidationMethod, ValidationResult, AccuracyResult,
                         LossResult, Top1Accuracy, Top5Accuracy, Loss, MAE,
                         HitRatio, NDCG, TreeNNAccuracy)
from .optimizer import (Optimizer, LocalOptimizer, DistriOptimizer,
                        ParallelOptimizer, BaseOptimizer, Metrics)
from .evaluator import Evaluator, LocalValidator, DistriValidator
from .predictor import Predictor, PredictionService

# pyspark optim/optimizer.py also exposes these from the optim namespace
from ..visualization import TrainSummary, ValidationSummary  # noqa: E402
from ..nn.criterion import ActivityRegularization  # noqa: E402
