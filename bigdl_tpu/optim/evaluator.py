"""Evaluator (parity: reference ``optim/Evaluator.scala`` /
``optim/LocalValidator.scala`` / ``optim/DistriValidator.scala``)."""
from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp

from .. import observability as obs
from ..dataset.dataset import AbstractDataSet, ShardedDataSet
from .staging import staged
from ..utils import engine
from ..utils.table import Table


class Evaluator:
    def __init__(self, model, prefetch_depth: int = 2):
        self.model = model
        self.prefetch_depth = prefetch_depth
        self._fwd = None

    def _forward_fn(self):
        if self._fwd is None:
            model = self.model
            engine.maybe_enable_compilation_cache()

            def fwd(params, state, x):
                out, _ = model.apply(params, state, x, training=False)
                return out
            self._fwd = jax.jit(fwd)
        return self._fwd

    @staticmethod
    def _stage(mb):
        """Host batch -> (device input, host MiniBatch); runs on the
        stager thread so the next batch transfers while the current one
        evaluates (the host-side target stays host-resident for the
        numpy metric methods)."""
        from .staging import place_host_value
        return place_host_value(mb.get_input()), mb

    def evaluate(self, dataset: AbstractDataSet, methods: List,
                 batch_size: int = 32):
        self.model.ensure_initialized()
        fwd = self._forward_fn()
        batched = ShardedDataSet(dataset, batch_size, drop_last=False)
        results = [None] * len(methods)
        batches = staged(batched.data(train=False), self._stage,
                         depth=self.prefetch_depth, name="eval_stager")
        try:
            for x, mb in batches:
                sp = obs.span("eval/batch")
                with sp:
                    out = fwd(self.model.params, self.model.state, x)
                    for i, m in enumerate(methods):
                        r = m(out, mb.get_target())
                        results[i] = r if results[i] is None \
                            else results[i] + r
                if obs.enabled():
                    # one clock source: the histogram reads the span's own
                    # duration rather than timing the interval a second time
                    obs.histogram("eval/batch_s", unit="s").observe(
                        sp.duration_s)
        finally:
            batches.close()
        return results


class LocalValidator(Evaluator):
    """Name parity: optim/LocalValidator.scala (same engine here)."""


class DistriValidator(Evaluator):
    """Name parity: optim/DistriValidator.scala — validation batches shard
    over the engine mesh exactly like training ones (XLA owns the split)."""
