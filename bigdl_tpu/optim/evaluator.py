"""Evaluator (parity: reference ``optim/Evaluator.scala`` /
``optim/LocalValidator.scala`` / ``optim/DistriValidator.scala``)."""
from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp

from .. import observability as obs
from ..dataset.dataset import AbstractDataSet, ShardedDataSet
from ..utils.table import Table


class Evaluator:
    def __init__(self, model):
        self.model = model
        self._fwd = None

    def _forward_fn(self):
        if self._fwd is None:
            model = self.model

            def fwd(params, state, x):
                out, _ = model.apply(params, state, x, training=False)
                return out
            self._fwd = jax.jit(fwd)
        return self._fwd

    def evaluate(self, dataset: AbstractDataSet, methods: List,
                 batch_size: int = 32):
        self.model.ensure_initialized()
        fwd = self._forward_fn()
        batched = ShardedDataSet(dataset, batch_size, drop_last=False)
        results = [None] * len(methods)
        for mb in batched.data(train=False):
            sp = obs.span("eval/batch")
            with sp:
                x = mb.get_input()
                x = jax.tree_util.tree_map(jnp.asarray, x) \
                    if isinstance(x, Table) else jnp.asarray(x)
                out = fwd(self.model.params, self.model.state, x)
                for i, m in enumerate(methods):
                    r = m(out, mb.get_target())
                    results[i] = r if results[i] is None else results[i] + r
            if obs.enabled():
                # one clock source: the histogram reads the span's own
                # duration rather than timing the interval a second time
                obs.histogram("eval/batch_s", unit="s").observe(
                    sp.duration_s)
        return results


class LocalValidator(Evaluator):
    """Name parity: optim/LocalValidator.scala (same engine here)."""


class DistriValidator(Evaluator):
    """Name parity: optim/DistriValidator.scala — validation batches shard
    over the engine mesh exactly like training ones (XLA owns the split)."""
