"""Evaluator (parity: reference ``optim/Evaluator.scala`` /
``optim/LocalValidator.scala`` / ``optim/DistriValidator.scala``)."""
from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp

from .. import observability as obs
from ..dataset.dataset import AbstractDataSet, ShardedDataSet
from .staging import staged
from ..utils import engine
from ..utils.table import Table


def _stack_tree(items):
    """[pytree, ...] (equal leaf shapes) -> one pytree of [K, ...]
    device stacks — the evaluator/predictor superstep's group assembly,
    run on the STAGER thread like the optimizer's (optim/staging.py)."""
    return jax.tree_util.tree_map(lambda *leaves: jnp.stack(leaves), *items)


def _tree_shape_key(item):
    """Group key: leaf shapes+dtypes — a ragged epoch tail forms its own
    (smaller) group instead of failing the stack."""
    return tuple((tuple(l.shape), str(l.dtype))
                 for l in jax.tree_util.tree_leaves(item))


class Evaluator:
    def __init__(self, model, prefetch_depth: int = 2):
        self.model = model
        self.prefetch_depth = prefetch_depth
        self._fwd = None
        self._fwd_stats = None
        self._superstep = 1

    def set_superstep(self, k: int):
        """Fuse K evaluation batches into ONE compiled dispatch — a
        ``lax.scan`` forward with stacked per-method stats accumulation,
        the forward-loop analog of ``Optimizer.set_superstep`` (ROADMAP
        deferred item): per-batch dispatch envelope is paid once per K
        batches, and the per-epoch readback stays ONE summed stats
        vector. Applies to the device-stats path (every built-in
        ValidationMethod); the host-metric fallback evaluates per batch
        regardless. ``eval/dispatches`` counts compiled calls — the
        K-fold drop is asserted in tests/test_superstep.py."""
        if k < 1:
            raise ValueError(f"superstep must be >= 1, got {k}")
        self._superstep = int(k)
        self._fwd_stats = None  # scan program differs — rebuild
        return self

    def _forward_fn(self):
        if self._fwd is None:
            model = self.model
            engine.maybe_enable_compilation_cache()

            def fwd(params, state, x):
                out, _ = model.apply(params, state, x, training=False)
                return out
            self._fwd = obs.perf.instrument_jit(
                jax.jit(fwd), name="eval/forward", kind="forward",
                key_argnums=(2,))
        return self._fwd

    def _forward_stats_fn(self, methods):
        """Forward + per-method device stats in ONE jitted program, so
        the batch loop accumulates stats sums on device and never pulls
        the (large) output tensor to host. With ``set_superstep(K)`` the
        program is a ``lax.scan`` over a [K, B, ...] batch stack whose
        K per-batch stats vectors sum INSIDE the program — K batches,
        one dispatch, still one number-vector out."""
        # key by the method OBJECTS (strong refs — an id()-keyed cache
        # could collide with a recycled address after the old list dies)
        key = tuple(methods)
        if self._fwd_stats is None or len(self._fwd_stats[0]) != len(key) \
                or any(a is not b for a, b in zip(self._fwd_stats[0], key)):
            model = self.model
            engine.maybe_enable_compilation_cache()

            if self._superstep > 1:
                def fwd_stats(params, state, xs, ys):
                    def body(_, xy):
                        x, y = xy
                        out, _s = model.apply(params, state, x,
                                              training=False)
                        return None, tuple(m.device_stats(out, y)
                                           for m in methods)
                    _, stacked = jax.lax.scan(body, None, (xs, ys))
                    return tuple(jnp.sum(s, axis=0) for s in stacked)
                name = "eval/forward_stats_scan"
            else:
                def fwd_stats(params, state, x, y):
                    out, _ = model.apply(params, state, x, training=False)
                    return tuple(m.device_stats(out, y) for m in methods)
                name = "eval/forward_stats"
            self._fwd_stats = (key, obs.perf.instrument_jit(
                jax.jit(fwd_stats), name=name,
                kind="forward", key_argnums=(2, 3)))
        return self._fwd_stats[1]

    @staticmethod
    def _stage(mb):
        """Host batch -> (device input, host MiniBatch); runs on the
        stager thread so the next batch transfers while the current one
        evaluates (the host-side target stays host-resident for the
        numpy metric methods)."""
        from .staging import place_host_value
        return place_host_value(mb.get_input()), mb

    @staticmethod
    def _stage_device(mb):
        """Device-accumulation staging: input AND target transfer on the
        stager thread — the batch loop then touches no host arrays at
        all (stats stay device-resident until the per-epoch readback)."""
        from .staging import place_host_value
        return place_host_value(mb.get_input()), \
            place_host_value(mb.get_target())

    def evaluate(self, dataset: AbstractDataSet, methods: List,
                 batch_size: int = 32):
        self.model.ensure_initialized()
        if all(m.supports_device_stats() for m in methods):
            return self._evaluate_device(dataset, methods, batch_size)
        return self._evaluate_host(dataset, methods, batch_size)

    def _evaluate_device(self, dataset, methods, batch_size):
        """Device-side metric accumulation: per-batch stats vectors sum
        into device-resident accumulators across the whole loop and the
        totals read back ONCE per epoch — the batch loop itself is
        sync-free (ROADMAP open item #4)."""
        fwd_stats = self._forward_stats_fn(methods)
        k = self._superstep
        batched = ShardedDataSet(dataset, batch_size, drop_last=False)
        acc = None
        batches = staged(batched.data(train=False), self._stage_device,
                         depth=self.prefetch_depth, name="eval_stager",
                         group=k,
                         group_fn=_stack_tree if k > 1 else None,
                         group_key=_tree_shape_key if k > 1 else None)
        try:
            for x, y in batches:
                # superstep: (x, y) is a [j<=K, B, ...] device stack and
                # this ONE dispatch scans all j batches
                sp = obs.span("eval/batch")
                with sp:
                    stats = fwd_stats(self.model.params, self.model.state,
                                      x, y)
                    acc = stats if acc is None else tuple(
                        a + s for a, s in zip(acc, stats))
                if obs.enabled():
                    obs.counter("eval/dispatches").inc()
                    obs.histogram("eval/batch_s", unit="s").observe(
                        sp.duration_s)
        finally:
            batches.close()
        if acc is None:
            return [None] * len(methods)
        # sync-ok: the ONE per-epoch readback of the summed stats
        host = jax.device_get(acc)
        if obs.enabled():
            obs.counter("eval/metric_readbacks").inc()
        return [m.result_from_stats(s) for m, s in zip(methods, host)]

    def _evaluate_host(self, dataset, methods, batch_size):
        """Per-batch numpy metric path (methods without device stats —
        rank-based metrics like HitRatio/NDCG)."""
        fwd = self._forward_fn()
        batched = ShardedDataSet(dataset, batch_size, drop_last=False)
        results = [None] * len(methods)
        batches = staged(batched.data(train=False), self._stage,
                         depth=self.prefetch_depth, name="eval_stager")
        try:
            for x, mb in batches:
                sp = obs.span("eval/batch")
                with sp:
                    out = fwd(self.model.params, self.model.state, x)
                    for i, m in enumerate(methods):
                        r = m(out, mb.get_target())
                        results[i] = r if results[i] is None \
                            else results[i] + r
                if obs.enabled():
                    obs.counter("eval/dispatches").inc()
                    # one clock source: the histogram reads the span's own
                    # duration rather than timing the interval a second time
                    obs.histogram("eval/batch_s", unit="s").observe(
                        sp.duration_s)
        finally:
            batches.close()
        return results


class LocalValidator(Evaluator):
    """Name parity: optim/LocalValidator.scala (same engine here)."""


class DistriValidator(Evaluator):
    """Name parity: optim/DistriValidator.scala — validation batches shard
    over the engine mesh exactly like training ones (XLA owns the split)."""
