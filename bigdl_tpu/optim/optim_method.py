"""Optimization methods + learning-rate schedules.

Parity: reference ``optim/OptimMethod.scala``, ``optim/SGD.scala`` (incl. the
full LearningRateSchedule family), ``optim/Adam.scala``,
``optim/ParallelAdam.scala``, ``optim/Adagrad.scala``, ``optim/Adadelta.scala``,
``optim/Adamax.scala``, ``optim/RMSprop.scala``, ``optim/Ftrl.scala``,
``optim/LarsSGD.scala``, ``optim/LBFGS.scala`` + ``optim/LineSearch.scala``.

Design: each method holds hyperparameters (python scalars, baked into the
trace) and exposes ``init_state(params) -> pytree`` and
``update(grads, params, state, lr) -> (new_params, new_state)`` — pure,
jit-able, tree-mapped. Schedules run host-side each step (they are control
logic, not compute) and feed ``lr`` in as a scalar argument, so changing lr
never retraces the step.

The reference's ParallelAdam (multi-threaded sharded update) maps to
DistriOptimizer's ZeRO-style sharded update in
``bigdl_tpu/parallel/allreduce.py``; the math here is identical to Adam.
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

_tmap = jax.tree_util.tree_map


def _keep_dtype(new_params, params):
    """Updates must not promote param dtype (bf16 params stay bf16 even
    with an f32 lr scalar — promotion would retrace every conv)."""
    return _tmap(lambda n, o: n.astype(o.dtype), new_params, params)


# ---------------------------------------------------------------------------
# Learning-rate schedules (parity: optim/SGD.scala:200-700)
# ---------------------------------------------------------------------------
class LearningRateSchedule:
    """Host-side schedule. ``update_lr(method_state) -> lr`` where
    method_state carries 'neval' (iterations so far, 0-based), 'epoch'
    (1-based), optionally 'score'/'loss'."""

    def update_lr(self, lr, state):
        raise NotImplementedError


class Default(LearningRateSchedule):
    """lr / (1 + neval * learningRateDecay) (SGD.scala:500)."""

    def __init__(self):
        self.decay = 0.0  # set by SGD from learningrate_decay

    def update_lr(self, lr, state):
        return lr / (1.0 + state["neval"] * self.decay)


class Poly(LearningRateSchedule):
    """lr * (1 - neval/maxIteration)^power (SGD.scala:290)."""

    def __init__(self, power: float, max_iteration: int):
        self.power, self.max_iteration = power, max_iteration

    def update_lr(self, lr, state):
        if state["neval"] >= self.max_iteration:
            return 0.0
        return lr * (1.0 - state["neval"] / self.max_iteration) ** self.power


class Step(LearningRateSchedule):
    """lr * gamma^(floor(neval/stepSize)) (SGD.scala:329)."""

    def __init__(self, step_size: int, gamma: float):
        self.step_size, self.gamma = step_size, gamma

    def update_lr(self, lr, state):
        return lr * self.gamma ** (state["neval"] // self.step_size)


class MultiStep(LearningRateSchedule):
    """(SGD.scala:360)."""

    def __init__(self, step_sizes, gamma: float):
        self.step_sizes, self.gamma = list(step_sizes), gamma

    def update_lr(self, lr, state):
        n = sum(1 for s in self.step_sizes if state["neval"] >= s)
        return lr * self.gamma ** n


class EpochStep(LearningRateSchedule):
    """lr * gamma^(floor((epoch-1)/stepSize)) (SGD.scala:423)."""

    def __init__(self, step_size: int, gamma: float):
        self.step_size, self.gamma = step_size, gamma

    def update_lr(self, lr, state):
        return lr * self.gamma ** ((state["epoch"] - 1) // self.step_size)


class EpochDecay(LearningRateSchedule):
    """lr * 0.1^decayType(epoch) (SGD.scala:397)."""

    def __init__(self, decay_type):
        self.decay_type = decay_type

    def update_lr(self, lr, state):
        return lr * 0.1 ** self.decay_type(state["epoch"])


class NaturalExp(LearningRateSchedule):
    """lr * exp(-gamma * floor(neval/decayStep)) (SGD.scala:455)."""

    def __init__(self, decay_step: int, gamma: float):
        self.decay_step, self.gamma = decay_step, gamma

    def update_lr(self, lr, state):
        return lr * math.exp(-self.gamma * (state["neval"] // self.decay_step))


class Exponential(LearningRateSchedule):
    """lr * decayRate^(neval/decayStep) (SGD.scala:476)."""

    def __init__(self, decay_step: int, decay_rate: float,
                 stair_case: bool = False):
        self.decay_step, self.decay_rate = decay_step, decay_rate
        self.stair_case = stair_case

    def update_lr(self, lr, state):
        p = state["neval"] / self.decay_step
        if self.stair_case:
            p = math.floor(p)
        return lr * self.decay_rate ** p


class Warmup(LearningRateSchedule):
    """lr + delta * neval (SGD.scala:599; used inside SequentialSchedule)."""

    def __init__(self, delta: float):
        self.delta = delta

    def update_lr(self, lr, state):
        return lr + self.delta * state["neval"]


class CosineAnnealing(LearningRateSchedule):
    """Cosine decay lr → min_lr over ``max_iteration`` steps, optionally
    restarting (SGDR). Beyond the reference's 14 schedules — the
    transformer-era default; compose with Warmup via SequentialSchedule
    for the standard warmup+cosine recipe."""

    def __init__(self, max_iteration: int, min_lr: float = 0.0,
                 restarts: bool = False, t_mult: float = 1.0):
        self.max_iteration = max_iteration
        self.min_lr = min_lr
        self.restarts = restarts
        self.t_mult = t_mult

    def update_lr(self, lr, state):
        import math as _m
        t = state["neval"]
        period = self.max_iteration
        if self.restarts:
            # walk the restart periods (period *= t_mult each cycle)
            while t >= period:
                t -= period
                period = max(1, int(period * self.t_mult))
        else:
            t = min(t, period)
        cos = 0.5 * (1.0 + _m.cos(_m.pi * t / period))
        return self.min_lr + (lr - self.min_lr) * cos


class SequentialSchedule(LearningRateSchedule):
    """Chain schedules, each active for maxIteration steps (SGD.scala:623)."""

    def __init__(self, iteration_per_epoch: int = 1):
        self.iteration_per_epoch = iteration_per_epoch
        self.schedules = []  # (schedule, max_iter)

    def add(self, schedule, max_iteration: int):
        self.schedules.append((schedule, max_iteration))
        return self

    def update_lr(self, lr, state):
        n = state["neval"]
        offset = 0
        for sched, mx in self.schedules:
            if n < offset + mx or (sched, mx) == self.schedules[-1]:
                sub = dict(state)
                sub["neval"] = n - offset
                sub["epoch"] = max(1, (n - offset) // self.iteration_per_epoch + 1)
                return sched.update_lr(lr, sub)
            offset += mx
        return lr


class Regime:
    """(SGD.scala:526)."""

    def __init__(self, start_epoch: int, end_epoch: int, config: dict):
        self.start_epoch, self.end_epoch, self.config = \
            start_epoch, end_epoch, config


class EpochSchedule(LearningRateSchedule):
    """Per-epoch-range config regimes (SGD.scala:233)."""

    def __init__(self, regimes):
        self.regimes = list(regimes)

    def update_lr(self, lr, state):
        e = state["epoch"]
        for r in self.regimes:
            if r.start_epoch <= e <= r.end_epoch:
                return r.config.get("learningRate",
                                    r.config.get("learning_rate", lr))
        return lr


class Plateau(LearningRateSchedule):
    """Reduce on plateau of a monitored metric (SGD.scala:544)."""

    def __init__(self, monitor: str = "score", factor: float = 0.1,
                 patience: int = 10, mode: str = "min", epsilon: float = 1e-4,
                 cooldown: int = 0, min_lr: float = 0.0):
        self.monitor, self.factor, self.patience = monitor, factor, patience
        self.mode, self.epsilon, self.cooldown = mode, epsilon, cooldown
        self.min_lr = min_lr
        self.best = None
        self.wait = 0
        self.cooldown_counter = 0
        self.multiplier = 1.0

    def _better(self, cur, best):
        if self.mode == "min":
            return cur < best - self.epsilon
        return cur > best + self.epsilon

    def update_lr(self, lr, state):
        cur = state.get(self.monitor)
        if cur is not None:
            if self.best is None or self._better(cur, self.best):
                self.best = cur
                self.wait = 0
            elif self.cooldown_counter > 0:
                self.cooldown_counter -= 1
                self.wait = 0
            else:
                self.wait += 1
                if self.wait >= self.patience:
                    self.multiplier *= self.factor
                    self.wait = 0
                    self.cooldown_counter = self.cooldown
        return max(lr * self.multiplier, self.min_lr)

    def force_reduction(self) -> float:
        """Apply one factor reduction NOW, regardless of the patience
        counter — the hook anomaly-driven control uses when the health
        layer's ``health/plateau`` detector (which watches the per-step
        loss the loop already syncs, not the per-epoch validation score
        this schedule polls) fires first. Resets the patience window
        and enters cooldown exactly as a patience-driven reduction
        would; returns the new multiplier."""
        self.multiplier *= self.factor
        self.wait = 0
        self.cooldown_counter = self.cooldown
        return self.multiplier


class EpochDecayWithWarmUp(LearningRateSchedule):
    """Linear warmup then epoch decay (SGD.scala:671)."""

    def __init__(self, warmup_iteration: int, warmup_delta: float, decay_type):
        self.warmup_iteration = warmup_iteration
        self.warmup_delta = warmup_delta
        self.decay_type = decay_type

    def update_lr(self, lr, state):
        if state["neval"] < self.warmup_iteration:
            return lr + self.warmup_delta * state["neval"]
        return (lr + self.warmup_delta * self.warmup_iteration) * \
            0.1 ** self.decay_type(state["epoch"])


# ---------------------------------------------------------------------------
# Optim methods
# ---------------------------------------------------------------------------
class OptimMethod:
    """Base (parity: optim/OptimMethod.scala)."""

    def __init__(self, learningrate: float = 1e-3):
        self.learningrate = learningrate
        self.state = {"neval": 0, "epoch": 1}

    def init_state(self, params):
        return {}

    def update(self, grads, params, opt_state, lr):
        raise NotImplementedError

    def save(self, path, overwrite=True):
        """Persist this optim method incl. hyper-params and step state
        (parity: OptimMethod.save). Atomic tmp+rename write — a crash
        mid-dump must not destroy the previous valid save."""
        import os
        if not overwrite and os.path.exists(path):
            raise IOError(f"{path} exists and overwrite=False")
        from .optimizer import _atomic_pickle
        _atomic_pickle(path, self)
        return self

    @staticmethod
    def load(path):
        """Load an optim method saved by :meth:`save` (parity:
        OptimMethod.load)."""
        import pickle
        with open(path, "rb") as f:
            m = pickle.load(f)
        if not isinstance(m, OptimMethod):
            raise TypeError(f"{path} does not contain an OptimMethod "
                            f"(got {type(m).__name__})")
        return m

    def get_learning_rate(self):
        return self.current_lr()

    def current_lr(self):
        return self.learningrate

    def current_lr_vector(self, k: int):
        """Learning rates for the next ``k`` steps — the schedule
        vectorization a superstep dispatch needs: ``[lr(neval), ...,
        lr(neval + k - 1)]`` precomputed host-side so K fused updates
        each see exactly the lr the K=1 loop would have fed them.
        Implemented by advancing ``state['neval']`` through the window
        (so stateful schedules observe one ``update_lr`` call per step,
        same as K=1) and restoring it; loss/score-driven schedules see
        the values as of the superstep start — the same observation lag
        ``window:K`` introduces."""
        if k == 1:
            return [self.current_lr()]
        n0 = self.state["neval"]
        try:
            lrs = []
            for i in range(k):
                self.state["neval"] = n0 + i
                lrs.append(self.current_lr())
        finally:
            self.state["neval"] = n0
        return lrs

    def clone(self):
        import copy
        return copy.deepcopy(self)

    # reference-style optimize() on a feval closure, for LBFGS parity and
    # unit tests of a single method on a flat vector
    def optimize(self, feval, x):
        if not hasattr(self, "_flat_state"):
            self._flat_state = self.init_state(x)
        loss, g = feval(x)
        lr = self.current_lr()
        new_x, self._flat_state = self.update(g, x, self._flat_state, lr)
        self.state["neval"] += 1
        return new_x, [loss]


class SGD(OptimMethod):
    """optim/SGD.scala:39 — momentum/dampening/nesterov/weightDecay +
    schedule family."""

    def __init__(self, learningrate: float = 1e-3,
                 learningrate_decay: float = 0.0, weightdecay: float = 0.0,
                 momentum: float = 0.0, dampening: Optional[float] = None,
                 nesterov: bool = False, learningrate_schedule=None,
                 **_ignored):
        super().__init__(learningrate)
        self.learningrate_decay = learningrate_decay
        self.weightdecay = weightdecay
        self.momentum = momentum
        self.dampening = momentum if dampening is None else dampening
        self.nesterov = nesterov
        if learningrate_schedule is None:
            learningrate_schedule = Default()
        if isinstance(learningrate_schedule, Default):
            learningrate_schedule.decay = learningrate_decay
        self.learningrate_schedule = learningrate_schedule
        if nesterov and (momentum <= 0 or self.dampening != 0):
            # match reference require: nesterov needs momentum, zero dampening
            self.dampening = 0.0

    def current_lr(self):
        return self.learningrate_schedule.update_lr(self.learningrate,
                                                    self.state)

    def init_state(self, params):
        if self.momentum <= 0:
            return {}
        return {"v": _tmap(jnp.zeros_like, params)}

    def update(self, grads, params, opt_state, lr):
        wd, mom, damp = self.weightdecay, self.momentum, self.dampening
        if wd > 0:
            grads = _tmap(lambda g, w: g + wd * w, grads, params)
        if mom > 0:
            v = _tmap(lambda v, g: mom * v + (1 - damp) * g,
                      opt_state["v"], grads)
            if self.nesterov:
                grads = _tmap(lambda g, vv: g + mom * vv, grads, v)
            else:
                grads = v
            new_state = {"v": v}
        else:
            new_state = opt_state
        new_params = _tmap(lambda w, g: w - lr * g, params, grads)
        return _keep_dtype(new_params, params), new_state


class Adam(OptimMethod):
    """optim/Adam.scala."""

    def __init__(self, learningrate: float = 1e-3,
                 learningrate_decay: float = 0.0, beta1: float = 0.9,
                 beta2: float = 0.999, epsilon: float = 1e-8, **_ignored):
        super().__init__(learningrate)
        self.learningrate_decay = learningrate_decay
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def current_lr(self):
        return self.learningrate / (1 + self.state["neval"] *
                                    self.learningrate_decay)

    def init_state(self, params):
        return {"m": _tmap(jnp.zeros_like, params),
                "v": _tmap(jnp.zeros_like, params),
                "t": jnp.zeros((), jnp.int32)}

    def update(self, grads, params, opt_state, lr):
        b1, b2, eps = self.beta1, self.beta2, self.epsilon
        t = opt_state["t"] + 1
        m = _tmap(lambda m, g: b1 * m + (1 - b1) * g, opt_state["m"], grads)
        v = _tmap(lambda v, g: b2 * v + (1 - b2) * g * g, opt_state["v"], grads)
        bc1 = 1 - b1 ** t.astype(jnp.float32)
        bc2 = 1 - b2 ** t.astype(jnp.float32)
        new_params = _tmap(
            lambda w, mm, vv: w - lr * (mm / bc1) /
            (jnp.sqrt(vv / bc2) + eps), params, m, v)
        return _keep_dtype(new_params, params), {"m": m, "v": v, "t": t}


class ParallelAdam(Adam):
    """optim/ParallelAdam.scala — identical math; the parallel (sharded)
    update is realised by DistriOptimizer's ZeRO path."""


class AdamW(Adam):
    """Adam with DECOUPLED weight decay (Loshchilov & Hutter) — beyond the
    reference (its optimizers only know L2-via-gradient regularizers,
    which Adam's preconditioner distorts). Decay applies directly to the
    weights at the scheduled lr, outside the moment estimates — the
    de-facto transformer training default.

    ``decay_filter(leaf) -> bool`` selects which leaves decay; the
    default (ndim >= 2) excludes biases and norm scales/offsets, matching
    the standard transformer recipe. Pass ``lambda w: True`` to decay
    everything."""

    def __init__(self, learningrate: float = 1e-3,
                 learningrate_decay: float = 0.0, beta1: float = 0.9,
                 beta2: float = 0.999, epsilon: float = 1e-8,
                 weight_decay: float = 0.01, decay_filter=None, **_ignored):
        super().__init__(learningrate, learningrate_decay, beta1, beta2,
                         epsilon)
        self.weight_decay = weight_decay
        self.decay_filter = decay_filter

    def update(self, grads, params, opt_state, lr):
        new_params, new_state = super().update(grads, params, opt_state, lr)
        if self.weight_decay:
            wd = self.weight_decay
            keep = self.decay_filter or (lambda w: w.ndim >= 2)
            new_params = _tmap(
                lambda nw, w: nw - lr * wd * w if keep(w) else nw,
                new_params, params)
            new_params = _keep_dtype(new_params, params)
        return new_params, new_state


class Adagrad(OptimMethod):
    """optim/Adagrad.scala."""

    def __init__(self, learningrate: float = 1e-3,
                 learningrate_decay: float = 0.0, weightdecay: float = 0.0,
                 **_ignored):
        super().__init__(learningrate)
        self.learningrate_decay = learningrate_decay
        self.weightdecay = weightdecay

    def current_lr(self):
        return self.learningrate / (1 + self.state["neval"] *
                                    self.learningrate_decay)

    def init_state(self, params):
        return {"accum": _tmap(jnp.zeros_like, params)}

    def update(self, grads, params, opt_state, lr):
        if self.weightdecay > 0:
            grads = _tmap(lambda g, w: g + self.weightdecay * w, grads, params)
        accum = _tmap(lambda a, g: a + g * g, opt_state["accum"], grads)
        new_params = _tmap(
            lambda w, g, a: w - lr * g / (jnp.sqrt(a) + 1e-10),
            params, grads, accum)
        return _keep_dtype(new_params, params), {"accum": accum}


class Adadelta(OptimMethod):
    """optim/Adadelta.scala (decayRate rho)."""

    def __init__(self, decayrate: float = 0.9, epsilon: float = 1e-10,
                 **_ignored):
        super().__init__(1.0)
        self.rho, self.epsilon = decayrate, epsilon

    def init_state(self, params):
        return {"accum": _tmap(jnp.zeros_like, params),
                "delta_accum": _tmap(jnp.zeros_like, params)}

    def update(self, grads, params, opt_state, lr):
        rho, eps = self.rho, self.epsilon
        accum = _tmap(lambda a, g: rho * a + (1 - rho) * g * g,
                      opt_state["accum"], grads)
        delta = _tmap(
            lambda g, a, d: g * jnp.sqrt(d + eps) / jnp.sqrt(a + eps),
            grads, accum, opt_state["delta_accum"])
        delta_accum = _tmap(lambda d, dl: rho * d + (1 - rho) * dl * dl,
                            opt_state["delta_accum"], delta)
        new_params = _tmap(lambda w, d: w - lr * d, params, delta)
        return _keep_dtype(new_params, params), {"accum": accum, "delta_accum": delta_accum}


class Adamax(OptimMethod):
    """optim/Adamax.scala."""

    def __init__(self, learningrate: float = 0.002, beta1: float = 0.9,
                 beta2: float = 0.999, epsilon: float = 1e-38, **_ignored):
        super().__init__(learningrate)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def init_state(self, params):
        return {"m": _tmap(jnp.zeros_like, params),
                "u": _tmap(jnp.zeros_like, params),
                "t": jnp.zeros((), jnp.int32)}

    def update(self, grads, params, opt_state, lr):
        b1, b2 = self.beta1, self.beta2
        t = opt_state["t"] + 1
        m = _tmap(lambda m, g: b1 * m + (1 - b1) * g, opt_state["m"], grads)
        u = _tmap(lambda u, g: jnp.maximum(b2 * u, jnp.abs(g) + self.epsilon),
                  opt_state["u"], grads)
        bc = 1 - b1 ** t.astype(jnp.float32)
        new_params = _tmap(lambda w, mm, uu: w - (lr / bc) * mm / uu,
                           params, m, u)
        return _keep_dtype(new_params, params), {"m": m, "u": u, "t": t}


class RMSprop(OptimMethod):
    """optim/RMSprop.scala."""

    def __init__(self, learningrate: float = 1e-2,
                 learningrate_decay: float = 0.0, decayrate: float = 0.99,
                 epsilon: float = 1e-8, **_ignored):
        super().__init__(learningrate)
        self.learningrate_decay = learningrate_decay
        self.decayrate, self.epsilon = decayrate, epsilon

    def current_lr(self):
        return self.learningrate / (1 + self.state["neval"] *
                                    self.learningrate_decay)

    def init_state(self, params):
        return {"accum": _tmap(jnp.zeros_like, params)}

    def update(self, grads, params, opt_state, lr):
        rho = self.decayrate
        accum = _tmap(lambda a, g: rho * a + (1 - rho) * g * g,
                      opt_state["accum"], grads)
        new_params = _tmap(
            lambda w, g, a: w - lr * g / (jnp.sqrt(a) + self.epsilon),
            params, grads, accum)
        return _keep_dtype(new_params, params), {"accum": accum}


class Ftrl(OptimMethod):
    """optim/Ftrl.scala — FTRL-proximal."""

    def __init__(self, learningrate: float = 1e-3,
                 learningrate_power: float = -0.5,
                 initial_accumulator_value: float = 0.1,
                 l1_regularization_strength: float = 0.0,
                 l2_regularization_strength: float = 0.0,
                 l2_shrinkage_regularization_strength: float = 0.0,
                 **_ignored):
        super().__init__(learningrate)
        self.lr_power = learningrate_power
        self.init_accum = initial_accumulator_value
        self.l1 = l1_regularization_strength
        self.l2 = l2_regularization_strength
        self.l2_shrinkage = l2_shrinkage_regularization_strength

    def init_state(self, params):
        return {"accum": _tmap(lambda p: jnp.full_like(p, self.init_accum),
                               params),
                "linear": _tmap(jnp.zeros_like, params)}

    def update(self, grads, params, opt_state, lr):
        lp = self.lr_power

        def upd(w, g, a, l):
            g_shrunk = g + 2 * self.l2_shrinkage * w
            new_a = a + g * g
            sigma = (jnp.power(new_a, -lp) - jnp.power(a, -lp)) / lr
            new_l = l + g_shrunk - sigma * w
            quad = jnp.power(new_a, -lp) / lr + 2 * self.l2
            l_reg = jnp.clip(new_l, -self.l1, self.l1)
            new_w = (l_reg - new_l) / quad
            return new_w, new_a, new_l

        flat_p, tdef = jax.tree_util.tree_flatten(params)
        flat_g = jax.tree_util.tree_leaves(grads)
        flat_a = jax.tree_util.tree_leaves(opt_state["accum"])
        flat_l = jax.tree_util.tree_leaves(opt_state["linear"])
        outs = [upd(w, g, a, l) for w, g, a, l in
                zip(flat_p, flat_g, flat_a, flat_l)]
        new_params = jax.tree_util.tree_unflatten(tdef, [o[0] for o in outs])
        accum = jax.tree_util.tree_unflatten(tdef, [o[1] for o in outs])
        linear = jax.tree_util.tree_unflatten(tdef, [o[2] for o in outs])
        return _keep_dtype(new_params, params), {"accum": accum, "linear": linear}


class LarsSGD(OptimMethod):
    """optim/LarsSGD.scala — layer-wise adaptive rate scaling. Trust ratio is
    computed per param leaf (≈ per layer tensor, as in the reference)."""

    def __init__(self, learningrate: float = 1e-2, trust: float = 1.0,
                 momentum: float = 0.9, weightdecay: float = 0.0,
                 learningrate_schedule=None, **_ignored):
        super().__init__(learningrate)
        self.trust, self.momentum, self.weightdecay = \
            trust, momentum, weightdecay
        self.learningrate_schedule = learningrate_schedule or Default()

    def current_lr(self):
        return self.learningrate_schedule.update_lr(self.learningrate,
                                                    self.state)

    def init_state(self, params):
        return {"v": _tmap(jnp.zeros_like, params)}

    def update(self, grads, params, opt_state, lr):
        def upd(w, g, v):
            wn = jnp.linalg.norm(w.reshape(-1))
            gn = jnp.linalg.norm(g.reshape(-1))
            local_lr = jnp.where(
                (wn > 0) & (gn > 0),
                self.trust * wn / (gn + self.weightdecay * wn + 1e-9),
                1.0)
            vv = self.momentum * v + lr * local_lr * \
                (g + self.weightdecay * w)
            return w - vv, vv

        flat_p, tdef = jax.tree_util.tree_flatten(params)
        flat_g = jax.tree_util.tree_leaves(grads)
        flat_v = jax.tree_util.tree_leaves(opt_state["v"])
        outs = [upd(w, g, v) for w, g, v in zip(flat_p, flat_g, flat_v)]
        new_params = jax.tree_util.tree_unflatten(tdef, [o[0] for o in outs])
        new_v = jax.tree_util.tree_unflatten(tdef, [o[1] for o in outs])
        return _keep_dtype(new_params, params), {"v": new_v}


class LBFGS(OptimMethod):
    """optim/LBFGS.scala — limited-memory BFGS with optional line search.
    Host-driven (two-loop recursion over flat vectors); ``optimize(feval, x)``
    is the entry point, matching the reference's full-batch usage."""

    def __init__(self, max_iter: int = 20, max_eval: Optional[float] = None,
                 tol_fun: float = 1e-5, tol_x: float = 1e-9,
                 n_correction: int = 100, learningrate: float = 1.0,
                 line_search: bool = False, **_ignored):
        super().__init__(learningrate)
        self.max_iter, self.tol_fun, self.tol_x = max_iter, tol_fun, tol_x
        self.n_correction = n_correction
        self.line_search = line_search
        self.max_eval = max_eval or max_iter * 1.25

    def optimize(self, feval, x):
        from jax.flatten_util import ravel_pytree
        x_flat, unravel = ravel_pytree(x)

        def f(v):
            loss, g = feval(unravel(v))
            return float(loss), ravel_pytree(g)[0]

        losses = []
        s_list, y_list, rho_list = [], [], []
        loss, g = f(x_flat)
        losses.append(loss)
        n_eval = 1
        for it in range(self.max_iter):
            if jnp.max(jnp.abs(g)) <= self.tol_fun:
                break
            # two-loop recursion
            q = g
            alphas = []
            for s, y, rho in zip(reversed(s_list), reversed(y_list),
                                 reversed(rho_list)):
                a = rho * jnp.dot(s, q)
                alphas.append(a)
                q = q - a * y
            if y_list:
                gamma = jnp.dot(s_list[-1], y_list[-1]) / \
                    jnp.dot(y_list[-1], y_list[-1])
                q = gamma * q
            for (s, y, rho), a in zip(zip(s_list, y_list, rho_list),
                                      reversed(alphas)):
                b = rho * jnp.dot(y, q)
                q = q + (a - b) * s
            d = -q
            # step size: line search (backtracking armijo) or fixed lr
            t = self.learningrate if it > 0 or s_list else \
                min(1.0, 1.0 / float(jnp.sum(jnp.abs(g)))) * self.learningrate
            gtd = float(jnp.dot(g, d))
            if gtd > -self.tol_x:
                break
            if self.line_search:
                for _ in range(25):
                    new_loss, _ = f(x_flat + t * d)
                    n_eval += 1
                    if new_loss <= loss + 1e-4 * t * gtd:
                        break
                    t *= 0.5
            x_new = x_flat + t * d
            loss_new, g_new = f(x_new)
            n_eval += 1
            s = x_new - x_flat
            y = g_new - g
            sy = float(jnp.dot(s, y))
            if sy > 1e-10:
                if len(s_list) >= self.n_correction:
                    s_list.pop(0)
                    y_list.pop(0)
                    rho_list.pop(0)
                s_list.append(s)
                y_list.append(y)
                rho_list.append(1.0 / sy)
            if abs(loss_new - loss) < self.tol_fun:
                x_flat, loss, g = x_new, loss_new, g_new
                losses.append(loss)
                break
            x_flat, loss, g = x_new, loss_new, g_new
            losses.append(loss)
            if n_eval >= self.max_eval:
                break
        self.state["neval"] += 1
        return unravel(x_flat), losses

    def init_state(self, params):
        return {}

    def update(self, grads, params, opt_state, lr):
        # plain gradient step when used inside a jitted loop
        return _keep_dtype(_tmap(lambda w, g: w - lr * g, params, grads),
                           params), opt_state
