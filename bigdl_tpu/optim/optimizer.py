"""Training drivers.

Parity: reference ``optim/Optimizer.scala``, ``optim/LocalOptimizer.scala``,
``optim/DistriOptimizer.scala``, ``optim/AbstractOptimizer.scala``,
``optim/Metrics.scala``, plus DistriOptimizer's checkpoint/summary/validation
plumbing (DistriOptimizer.scala:90-640).

Execution model (TPU-first):

* The whole training step — forward, loss (+ per-layer regularizers),
  backward, gradient clipping, optimizer update — is ONE jitted function.
  The reference re-enters the JVM interpreter per layer per step; here XLA
  compiles the step once and fuses across layer boundaries.
* ``LocalOptimizer``: single device.
* ``DistriOptimizer``: the global batch is laid out over the mesh ``data``
  axis. Two parameter modes:
  - ``replicated`` (default): params replicated, XLA GSPMD inserts the
    gradient all-reduce over ICI automatically — the hardware analog of the
    reference's block-manager all-reduce;
  - ``zero1``: params flattened to one contiguous vector and updated
    slice-per-device via psum_scatter/all_gather (see
    ``parallel/allreduce.py``) — the literal TPU translation of
    AllReduceParameter's owner-slice design, with sharded optimizer state.
* LR schedules, triggers, checkpointing, validation, summaries run host-side
  between steps (control, not compute).
"""
from __future__ import annotations

import logging
import os
import pickle
import queue
import threading
import time
from collections import deque
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import observability as obs
from ..observability import cluster as _cluster
from ..observability import flight as _flight
from ..observability import health as _health
from ..parallel import chaos as _chaos
from ..parallel.failure import (FaultPolicy, HeartbeatLost, TrainingHalted,
                                PERMANENT, TRANSIENT, classify_failure,
                                probe_mesh, _run_with_timeout)
from .optim_method import OptimMethod, Plateau, SGD
from .regularizer import regularizer_tree, regularization_loss
from .trigger import Trigger, max_epoch as _max_epoch
from ..dataset.dataset import AbstractDataSet, ShardedDataSet, DataSet
from ..dataset.minibatch import MiniBatch
from ..nn.module import Module, Criterion
from .staging import staged
from ..utils import engine
from ..utils.table import Table

_tmap = jax.tree_util.tree_map
_LOG = logging.getLogger(__name__)

def _read_umask():
    """The process umask, read WITHOUT the os.umask(0)/restore dance
    when possible — that flip is process-wide, and another thread
    creating a file inside the window (serving batcher, a lazy import
    off a worker thread) would get world-writable modes. Linux exposes
    it race-free in /proc; elsewhere fall back to the racy read once
    here at import."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("Umask:"):
                    return int(line.split()[1], 8)
    except (OSError, ValueError, IndexError):
        pass
    um = os.umask(0)
    os.umask(um)
    return um


# _atomic_pickle restores umask-based modes on its mkstemp tmps, which
# are born 0600
_UMASK = _read_umask()


def _atomic_pickle(path, payload):
    """Crash-consistent write: unique tmp + fsync + atomic rename +
    directory fsync. A kill at ANY point — mid-dump, post-dump
    pre-rename, post-rename pre-dir-sync under power loss — leaves
    either the previous intact file or the complete new one, never a
    truncated 'latest' (the file every recovery path — nan resume,
    remediation halt, elastic restart — trusts blindly). The tmp name
    is unique per write (mkstemp), so a writer killed mid-dump can
    never have its half-written tmp renamed over the target by a later
    writer reusing the same tmp path, and concurrent writers (two
    optimizers sharing a checkpoint dir) never interleave into one
    file. Failed writes remove their tmp — no litter accumulates."""
    _chaos.maybe_fire("checkpoint/write")
    import tempfile
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(
        prefix=os.path.basename(path) + ".", suffix=".tmp", dir=d)
    try:
        with os.fdopen(fd, "wb") as f:
            # mkstemp creates 0600 and os.replace keeps the tmp's mode;
            # a checkpoint must stay as readable as a plain open() would
            # have made it (eval jobs / backup agents under another uid)
            os.fchmod(f.fileno(), 0o666 & ~_UMASK)
            pickle.dump(payload, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    # fsync the DIRECTORY: the rename itself must survive power loss,
    # or recovery could see the pre-checkpoint directory state
    try:
        dfd = os.open(d, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass  # platforms without dir fsync keep file-level durability


class _AsyncCheckpointWriter:
    """One daemon writer thread; submissions are written IN ORDER (so the
    'latest checkpoint' on disk is always the latest submitted), each via
    the atomic tmp+rename. ``flush`` drains the queue and re-raises the
    first writer error (a silently failing checkpointer is worse than a
    crashed one). The reference writes checkpoints synchronously on the
    Spark driver (Optimizer.setCheckpoint → File.save); on TPU the step
    loop should not stall on host file IO."""

    def __init__(self, max_pending: int = 2):
        # bounded: a slow disk backpressures the training loop instead of
        # accumulating one full host model copy per checkpoint interval
        self._q: queue.Queue = queue.Queue(maxsize=max_pending)
        self._err = None
        self._thread = None

    def _run(self):
        while True:
            item = self._q.get()
            try:
                if item is None:
                    return
                path, payload = item
                try:
                    _atomic_pickle(path, payload)
                except Exception as e:  # noqa: BLE001 — surfaced in flush
                    if self._err is None:
                        self._err = e
            finally:
                self._q.task_done()

    def submit(self, path, payload):
        if self._thread is None:
            self._thread = threading.Thread(target=self._run, daemon=True)
            self._thread.start()
        self._q.put((path, payload))
        if obs.enabled():
            obs.gauge("checkpoint/queue_depth").set(self._q.qsize())

    def flush(self, timeout=None):
        if self._thread is not None:
            if timeout is None:
                self._q.join()
            else:
                deadline = time.monotonic() + timeout
                while self._q.unfinished_tasks and \
                        time.monotonic() < deadline:
                    time.sleep(0.05)
                if self._q.unfinished_tasks:
                    raise TimeoutError(
                        f"{self._q.unfinished_tasks} async checkpoint "
                        f"write(s) still pending after {timeout}s")
        if self._err is not None:
            err, self._err = self._err, None
            raise RuntimeError(
                f"async checkpoint write failed: {err}") from err

    def close(self, timeout=None):
        """Flush, then stop the writer thread (optimize() calls this so
        no daemon thread outlives the run). ``timeout`` bounds the whole
        attempt for halt paths: a writer wedged on hung storage (dead
        NFS mid-remediation) is ABANDONED to its daemon fate instead of
        wedging the exit — the remediation checkpoint already landed
        synchronously, and an elastic resume prefers the halt's own
        checkpoint path over mtime, so a late-landing stale write
        cannot be silently resumed."""
        try:
            self.flush(timeout)
        finally:
            if self._thread is not None:
                try:
                    self._q.put_nowait(None)
                except queue.Full:
                    pass  # wedged writer never drains: abandon it
                self._thread.join(timeout=30 if timeout is None
                                  else timeout)
                self._thread = None


class Metrics:
    """Per-phase timing metrics (parity: optim/Metrics.scala).

    Retained as the optimizer-local view (``.values`` is part of the
    public surface); when observability is enabled every ``add`` also
    mirrors into the process-global registry as an
    ``optim/<name>`` histogram, so the Prometheus/Chrome exporters and
    the TensorBoard bridge see the same numbers without a second
    collection path."""

    def __init__(self, namespace: str = "optim"):
        self.values = {}
        self._namespace = namespace

    def add(self, name, value):
        self.values.setdefault(name, []).append(value)
        if obs.enabled():
            obs.histogram(f"{self._namespace}/{name}").observe(value)

    def mean(self, name):
        if name not in self.values:
            raise KeyError(
                f"no metric named {name!r} has been recorded "
                f"(seen: {sorted(self.values)})")
        v = self.values[name]
        return sum(v) / len(v)

    def summary(self):
        return {k: self.mean(k) for k in self.values}


def _frozen_mask(model):
    """Mask pytree matching ``model.params``: 0.0 under frozen modules
    (Module.freeze), 1.0 elsewhere; None when nothing is frozen.

    Per-module flags, no ancestor propagation: ``freeze()`` marks whole
    subtrees, so ``unfreeze("head")`` under a frozen root works."""
    from ..nn.module import Container
    from ..nn.recurrent import Recurrent
    model.ensure_initialized()
    if not any(getattr(m, "_frozen", False) for m in model.modules_iter()):
        return None

    def rec(m, p):
        if isinstance(m, Recurrent) and isinstance(p, dict) and "cell" in p:
            return {"cell": rec(m.cell, p["cell"])}
        if isinstance(m, Container) and isinstance(p, dict):
            out = {}
            for k, v in p.items():
                if k.isdigit() and int(k) < len(m.modules):
                    out[k] = rec(m.modules[int(k)], v)
                else:
                    out[k] = _leaf_mask(m, v)
            return out
        return _leaf_mask(m, p)

    def _leaf_mask(m, p):
        val = 0.0 if getattr(m, "_frozen", False) else 1.0
        return _tmap(lambda a: val, p)

    return rec(model, model.params)


def _scan_superstep(step):
    """Lift a single-step function ``step(params, opt_state, mstate, x, y,
    lr, rng) -> (loss, params', opt_state', mstate')`` into a superstep:
    ``lax.scan`` over K stacked microbatches threading the training state
    through K updates inside ONE XLA program. Losses come back as a
    single ``[K]`` device array — one dispatch and one batched readback
    amortize the per-step host costs K-fold. The per-microstep math (incl.
    the in-step NaN guard: a non-finite microstep keeps the previous
    state, later microsteps proceed from it — exactly the K=1 'skip'
    dataflow) is the same program the per-step loop compiles; trajectories
    match K=1 bitwise for fusion-insensitive bodies (elementwise/matmul
    MLPs — asserted in tests/test_superstep.py). XLA may re-fuse across
    microstep boundaries, which can reorder a handful of GEMM/conv
    accumulations — measured <= 4e-9 absolute drift on LeNet/CPU over 8
    steps, i.e. last-mantissa-bit float noise, never a semantic change."""

    def superstep(params, opt_state, mstate, xs, ys, lrs, rngs):
        def body(carry, inp):
            p, o, m = carry
            x, y, lr, rng = inp
            loss, p, o, m = step(p, o, m, x, y, lr, rng)
            return (p, o, m), loss

        (params, opt_state, mstate), losses = jax.lax.scan(
            body, (params, opt_state, mstate), (xs, ys, lrs, rngs))
        return losses, params, opt_state, mstate

    return superstep


def _clip_grads(grads, clip_const=None, clip_norm=None):
    if clip_const is not None:
        lo, hi = clip_const
        grads = _tmap(lambda g: jnp.clip(g, lo, hi), grads)
    if clip_norm is not None:
        leaves = jax.tree_util.tree_leaves(grads)
        total = jnp.sqrt(sum(jnp.sum(jnp.square(g)) for g in leaves))
        scale = jnp.minimum(1.0, clip_norm / (total + 1e-12))
        grads = _tmap(lambda g: g * scale, grads)
    return grads


class RemediationPolicy:
    """Tier-1 observe→act configuration: what the optimizer DOES when
    the health layer (PR 5) sees trouble, instead of only recording it.

    * **Stall remediation** — when the step loop's watchdog beacon
      stalls, the policy probes the mesh (``probe_mesh``, bounded by
      ``probe_timeout_s``) to classify transient vs. dead. A dead mesh
      — or any stall when ``halt_on_stall`` is set — checkpoints the
      last resolved training state from the watchdog thread (the loop
      itself is wedged), dumps a flight bundle, and requests a
      :class:`~bigdl_tpu.parallel.failure.TrainingHalted` exit: the run
      leaves artifacts instead of hanging forever. ``exit_process``
      additionally ``os._exit(86)`` s after the artifacts land, for
      loops wedged beyond rescue in a dead collective. The checkpoint's
      device→host fetch is itself bounded by
      ``halt_artifact_timeout_s`` (it has no deadline of its own, and a
      dead mesh would otherwise wedge the watchdog thread doing the
      remediating); on expiry the halt lands bundle-only.
    * **Heartbeat membership** — with a ``heartbeat``
      (:class:`~bigdl_tpu.parallel.failure.Heartbeat`), the loop beats
      every ``heartbeat_every`` steps with ``heartbeat_timeout_s``; a
      lost or stale exchange checkpoints-and-halts with the stale peer
      ids recorded as ``lost_processes`` — the membership signal the
      elastic restarter reshapes the mesh from.
    * **Anomaly-driven control** — ``health/plateau`` events (from the
      losses the sync policy already resolves — zero new readbacks)
      optionally drive the LR: a :class:`Plateau` schedule gets
      :meth:`~Plateau.force_reduction`, any other schedule a
      ``plateau_factor`` multiplier (``health/lr_reduced`` event);
      ``early_stop_plateaus`` ends the run cleanly after N plateaus,
      and ``max_spikes`` checkpoint-and-halts a diverging run after N
      ``health/loss_spike`` events.
    * **Stragglers** — with a ``straggler_monitor``, per-step times are
      recorded and a report runs every ``straggler_every`` steps;
      persistent stragglers fire ``health/straggler`` (see
      :class:`~bigdl_tpu.parallel.failure.StragglerMonitor`).

    Stall/probe remediation needs observability enabled (the watchdog
    is the trigger); heartbeat, anomaly control and stragglers work
    either way.
    """

    def __init__(self, halt_on_stall: bool = False,
                 probe_timeout_s: float = 30.0,
                 exit_process: bool = False,
                 halt_artifact_timeout_s: float = 120.0,
                 heartbeat=None, heartbeat_every: int = 0,
                 heartbeat_timeout_s: float = 60.0,
                 plateau_lr: bool = False, plateau_factor: float = 0.1,
                 min_lr_scale: float = 1e-4,
                 early_stop_plateaus: Optional[int] = None,
                 max_spikes: Optional[int] = None,
                 straggler_monitor=None, straggler_every: int = 0):
        if heartbeat is not None and heartbeat_every < 1:
            raise ValueError("heartbeat needs heartbeat_every >= 1 "
                             f"(got {heartbeat_every})")
        if straggler_monitor is not None and straggler_every < 1:
            raise ValueError("straggler_monitor needs straggler_every >= 1 "
                             f"(got {straggler_every})")
        self.halt_on_stall = halt_on_stall
        self.probe_timeout_s = float(probe_timeout_s)
        self.exit_process = exit_process
        self.halt_artifact_timeout_s = float(halt_artifact_timeout_s)
        self.heartbeat = heartbeat
        self.heartbeat_every = int(heartbeat_every)
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.plateau_lr = plateau_lr
        self.plateau_factor = float(plateau_factor)
        self.min_lr_scale = float(min_lr_scale)
        self.early_stop_plateaus = early_stop_plateaus
        self.max_spikes = max_spikes
        self.straggler_monitor = straggler_monitor
        self.straggler_every = int(straggler_every)
        # per-run bookkeeping (reset by Optimizer.optimize())
        self.plateaus = 0
        self.spikes = 0
        self._last_beat_neval = 0
        self._last_straggler_neval = 0

    def reset_run_state(self):
        self.plateaus = 0
        self.spikes = 0
        self._last_beat_neval = 0
        self._last_straggler_neval = 0


class BaseOptimizer:
    def __init__(self, model: Module, training_set, criterion: Criterion,
                 optim_method: Optional[OptimMethod] = None,
                 end_trigger: Optional[Trigger] = None, batch_size: int = 32):
        self.model = model
        self.criterion = criterion
        self.optim_method = optim_method or SGD(learningrate=0.01)
        self.end_trigger = end_trigger or _max_epoch(1)
        self.batch_size = batch_size
        self.training_set = self._as_dataset(training_set)

        self.validation_trigger = None
        self.validation_set = None
        self.validation_methods = None
        self.checkpoint_trigger = None
        self.checkpoint_path = None
        self.checkpoint_overwrite = True
        self.checkpoint_async = False
        self._ckpt_writer = _AsyncCheckpointWriter()
        self.train_summary = None
        self.val_summary = None
        self.clip_const = None
        self.clip_norm = None
        self.nan_policy = "error"  # or "skip" / "resume"
        self.max_nan_retries = 10  # consecutive non-finite steps before abort
        self.sync_policy = "sync"  # or "async" / "window:K"
        self.prefetch_depth = 2    # >= 2 enables the lookahead stager
        self.superstep = 1         # K fused steps per dispatch (lax.scan)
        self._pending_loss = None
        self._loss_window = deque()
        self._resolved_step = None  # provenance of the last resolved loss
        self.metrics = Metrics()
        self._step_fn = None
        # health layer (active only while observability is enabled):
        # stall watchdog deadline/callback, anomaly-detector config
        # (None disables; a dict overrides SeriesMonitor defaults)
        self.stall_deadline_s = None   # None -> BIGDL_TPU_STALL_S default
        self.stall_startup_grace_s = None  # None -> max(deadline, default)
        self._stall_grace_pending = False
        self.on_stall = None
        self.anomaly_config: Optional[dict] = {}
        self._step_beacon = _health.NULL_BEACON
        self._loss_monitor = None
        self._profiler = None
        # cluster metric snapshots (BIGDL_TPU_METRIC_SNAP_S cadence;
        # a zero interval makes every maybe_write a single comparison)
        self._snap_writer = _cluster.MetricSnapshotWriter(every_s=0)
        # self-healing (PR 6): Tier-1 observe→act policy, Tier-2
        # dispatch retry budget, and the cross-thread halt/live-state
        # channel the watchdog-thread remediation writes into
        self.remediation: Optional[RemediationPolicy] = None
        self.fault_policy: Optional[FaultPolicy] = None
        self._halt_requested: Optional[TrainingHalted] = None
        self._live_state = None        # (params, opt_state, mstate)
        self._remediation_lr_scale = 1.0
        self._remediating = False      # one stall remediation in flight

    # -- reference API surface ------------------------------------------
    def set_model(self, model):
        """Swap the model for optimizer reuse (pyspark Optimizer.set_model).
        Training PROGRESS resets with it: the epoch/iteration counters and
        any checkpoint-resume optimizer state belong to the old model —
        without the reset a second ``optimize()`` would stop at the old
        end-trigger after one step (or feed the old model's opt-state tree
        into the new step)."""
        self.model = model
        self.optim_method.state = {"neval": 0, "epoch": 1}
        self._resume_opt_state = None
        return self

    def set_criterion(self, criterion):
        """Swap the criterion for optimizer reuse (pyspark
        Optimizer.set_criterion). The step is rebuilt on the next
        ``optimize()``."""
        self.criterion = criterion
        return self

    def set_traindata(self, training_set, batch_size=None):
        """Swap the training data for optimizer reuse (pyspark
        Optimizer.set_traindata)."""
        self.training_set = self._as_dataset(training_set)
        if batch_size:
            self.batch_size = batch_size
        return self

    def set_summary_trigger(self, name, trigger):
        """Modify when a summary named tag is recorded (pyspark
        Optimizer.set_summary_trigger). Train tags: "Loss",
        "LearningRate", "Throughput". Validation: "Validation" gates all
        validation scalars; a per-method tag (its repr) gates one."""
        val_tags = {repr(m) for m in (self.validation_methods or ())}
        is_val_tag = name.startswith("Validation") or name in val_tags
        if is_val_tag:
            if self.val_summary is None:
                raise ValueError(
                    "set_summary_trigger(%r): validation tag but no "
                    "validation summary is set — call set_val_summary "
                    "first (the train loop only consults Loss/"
                    "LearningRate/Throughput)" % (name,))
            target = self.val_summary
        elif self.train_summary is not None:
            target = self.train_summary
        else:
            raise ValueError("set a train/val summary before "
                             "set_summary_trigger")
        target.set_summary_trigger(name, trigger)
        return self

    def prepare_input(self):
        """Materialise the dataset ahead of ``optimize`` (pyspark
        Optimizer.prepare_input — there, forces the cached RDD; here the
        dataset protocol is already local, so this just touches one
        batch to surface IO errors early). Open-epoch datasets (the
        native prefetchers spawn decode workers per data() call) are
        skipped — pulling one batch would leave a whole epoch's worker
        run open."""
        if getattr(self.training_set, "_epoch_open", None) is not None:
            return self
        it = iter(self.training_set.data(train=False))
        try:
            next(it, None)
        finally:
            # generator-backed datasets may hold resources (open files,
            # worker pools) in the abandoned iterator — release eagerly
            close = getattr(it, "close", None)
            if close is not None:
                close()
        return self

    def set_validation(self, trigger, dataset, methods, batch_size=None):
        self.validation_trigger = trigger
        self.validation_set = self._as_dataset(dataset)
        self.validation_methods = list(methods)
        self.validation_batch = batch_size or self.batch_size
        return self

    def set_checkpoint(self, trigger, path, overwrite=True,
                       async_write=False):
        """``async_write=True`` moves serialization + file IO onto a
        background writer thread (ordered, atomic) so the training loop
        only pays the device→host fetch; ``wait_for_checkpoints()`` (also
        called at the end of ``optimize``) flushes and surfaces errors."""
        self.checkpoint_trigger = trigger
        self.checkpoint_path = path
        self.checkpoint_overwrite = overwrite
        self.checkpoint_async = async_write
        os.makedirs(path, exist_ok=True)
        return self

    def set_train_summary(self, summary):
        self.train_summary = summary
        return self

    def set_val_summary(self, summary):
        self.val_summary = summary
        return self

    def set_end_when(self, trigger):
        self.end_trigger = trigger
        return self

    def set_gradclip_const(self, clip_min: float, clip_max: float):
        self.clip_const = (clip_min, clip_max)
        return self

    def set_gradclip_l2norm(self, clip_norm: float):
        self.clip_norm = clip_norm
        return self

    def disable_gradclip(self):
        self.clip_const = self.clip_norm = None
        return self

    def set_sync_policy(self, policy: str):
        """'sync' (default) reads each step's loss immediately — the host
        blocks on the device every iteration. 'async' reads the PREVIOUS
        step's loss instead, so the next batch is prepared and enqueued
        while the device still computes (loss logging, NaN detection and
        min-loss triggers lag one step; the in-step NaN guard keeps params
        safe on-device either way). Use 'async' for device-bound training.

        'window:K' generalizes async: up to K losses stay in flight as
        device arrays and the host resolves the OLDEST only once the
        window is full, so loss observation (logging, NaN detection,
        min-loss triggers) lags K-1 steps and the device pipeline is
        never drained by a blocking read. 'window:1' == 'sync'. The NaN
        policy semantics are preserved — a non-finite resolved loss
        raises/skips/replays-from-checkpoint exactly like sync, just K-1
        steps later (params stay safe meanwhile via the in-step guard).
        """
        if isinstance(policy, str) and policy.startswith("window:"):
            k = int(policy.split(":", 1)[1])
            if k < 1:
                raise ValueError(f"window size must be >= 1, got {k}")
        else:
            assert policy in ("sync", "async")
        self.sync_policy = policy
        return self

    def set_prefetch(self, depth: int):
        """Lookahead depth of the batch stager: with ``depth >= 2`` a
        host thread produces and device_puts batches N+1..N+depth while
        step N runs, collapsing ``step/data_fetch`` to a queue pop.
        ``0``/``1`` keep the serial fetch (exact A/B switch — the staged
        loop is order-preserving, so trajectories are identical)."""
        depth = int(depth)
        if depth < 0:
            raise ValueError(f"prefetch depth must be >= 0, got {depth}")
        self.prefetch_depth = depth
        return self

    def set_superstep(self, k: int):
        """Fuse K training steps into ONE compiled XLA program: the step
        becomes a ``lax.scan`` over K stacked microbatches that threads
        (params, opt_state, model state) through K updates on device, so
        the host pays one dispatch, one batched ``[K]`` loss readback and
        one round of bookkeeping per K steps instead of per step — the
        win when host dispatch dominates (small/medium models, remote-
        device tunnels). Semantics stay identical to K=1: LR schedules
        are precomputed as a ``[K]`` vector, the per-step RNG stream is
        unchanged, and dispatches auto-clamp so a superstep never
        straddles an epoch end or a checkpoint/validation/end-trigger
        boundary. When K > 1 the batched readback REPLACES the per-loss
        resolution of ``sync``/``async``/``window:K`` (loss observation,
        NaN detection and loss-driven triggers resolve once per
        superstep — the same K-step observation lag ``window:K`` has).
        ``1`` restores the per-step loop exactly.

        Equivalence: the scan body IS the per-step program, so the
        trajectory matches K=1 bitwise for fusion-insensitive models
        (MLPs); where XLA re-fuses across microstep boundaries (conv/
        GEMM epilogues) a handful of accumulations reorder — measured
        <= 4e-9 absolute drift on LeNet/CPU, float ulp noise."""
        k = int(k)
        if k < 1:
            raise ValueError(f"superstep must be >= 1, got {k}")
        self.superstep = k
        return self

    def _window_k(self) -> Optional[int]:
        if isinstance(self.sync_policy, str) and \
                self.sync_policy.startswith("window:"):
            return int(self.sync_policy.split(":", 1)[1])
        return None

    def set_stall_deadline(self, seconds: float, on_stall=None,
                           startup_grace_s=None):
        """Arm the stall watchdog for this optimizer's loops: the step
        loop and its batch stager pulse progress beacons, and a beacon
        quiet for ``seconds`` fires a structured ``health/stall`` event
        (plus ``on_stall(beacon, age_s)`` when given) instead of the run
        silently hanging — the remote-TPU 'no output' failure mode.
        Active only while observability is enabled; the default deadline
        without this call is ``BIGDL_TPU_STALL_S`` (600s).

        ``startup_grace_s``: the deadline in force until the FIRST
        dispatch completes. The first step blocks for the whole XLA
        compile — minutes on a real pod — which is silence a
        steady-state deadline would misread as a stall (and, with
        ``RemediationPolicy(halt_on_stall=True)``, kill a healthy run
        before it trained a step). Defaults to
        ``max(seconds, BIGDL_TPU_STALL_S)``; the step loop tightens the
        beacon to ``seconds`` the moment the first step lands."""
        seconds = float(seconds)
        if seconds <= 0:
            raise ValueError(f"stall deadline must be > 0, got {seconds}")
        if startup_grace_s is not None and float(startup_grace_s) < seconds:
            raise ValueError(
                f"startup_grace_s ({startup_grace_s}) must be >= the "
                f"steady-state deadline ({seconds})")
        self.stall_deadline_s = seconds
        self.stall_startup_grace_s = None if startup_grace_s is None \
            else float(startup_grace_s)
        self.on_stall = on_stall
        return self

    def set_remediation(self, policy: Optional[RemediationPolicy]):
        """Arm the Tier-1 observe→act loop (see
        :class:`RemediationPolicy`): stalls and heartbeat loss
        checkpoint-and-exit with a flight bundle instead of hanging,
        plateau/spike anomalies optionally drive the LR schedule and
        early-stop, straggler reports run on a cadence. ``None``
        disarms."""
        if policy is not None and not isinstance(policy, RemediationPolicy):
            raise TypeError(f"expected RemediationPolicy, got {policy!r}")
        self.remediation = policy
        return self

    def set_fault_policy(self, policy: Optional[FaultPolicy]):
        """Arm Tier-2 dispatch retry (see
        :class:`~bigdl_tpu.parallel.failure.FaultPolicy`): every
        dispatch snapshots the resolved host-side training state first,
        and a TRANSIENT device/collective failure replays the in-flight
        step — under superstep fusion, the whole K-step group — from
        that snapshot after an exponential backoff, so a dropped tunnel
        packet costs one step's latency instead of the run. The replay
        reuses the step's exact batches, lr vector and rng keys, so a
        retried run is bitwise-identical to a fault-free one. Permanent
        failures raise immediately (Tier 3 owns those). The per-
        dispatch snapshot is a device→host fetch of params/opt-state —
        meaningful overhead, so arm this for flaky transports, not by
        default. ``None`` disarms.

        SINGLE-CONTROLLER ONLY: the replay re-enters restore placement
        and the compiled step's collectives on THIS process alone. In a
        multi-controller run a failure one process sees and its peers
        don't would have only that process replaying — collectives the
        others never join, wedging the whole mesh until the watchdog
        kills it. Multi-controller transients belong to Tier 1 + Tier 3
        (heartbeat halt, checkpoint, elastic restart)."""
        if policy is not None and not isinstance(policy, FaultPolicy):
            raise TypeError(f"expected FaultPolicy, got {policy!r}")
        if policy is not None and jax.process_count() > 1:
            _LOG.warning(
                "FaultPolicy replay is single-controller: in this "
                "%d-process run a one-sided transient replay would "
                "desynchronize the mesh's collectives — rely on "
                "Tier 1 heartbeat remediation + elastic restart for "
                "cross-process faults", jax.process_count())
        self.fault_policy = policy
        return self

    def set_anomaly_detection(self, enabled: bool = True, **config):
        """Configure the rolling loss anomaly detector (spikes,
        plateaus, NaN streaks — ``observability.health.SeriesMonitor``;
        kwargs override its defaults, e.g. ``spike_sigma=6``,
        ``plateau_window=500``). It consumes the loss floats the sync
        policy already resolves — zero extra device readbacks.
        ``enabled=False`` turns it off entirely."""
        self.anomaly_config = dict(config) if enabled else None
        return self

    def set_nan_policy(self, policy: str):
        """'error' raises, 'skip' drops the step, 'resume' rolls back to the
        latest checkpoint (requires set_checkpoint) — the step-level analog of
        Spark's failed-task retry (SURVEY §5 failure detection)."""
        assert policy in ("error", "skip", "resume")
        self.nan_policy = policy
        return self

    def _latest_checkpoint(self):
        # one trust anchor for "the latest checkpoint" across every
        # recovery path: nan-resume here, elastic restart in the runner
        from ..parallel.elastic import find_latest_checkpoint
        return find_latest_checkpoint(self.checkpoint_path)

    # -- internals -------------------------------------------------------
    def _as_dataset(self, ds):
        if ds is None or isinstance(ds, AbstractDataSet):
            return ds
        if isinstance(ds, tuple) and len(ds) == 2:
            return DataSet.from_arrays(ds[0], ds[1])
        if isinstance(ds, (list,)):
            return DataSet.array(ds)
        if hasattr(ds, "data") and hasattr(ds, "size"):
            return ds  # batch-level dataset (e.g. native.NativePrefetcher)
        raise TypeError(f"unsupported dataset {type(ds)}")

    def _num_shards(self):
        return 1

    def _batched(self):
        if hasattr(self.training_set, "batches_per_epoch"):
            return self.training_set  # already yields MiniBatches
        return ShardedDataSet(self.training_set, self.batch_size,
                              num_shards=self._num_shards())

    def _build_step(self):
        model, criterion = self.model, self.criterion
        reg_tree = regularizer_tree(model)
        clip_const, clip_norm = self.clip_const, self.clip_norm
        optim = self.optim_method
        frozen_mask = _frozen_mask(model)

        def loss_fn(params, mstate, x, y, rng):
            out, new_state = model.apply(params, mstate, x, training=True,
                                         rng=rng)
            loss = criterion._forward(out, y)
            if reg_tree:
                loss = loss + regularization_loss(reg_tree, params)
            return loss, new_state

        def step(params, opt_state, mstate, x, y, lr, rng):
            (loss, new_mstate), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, mstate, x, y, rng)
            # trace-time span: this body runs under jit, so the span
            # appears once per compile (under the first step/dispatch)
            # and measures clip *trace* cost — the per-step clip itself
            # is fused into the compiled program
            with obs.span("step/grad_clip", traced=True):
                grads = _clip_grads(grads, clip_const, clip_norm)
            if frozen_mask is not None:
                grads = _tmap(lambda g, m: g * m, grads, frozen_mask)
            new_params, new_opt = optim.update(grads, params, opt_state, lr)
            if frozen_mask is not None:
                # weight decay must not move frozen params either — restore
                new_params = _tmap(
                    lambda n, o, m: jnp.where(m > 0, n, o),
                    new_params, params, frozen_mask)
            # NaN/Inf guard inside the compiled step (buffers are donated, so
            # the host can't roll back): a non-finite loss keeps the previous
            # params/opt-state and only the loss reports the failure.
            ok = jnp.isfinite(loss)
            pick = lambda new, old: _tmap(
                lambda a, b: jnp.where(ok, a, b), new, old)
            return (loss, pick(new_params, params), pick(new_opt, opt_state),
                    pick(new_mstate, mstate))

        fn = jax.jit(_scan_superstep(step), donate_argnums=(0, 1, 2)) \
            if self.superstep > 1 else \
            jax.jit(step, donate_argnums=(0, 1, 2))
        return self._instrument_step(fn)

    def _instrument_step(self, jit_fn):
        """Route the compiled step through the perf-introspection
        wrapper: each distinct batch signature records a
        CompiledArtifact (XLA FLOPs/bytes, memory footprint, compile
        wall time, cache provenance) that the live ``perf/mfu`` gauge
        and ``tools/xla_report.py`` read. Params/opt-state/model-state
        shapes are fixed for the life of the step fn, so the signature
        keys on the batch arguments alone (argnums 3, 4). Under
        superstep fusion the per-program step count is read off the
        ``[k, batch, ...]`` stack's leading dim at compile time — a
        clamped j<K group compiles its OWN program and its artifact
        must say j, not the configured K."""
        if self.superstep > 1:
            def steps_from_stack(args):
                leaves = jax.tree_util.tree_leaves(args[3])
                return leaves[0].shape[0] if leaves else 1
            steps = steps_from_stack
        else:
            steps = 1
        return obs.perf.instrument_jit(
            jit_fn, name="optim/step", kind="train_step",
            key_argnums=(3, 4), steps_per_program=steps)

    def _place_batch(self, x, y):
        from .staging import place_host_value
        return place_host_value(x), place_host_value(y)

    def _stage_minibatch(self, mb):
        """Produce-side staging: host MiniBatch -> device-resident (x, y).
        Runs on the stager thread when prefetch is enabled (the native
        bf16_nhwc prefetcher's batches pass through as a cast-free
        device_put), inline otherwise."""
        return self._place_batch(mb.get_input(), mb.get_target())

    def _stage_minibatch_host(self, mb):
        """Superstep produce-side stage 1: extract the host (x, y) only —
        placement happens once per GROUP in ``_stage_group`` so the whole
        ``[K, batch, ...]`` stack ships in one (sharded) device_put."""
        return mb.get_input(), mb.get_target()

    def _stage_group(self, items):
        """Superstep stacking stage (runs on the stager thread): K host
        microbatches -> one ``(k, xs, ys)`` element with device-resident
        ``[k, batch, ...]`` stacks, so the hot loop dequeues one element
        per dispatch. ``np.asarray`` first: the native prefetchers may
        hand device-resident batches (direct-to-device staging); the
        stack itself must run on host memory."""
        def stack(vals):
            return _tmap(lambda *ls: np.stack([np.asarray(l) for l in ls]),
                         *vals)
        xs = stack([x for x, _ in items])
        ys = stack([y for _, y in items])
        xs, ys = self._place_group(xs, ys)
        return len(items), xs, ys

    def _place_group(self, xs, ys):
        """Host ``[k, batch, ...]`` stacks -> device (overridden by
        DistriOptimizer to shard the per-step batch dim over the mesh)."""
        from .staging import place_host_value
        return place_host_value(xs), place_host_value(ys)

    @staticmethod
    def _stage_group_key(staged):
        """Stacking compatibility key: the per-step batch size. A ragged
        final batch (batch-level datasets without drop-remainder) must
        start its own smaller group, not np.stack against full ones."""
        x, _ = staged
        leaves = jax.tree_util.tree_leaves(x)
        return leaves[0].shape[0] if leaves else 0

    def _observe_loss(self, loss, step=None):
        """Apply the sync policy to this step's device loss. Returns the
        resolved host float to examine this iteration, or None when the
        windowed policy has not filled its in-flight budget yet. Every
        resolution is one host<->device sync, counted in
        ``optim/loss_syncs`` (supersteps cut this K-fold).

        ``step`` is the iteration this DISPATCH belongs to; under
        ``async``/``window:K`` the returned float describes an OLDER
        dispatch, and ``self._resolved_step`` names it — the health
        layer (flight ring, anomaly detector) must attribute a lagged
        loss to the step that produced it, not the step that read it."""
        k = self._window_k()
        self._resolved_step = step
        if k is not None:
            self._loss_window.append((step, loss))
            if obs.enabled():
                obs.gauge("optim/loss_window_inflight").set(
                    len(self._loss_window))
            if len(self._loss_window) < k:
                return None
            if obs.enabled():
                obs.counter("optim/loss_syncs").inc()
            self._resolved_step, oldest = self._loss_window.popleft()
            # sync-ok: windowed resolve of the OLDEST in-flight loss
            return float(oldest)
        if obs.enabled():
            obs.counter("optim/loss_syncs").inc()
        if self.sync_policy == "async":
            # examine the PREVIOUS step's loss: the device keeps
            # computing while the host preps the next batch
            prev, self._pending_loss = self._pending_loss, (step, loss)
            if prev is not None:
                self._resolved_step, loss = prev
            # sync-ok: lagged read (first step resolves its own loss)
            return float(loss)
        # sync-ok: sync policy blocks on every step by definition
        return float(loss)

    def _drain_pending_losses(self, state):
        """Resolve losses still in flight when the loop ends (async's one
        pending read, window:K's up-to-K-1 tail) — a NaN pending on the
        final steps must not be swallowed."""
        pending = list(self._loss_window)
        self._loss_window.clear()
        if self._pending_loss is not None:
            pending.append(self._pending_loss)
            self._pending_loss = None
        for _step, dev in pending:
            final = float(dev)  # sync-ok: end-of-run drain
            if np.isfinite(final):
                state["loss"] = final
            elif self.nan_policy == "error":
                raise FloatingPointError(
                    f"non-finite loss {final} on a final step "
                    f"({self.sync_policy} lagged read)")
            else:
                self.metrics.add("nan_skips", 1.0)

    def _checkpoint_payload(self, params, opt_state, mstate, state):
        """Host snapshot of the full training state. The optimizer state
        rides in CANONICAL (mesh-shape-agnostic) form — for ZeRO-1 the
        flat sharded vectors are unflattened back to params-shaped trees
        (``AllReduceParameter.state_to_canonical``) — so the same
        checkpoint restores under any device count or parameter mode:
        the contract elastic restart (Tier 3) depends on."""
        return {
            **self._host_step_state(params, opt_state, mstate),
            # from the CALLER's state, not self.optim_method.state: the
            # watchdog-thread halt path passes a snapshot taken next to
            # its _live_state read, and re-reading the live dict here
            # could pair step-N params with step-N+1 counters if the
            # loop unwedges mid-halt (in the loop paths ``state`` IS
            # optim_method.state, so this is the same dict)
            "optim_host_state": dict(state),
            "epoch": state["epoch"], "neval": state["neval"],
        }

    def _host_step_state(self, params, opt_state, mstate):
        """Host copies of the in-step trees in the checkpoint's
        CANONICAL (mesh-shape-agnostic) form — the single definition the
        checkpoint payload and the Tier-2 replay snapshot share, and the
        exact shape :meth:`_restore_step_state` parses."""
        return {
            "params": _tmap(np.asarray, self._params_for_checkpoint(params)),
            "opt_state": self._opt_state_for_checkpoint(opt_state),
            "model_state": self._to_host(mstate),
        }

    def _checkpoint(self, params, opt_state, mstate, state, tag=None,
                    force_sync=False):
        """Write one checkpoint; returns its path. ``tag`` overrides the
        name suffix (remediation checkpoints are tagged so a post-mortem
        can tell a scheduled snapshot from a halt artifact — both match
        the ``checkpoint*.bigdl`` pattern every restore path globs).
        ``force_sync`` bypasses the async writer: a halt must not race
        its own exit."""
        if tag is None:
            tag = "" if self.checkpoint_overwrite else \
                f"_e{state['epoch']}_i{state['neval']}"
        path = os.path.join(self.checkpoint_path, f"checkpoint{tag}.bigdl")
        # the device→host fetch is the only synchronous part; serialization
        # and file IO can ride the writer thread (async_write)
        payload = self._checkpoint_payload(params, opt_state, mstate, state)
        async_write = self.checkpoint_async and not force_sync
        with obs.span("step/checkpoint_submit", async_write=async_write):
            if async_write:
                self._ckpt_writer.submit(path, payload)
            else:
                _atomic_pickle(path, payload)
        if obs.enabled():
            _flight.record("checkpoint", path=path, neval=state["neval"],
                           epoch=state["epoch"],
                           async_write=async_write)
        return path

    def wait_for_checkpoints(self):
        """Block until every async checkpoint write has landed (re-raising
        a writer failure). No-op for synchronous checkpoints."""
        self._ckpt_writer.flush()

    def _close_checkpoints(self, timeout=None):
        self._ckpt_writer.close(timeout=timeout)

    def load_checkpoint(self, path):
        """Resume training state from a snapshot (parity:
        Optimizer.setCheckpoint + File.load resume flow)."""
        with open(path, "rb") as f:
            payload = pickle.load(f)
        self.model.ensure_initialized()
        self.model.params = _tmap(jnp.asarray, payload["params"])
        self.model.state = _tmap(jnp.asarray, payload["model_state"])
        self.optim_method.state.update(payload["optim_host_state"])
        self._resume_opt_state = _tmap(jnp.asarray, payload["opt_state"])
        return self

    def _validate(self, state):
        if self.validation_set is None:
            return None
        was_training = self.model.train_mode
        self.model.evaluate()
        from .evaluator import Evaluator
        with obs.span("step/validate", neval=state["neval"]):
            results = Evaluator(self.model).evaluate(
                self.validation_set, self.validation_methods,
                self.validation_batch)
        if was_training:
            self.model.training()
        scores = {}
        for method, res in zip(self.validation_methods, results):
            val, _ = res.result()
            scores[repr(method)] = val
            if self.val_summary is not None:
                # triggers gate recording: "Validation" gates every
                # validation scalar, the per-method tag gates one
                rec = self.val_summary.should_record
                if rec("Validation", state) and rec(repr(method), state):
                    self.val_summary.add_scalar(repr(method), val,
                                                state["neval"])
        if scores:
            state["score"] = list(scores.values())[0]
        return scores

    # -- main loop -------------------------------------------------------
    def optimize(self) -> Module:
        """Run training to the end trigger. With observability enabled
        the run is health-instrumented: the step loop and stager pulse
        stall beacons, the resolved losses feed the anomaly detector
        and the flight recorder, device-memory gauges register when the
        backend supports them, and an unhandled failure (including the
        NaN-policy aborts) dumps a flight-recorder crash bundle before
        re-raising — ``tools/flight_report.py`` renders it."""
        self._halt_requested = None
        self._live_state = None
        self._remediation_lr_scale = 1.0
        self._remediating = False
        if self.remediation is not None:
            self.remediation.reset_run_state()
        # with a remediation policy the stall callback is the Tier-1
        # handler (which chains any user on_stall); without one the
        # user callback rides the beacon directly as before
        on_stall = self._stall_handler if self.remediation is not None \
            else self.on_stall
        # the beacon opens at the startup grace (first dispatch = whole
        # XLA compile, legitimately silent for minutes) and is tightened
        # to the steady-state deadline when the first step lands
        deadline = self.stall_deadline_s \
            if self.stall_deadline_s is not None \
            else _health.default_stall_deadline()
        grace = self.stall_startup_grace_s \
            if self.stall_startup_grace_s is not None \
            else max(deadline, _health.default_stall_deadline())
        self._step_beacon = _health.beacon(
            "optim/step", deadline_s=max(grace, deadline),
            on_stall=on_stall)
        self._stall_grace_pending = (
            grace > deadline
            and self._step_beacon is not _health.NULL_BEACON)
        self._profiler = _health.profiler_window_from_env()
        self._loss_monitor = None
        if self.anomaly_config is not None and \
                (obs.enabled() or self.remediation is not None):
            # remediation's anomaly-driven control consumes the monitor's
            # returned events, so it runs even with observability off
            self._loss_monitor = _health.SeriesMonitor(
                "loss", **self.anomaly_config)
        if obs.enabled():
            _health.ensure_memory_telemetry()
            # re-read the snapshot cadence per run (tests and launchers
            # set BIGDL_TPU_METRIC_SNAP_S around individual runs)
            self._snap_writer = _cluster.default_writer()
            st = self.optim_method.state
            _flight.record("train/start", epoch=st.get("epoch"),
                           neval=st.get("neval"), seed=engine.get_seed(),
                           batch_size=self.batch_size,
                           superstep=self.superstep,
                           sync_policy=self.sync_policy)
        try:
            return self._optimize_impl()
        except TrainingHalted:
            raise  # Tier-1 already landed its checkpoint + bundle
        except BaseException as e:
            if obs.enabled():
                st = self.optim_method.state
                _flight.dump_crash_bundle(error=e, context={
                    "component": "optimizer",
                    "epoch": st.get("epoch"), "neval": st.get("neval"),
                    "seed": engine.get_seed(),
                    "batch_size": self.batch_size,
                    "superstep": self.superstep,
                    "sync_policy": self.sync_policy,
                    "nan_policy": self.nan_policy})
            raise
        finally:
            if self._snap_writer.enabled and obs.enabled():
                # terminal snapshot: the cluster merge must see this
                # process's END state, not its last cadence tick —
                # final=True so a finished process never reads as a
                # suspect-dead straggler once its snapshot goes stale
                self._snap_writer.write(
                    step=self.optim_method.state.get("neval"), final=True)
            self._step_beacon.close()
            self._step_beacon = _health.NULL_BEACON
            self._live_state = None
            if self._profiler is not None:
                self._profiler.close()
                self._profiler = None
            try:
                # idempotent (the success path already closed it,
                # UNBOUNDED — durability on a clean exit): a
                # TrainingHalted/crash exit must not leak the async
                # writer thread or let its queued stale writes keep
                # landing under the ElasticRunner's NEXT attempt, and
                # must not block forever on storage wedged badly enough
                # to be part of why we're halting
                self._close_checkpoints(timeout=30.0)
            except Exception:
                _LOG.exception("async checkpoint writer close failed")

    def _optimize_impl(self) -> Module:
        self.model.ensure_initialized()
        self.model.training()
        params, mstate = self.model.params, self.model.state
        opt_state = getattr(self, "_resume_opt_state", None)
        if opt_state is None:
            opt_state = self.optim_method.init_state(params)
        params, opt_state, mstate = self._prepare(params, opt_state, mstate)
        engine.maybe_enable_compilation_cache()
        with obs.span("optimizer/build_step"):
            self._step_fn = self._build_step()
        if obs.enabled():
            obs.gauge("engine/compile_cache_entries").set(
                engine.compilation_cache_entries())
        # never consume a dead run's in-flight losses
        self._pending_loss = None
        self._loss_window.clear()

        optim = self.optim_method
        state = optim.state  # {'neval', 'epoch', ...}
        batched = self._batched()
        done = False
        nan_streak = 0
        while not done:
            batched.shuffle()
            epoch_start = time.time()
            # the stager owns produce + device placement; with
            # prefetch_depth >= 2 both run on a lookahead thread while
            # the device computes, otherwise inline (the serial loop).
            # With superstep K > 1 it also owns the stacking stage:
            # groups of K microbatches assemble into [K, batch, ...]
            # device stacks and the hot loop dequeues one per dispatch.
            if self.superstep > 1:
                batches = staged(batched.data(train=True),
                                 self._stage_minibatch_host,
                                 depth=self.prefetch_depth, name="stager",
                                 group=self.superstep,
                                 group_fn=self._stage_group,
                                 group_key=self._stage_group_key,
                                 stall_deadline_s=self.stall_deadline_s)
            else:
                batches = staged(batched.data(train=True),
                                 self._stage_minibatch,
                                 depth=self.prefetch_depth, name="stager",
                                 stall_deadline_s=self.stall_deadline_s)
            box = {"params": params, "opt_state": opt_state,
                   "mstate": mstate, "nan_streak": nan_streak, "done": done}
            try:
                if self.superstep > 1:
                    self._run_epoch_supersteps(batches, state, box)
                else:
                    self._run_epoch_steps(batches, state, box)
            finally:
                batches.close()  # join the stager thread — no leaks, ever
            params, opt_state, mstate = \
                box["params"], box["opt_state"], box["mstate"]
            nan_streak, done = box["nan_streak"], box["done"]
            if not done:
                state["epoch"] += 1
                state["epoch_finished"] = True
                self.metrics.add("epoch_time", time.time() - epoch_start)
                if obs.enabled():
                    _flight.record("epoch", epoch=state["epoch"] - 1,
                                   neval=state["neval"],
                                   epoch_time_s=time.time() - epoch_start)
                self._fire_epoch(state, params, opt_state, mstate)
                if self.end_trigger(state):
                    done = True

        # drain the async/window in-flight losses (a NaN pending on the
        # final steps must not be swallowed)
        self._drain_pending_losses(state)
        self.model.params, self.model.state = \
            self._collect(params, mstate, opt_state)
        self.model.grad_params = _tmap(jnp.zeros_like, self.model.params)
        self._close_checkpoints()  # land async writes, stop the writer
        return self.model

    # -- self-healing tiers ---------------------------------------------
    def _dispatch_guarded(self, params, opt_state, mstate, *args):
        """The dispatch path, wrapped by the Tier-2 FaultPolicy when
        armed: snapshot the resolved host-side state BEFORE the call
        (the compiled step donates its state buffers — after a failed
        dispatch the device arrays may already be invalidated, so the
        replay must re-place from host), then on a retryable failure
        back off, restore, and replay the same step (or whole superstep
        group: same batches, same lr vector, same rng keys — bitwise
        the trajectory a fault-free run takes). Non-retryable failures
        propagate untouched."""
        fp = self.fault_policy
        if fp is None:
            return self._step_fn(params, opt_state, mstate, *args)
        snap = self._host_step_state(params, opt_state, mstate)
        if obs.enabled():
            obs.counter("optim/fault_snapshots").inc()
        while True:
            try:
                out = self._step_fn(params, opt_state, mstate, *args)
                # async dispatch defers device/collective failures to
                # the first readback, which happens at the loss sync far
                # OUTSIDE this guard — resolve here so a transient
                # surfaces where the retry can catch it (the armed path
                # is already serialized by the per-dispatch snapshot)
                jax.block_until_ready(out)  # sync-ok: Tier-2 fault guard
                fp.record_success()
                return out
            except FloatingPointError:
                raise  # NaN policy owns numeric failures, not the retry tier
            except Exception as e:
                cls = classify_failure(e)
                if not fp.should_retry(cls):
                    if obs.enabled():
                        _health.emit("fault_exhausted", failure_class=cls,
                                     error=f"{type(e).__name__}: {e}",
                                     consecutive=fp.consecutive)
                    raise
                fp.record_failure()
                delay = fp.backoff_s()
                # mirrors into the registry as optim/fault_retries; the
                # health/fault_retry counter rides the emit below
                self.metrics.add("fault_retries", 1.0)
                if obs.enabled():
                    _health.emit("fault_retry", failure_class=cls,
                                 error=f"{type(e).__name__}: {e}",
                                 attempt=fp.consecutive,
                                 backoff_s=round(delay, 3))
                if delay > 0:
                    fp.sleep(delay)
                params, opt_state, mstate = self._restore_step_state(snap)

    def _tighten_stall_deadline(self):
        """Drop the beacon's startup compile grace down to the
        steady-state stall deadline — called once the first dispatch
        completes (one bool check per step after that)."""
        if not self._stall_grace_pending:
            return
        self._stall_grace_pending = False
        # pulse BEFORE lowering the deadline: the beacon's age still
        # spans the whole compile, which would trip the tight deadline
        # instantly; the completed first dispatch IS the progress signal
        self._step_beacon.pulse()
        self._step_beacon.deadline_s = self.stall_deadline_s
        _health.watchdog().poke()  # recompute the check interval now

    def _check_halt(self):
        """Surface a halt the watchdog-thread remediation requested
        while this loop was blocked (checked at every iteration top and
        after every dispatch)."""
        if self._halt_requested is not None:
            ex, self._halt_requested = self._halt_requested, None
            raise ex

    def _try_halt_checkpoint(self, state, live):
        """Drain queued async writes, then land the synchronous
        remediation checkpoint from ``live`` ``(params, opt_state,
        mstate)``. Best-effort: any failure logs and returns None — it
        must not mask the halt."""
        try:
            self.wait_for_checkpoints()
        except Exception:
            _LOG.exception("async checkpoint drain failed during remediation")
        if live is None:
            return None
        try:
            p, o, m = live
            return self._checkpoint(
                p, o, m, state, force_sync=True,
                tag=f"_remediation_e{state.get('epoch', 0)}"
                    f"_i{state.get('neval', 0)}")
        except Exception:
            _LOG.exception(
                "remediation checkpoint failed (halting anyway; "
                "a wedged dispatch may have donated the live "
                "buffers)")
            return None

    def _land_halt_checkpoint(self, state, live, timeout_s=None):
        """Checkpoint step of the halt landing. ``timeout_s`` bounds the
        attempt on a disposable daemon worker: the device→host fetch
        inside has no deadline of its own, and on a DEAD mesh it blocks
        forever — which must never wedge the single watchdog monitor
        thread stall remediation runs on (``exit_process`` would never
        fire and every other beacon would go unmonitored). On expiry
        the worker is abandoned and the halt proceeds without a
        checkpoint (the flight bundle and ``TrainingHalted`` are pure
        host-side work and still land)."""
        if not self.checkpoint_path:
            return None
        if timeout_s is None:
            return self._try_halt_checkpoint(state, live)
        res = _run_with_timeout(
            lambda: self._try_halt_checkpoint(state, live), timeout_s)
        if res.get("timeout"):
            _LOG.error(
                "remediation checkpoint did not land within %.1fs "
                "(device fetch wedged on a dead mesh?); halting "
                "without one", timeout_s)
            return None
        return res.get("value")

    def _land_halt_artifacts(self, cause, state, live, error=None,
                             failure_class=PERMANENT, lost_processes=(),
                             ckpt_timeout_s=None, **extra):
        """Shared Tier-1 artifact landing — the loop-side :meth:`_halt`
        and the watchdog-thread :meth:`_stall_handler` must stay in
        lockstep, so there is exactly one copy: drain in-flight async
        checkpoint writes FIRST (a queued pre-halt write landing after
        the remediation snapshot would out-mtime it and
        ``find_latest_checkpoint`` would silently resume stale state),
        land the synchronous remediation checkpoint when the ``live``
        ``(params, opt_state, mstate)`` handles are available (bounded
        by ``ckpt_timeout_s`` when the caller cannot afford to block —
        see :meth:`_land_halt_checkpoint`), dump the flight bundle,
        emit ``health/remediation``, and return the
        :class:`TrainingHalted` for the caller to raise (step loop) or
        queue (watchdog thread). Every artifact is best-effort — a
        failure must not mask the halt."""
        ckpt = self._land_halt_checkpoint(state, live,
                                          timeout_s=ckpt_timeout_s)
        bundle = _flight.dump_crash_bundle(error=error, context={
            "component": "optimizer/remediation", "cause": cause,
            "failure_class": failure_class,
            "epoch": state.get("epoch"), "neval": state.get("neval"),
            "checkpoint": ckpt,
            "lost_processes": list(lost_processes), **extra})
        _health.emit("remediation", cause=cause,
                     failure_class=failure_class, checkpoint=ckpt,
                     bundle=bundle, neval=state.get("neval"),
                     lost_processes=list(lost_processes), **extra)
        return TrainingHalted(
            cause=cause, failure_class=failure_class, checkpoint_path=ckpt,
            bundle_path=bundle, epoch=state.get("epoch"),
            neval=state.get("neval"), lost_processes=lost_processes)

    def _halt(self, cause, state, params, opt_state, mstate, error=None,
              failure_class=PERMANENT, lost_processes=()):
        """Tier-1 checkpoint-and-exit from the step loop itself: land
        the halt artifacts and raise the :class:`TrainingHalted` they
        describe. The checkpoint fetch is bounded just like the
        watchdog path's: a heartbeat-loss halt is often remediating a
        mesh with a DEAD peer, and an unbounded device→host fetch of
        state sharded across it would wedge the run inside its own
        remediation."""
        pol = self.remediation
        raise self._land_halt_artifacts(
            cause, state, (params, opt_state, mstate), error=error,
            failure_class=failure_class, lost_processes=lost_processes,
            ckpt_timeout_s=pol.halt_artifact_timeout_s
            if pol is not None else None) from error

    def _stall_handler(self, beacon, age_s):
        """Watchdog-fired stall remediation entry: run the user's
        ``on_stall`` inline (cheap, PR-5 contract), then hand the
        classify-and-land work to a disposable side thread — the probe
        (``probe_timeout_s``) plus the bounded halt checkpoint
        (``halt_artifact_timeout_s``) can block for minutes, and the
        SINGLE watchdog monitor thread must keep checking every other
        beacon (serving batcher, stager, heartbeat prober) meanwhile.
        The beacon stays latched until the side thread's verdict
        (re-arm or halt), so one episode spawns one remediation."""
        if self.on_stall is not None:
            try:
                self.on_stall(beacon, age_s)
            except Exception:
                _LOG.exception("on_stall failed")
        pol = self.remediation
        if pol is None or self._halt_requested is not None \
                or self._remediating:
            return
        self._remediating = True
        threading.Thread(target=self._remediate_stall,
                         args=(beacon, age_s),
                         name="bigdl-stall-remediation",
                         daemon=True).start()

    def _remediate_stall(self, beacon, age_s):
        """Side-thread body of stall remediation: probe the mesh to
        classify the stall, and for a dead mesh (or ``halt_on_stall``)
        land the halt artifacts — the step loop is the thing that
        stopped, so it cannot save itself. The checkpoint comes from
        ``_live_state`` (the handles of the last COMPLETED dispatch —
        consistent by construction; best-effort if the wedged dispatch
        already donated them), then the halt is queued for the loop to
        raise if it ever unwedges; ``exit_process`` force-exits for
        loops that never will."""
        try:
            pol = self.remediation
            cls, err = TRANSIENT, None
            mesh = getattr(self, "mesh", None)
            if mesh is not None and pol.probe_timeout_s > 0:
                res = probe_mesh(mesh, timeout_s=pol.probe_timeout_s)
                if not res.ok:
                    cls = PERMANENT
                    err = RuntimeError(
                        f"mesh probe failed after {age_s:.1f}s stall of "
                        f"{beacon.name}: {res.error}")
            if cls != PERMANENT and not pol.halt_on_stall:
                # transient verdict: the watchdog already paged — but a
                # wedged loop will never pulse the stall latch clear
                # itself, and the monitor skips latched beacons, so
                # re-arm the deadline clock: a mesh that dies LATER in
                # the same stall episode gets probed (and halted) again
                # instead of hanging the run with remediation armed
                beacon.rearm()
                return
            # snapshot: if the loop unwedges mid-handler, a live state
            # dict would shear (tag, payload and exception each reading
            # a different neval)
            state = dict(self.optim_method.state)
            self._halt_requested = self._land_halt_artifacts(
                "stall", state, self._live_state, error=err,
                failure_class=cls, stalled_component=beacon.name,
                age_s=round(age_s, 3),
                ckpt_timeout_s=pol.halt_artifact_timeout_s)
            if pol.exit_process:
                os._exit(86)  # artifacts are on disk; the loop never is
        except Exception:
            _LOG.exception("stall remediation failed")
        finally:
            self._remediating = False

    def _apply_anomaly_events(self, pol, state, events):
        """Anomaly-driven control: act on the health events the loss
        monitor fired for THIS iteration's resolved losses. Returns
        True when the run should end cleanly (plateau early-stop)."""
        for ev in events:
            kind = ev.get("kind", "")
            if kind == "health/plateau":
                pol.plateaus += 1
                if pol.plateau_lr:
                    self._reduce_lr_for_plateau(pol, state)
                if pol.early_stop_plateaus is not None and \
                        pol.plateaus >= pol.early_stop_plateaus:
                    _health.emit("early_stop", reason="plateau",
                                 neval=state["neval"],
                                 plateaus=pol.plateaus)
                    return True
            elif kind.endswith("_spike"):
                pol.spikes += 1
                if pol.max_spikes is not None and \
                        pol.spikes >= pol.max_spikes:
                    raise FloatingPointError(
                        f"{pol.spikes} loss spikes "
                        f"(RemediationPolicy.max_spikes="
                        f"{pol.max_spikes}) — the run is diverging")
        return False

    def _reduce_lr_for_plateau(self, pol, state):
        sched = getattr(self.optim_method, "learningrate_schedule", None)
        if isinstance(sched, Plateau):
            mult = sched.force_reduction()
        else:
            self._remediation_lr_scale = max(
                self._remediation_lr_scale * pol.plateau_factor,
                pol.min_lr_scale)
            mult = self._remediation_lr_scale
        _health.emit("lr_reduced", reason="plateau", neval=state["neval"],
                     multiplier=mult,
                     schedule=type(sched).__name__ if sched else None)
        if obs.enabled():
            # under superstep fusion the reduction is applied to the
            # NEXT group's lr vector (the detection itself came off
            # this group's batched loss readback) — the instant marks
            # where the policy acted so the one-group lag is visible
            obs.counter("optim/lr_reductions").inc()
            obs.instant("optim/lr_reduced", neval=state["neval"],
                        multiplier=mult)

    def _remediation_tick(self, state, params, opt_state, mstate,
                          events, step_time_s=None):
        """One per-iteration (per-superstep-group under fusion) pass of
        the Tier-1 policy. Returns True when training should end
        cleanly; raises via :meth:`_halt` on heartbeat loss or spike
        overload. Runs host-side between dispatches — no readbacks
        beyond what the sync policy already resolved."""
        pol = self.remediation
        if pol is None:
            return False
        try:
            if events and self._apply_anomaly_events(pol, state, events):
                return True
        except FloatingPointError as e:
            self._halt("loss_spikes", state, params, opt_state, mstate,
                       error=e, failure_class=PERMANENT)
        hb = pol.heartbeat
        if hb is not None and \
                state["neval"] - pol._last_beat_neval >= pol.heartbeat_every:
            pol._last_beat_neval = state["neval"]
            try:
                stale = hb.beat(timeout_s=pol.heartbeat_timeout_s)
            except HeartbeatLost as e:
                self._halt("heartbeat_lost", state, params, opt_state,
                           mstate, error=e, failure_class=PERMANENT)
            if stale:
                self._halt("heartbeat_stale", state, params, opt_state,
                           mstate, failure_class=PERMANENT,
                           error=HeartbeatLost(
                               f"peers {stale} stopped advancing their "
                               f"heartbeat counters"),
                           lost_processes=stale)
        sm = pol.straggler_monitor
        if sm is not None:
            if step_time_s is not None:
                sm.record(step_time_s)
            # distance-based cadence, not ``% == 0``: under superstep
            # fusion neval advances by K and might never land on a
            # multiple (the heartbeat check above has the same shape)
            if state["neval"] - pol._last_straggler_neval >= \
                    pol.straggler_every:
                pol._last_straggler_neval = state["neval"]
                sm.report()  # emits health/straggler on persistence
        return False

    def _run_epoch_steps(self, batches, state, box):
        """One epoch of the pipelined step loop. ``batches`` yields
        device-resident (x, y) (already staged by the caller's stager);
        mutable step state travels in ``box``
        (params/opt_state/mstate/nan_streak/done) so every exit path —
        exhaustion, end trigger, an exception mid-step — leaves the
        caller with the latest device handles."""
        optim = self.optim_method
        params, opt_state, mstate = \
            box["params"], box["opt_state"], box["mstate"]
        nan_streak = box["nan_streak"]
        try:
            while True:
                self._step_beacon.pulse()
                self._check_halt()
                with obs.span("step", neval=state["neval"]):
                    t0 = time.time()
                    with obs.span("step/data_fetch"):
                        try:
                            x, y = next(batches)
                        except StopIteration:
                            return
                    t1 = time.time()
                    # *1.0 is bitwise-exact: the remediation scale only
                    # changes lr after a plateau actually reduced it
                    lr = optim.current_lr() * self._remediation_lr_scale
                    rng = engine.next_rng_key()
                    dsp = obs.span("step/dispatch")
                    with dsp:
                        loss, params, opt_state, mstate = \
                            self._dispatch_guarded(
                                params, opt_state, mstate, x, y,
                                jnp.asarray(lr, jnp.float32), rng)
                    # the last COMPLETED dispatch's handles: what the
                    # watchdog-thread stall remediation checkpoints
                    self._live_state = (params, opt_state, mstate)
                    self._tighten_stall_deadline()
                    if obs.enabled():
                        obs.counter("engine/dispatches").inc()
                    with obs.span("step/loss_sync"):
                        # step provenance: the dispatch just issued is
                        # iteration neval+1; async/window:K resolve an
                        # OLDER one — _resolved_step names it
                        loss_val = self._observe_loss(
                            loss, state["neval"] + 1)
                    t2 = time.time()
                    if loss_val is not None and not np.isfinite(loss_val):
                        nan_streak += 1
                        if obs.enabled():
                            _flight.record("nan",
                                           neval=self._resolved_step,
                                           epoch=state["epoch"],
                                           loss=loss_val,
                                           policy=self.nan_policy)
                        if self._loss_monitor is not None:
                            self._loss_monitor.observe(
                                loss_val, self._resolved_step)
                        if self.nan_policy == "error":
                            raise FloatingPointError(
                                f"non-finite loss {loss_val} at iteration "
                                f"{state['neval']} — enable "
                                f"set_nan_policy('skip') to drop such steps")
                        if nan_streak > self.max_nan_retries:
                            raise FloatingPointError(
                                f"{nan_streak} consecutive non-finite steps "
                                f"(nan_policy='{self.nan_policy}') — data or "
                                "hyperparameters are unrecoverably bad")
                        if self.nan_policy == "resume":
                            self.wait_for_checkpoints()  # in-flight writes
                            snap = self._latest_checkpoint()
                            if snap is None:
                                raise FloatingPointError(
                                    "non-finite loss with nan_policy='resume' "
                                    "but no checkpoint saved yet — call "
                                    "set_checkpoint(...) first")
                            with open(snap, "rb") as f:
                                payload = pickle.load(f)
                            self.optim_method.state.update(
                                payload["optim_host_state"])
                            params, opt_state, mstate = \
                                self._restore_step_state(payload)
                            # in-flight losses refer to pre-restore steps
                            self._pending_loss = None
                            self._loss_window.clear()
                            self.metrics.add("nan_resumes", 1.0)
                            obs.instant("step/nan_resume", neval=state["neval"])
                            continue
                        # 'skip': the in-step guard already kept the previous
                        # params; count the iteration so end triggers advance
                        self.metrics.add("nan_skips", 1.0)
                        obs.instant("step/nan_skip", neval=state["neval"])
                        state["neval"] += 1
                        continue
                    if loss_val is not None:
                        # windowed policies have no resolved loss until K
                        # are in flight — the streak/loss state only moves
                        # on an actually-observed value
                        nan_streak = 0
                        state["loss"] = loss_val
                    state["neval"] += 1
                    state["epoch_finished"] = False
                    health_events = []
                    if loss_val is not None:
                        # provenance rides the already-resolved host
                        # float — no extra readback; under async/
                        # window:K the loss belongs to _resolved_step,
                        # up to K-1 before the current iteration
                        if obs.enabled():
                            _flight.record("step",
                                           neval=self._resolved_step,
                                           epoch=state["epoch"],
                                           loss=loss_val)
                        if self._loss_monitor is not None:
                            health_events = self._loss_monitor.observe(
                                loss_val, self._resolved_step)
                    if self._profiler is not None:
                        self._profiler.maybe_tick(state["neval"])
                    self.metrics.add("data_time", t1 - t0)
                    self.metrics.add("step_time", t2 - t1)
                    if obs.enabled():
                        obs.counter("optim/steps").inc()
                        obs.gauge("optim/throughput", unit="samples/s").set(
                            self.batch_size / max(t2 - t0, 1e-9))
                        # live MFU + step-phase gauges: host floats the
                        # loop already measured, zero new readbacks. A
                        # dispatch that paid a compile measures XLA, not
                        # the model — excluded, like bench warmup. The
                        # wall is the FULL iteration (t0→t2): under
                        # async/window:K the dispatch+resolve sliver
                        # alone excludes the device time entirely.
                        if not getattr(self._step_fn, "last_call_compiled",
                                       True):
                            obs.perf.note_step(
                                getattr(self._step_fn, "last_artifact",
                                        None),
                                wall_s=t2 - t0, host_s=t1 - t0,
                                dispatch_s=dsp.duration_s)
                        self._snap_writer.maybe_write(step=state["neval"])
                    if self.train_summary is not None:
                        rec = self.train_summary.should_record
                        if loss_val is not None and rec("Loss", state):
                            self.train_summary.add_scalar("Loss", loss_val,
                                                          state["neval"])
                        if rec("LearningRate", state):
                            self.train_summary.add_scalar("LearningRate", lr,
                                                          state["neval"])
                        if rec("Throughput", state):
                            self.train_summary.add_scalar(
                                "Throughput",
                                self.batch_size / max(t2 - t0, 1e-9),
                                state["neval"])
                    if self._remediation_tick(state, params, opt_state,
                                              mstate, health_events,
                                              step_time_s=t2 - t1):
                        box["done"] = True
                        return
                    if self._fire_mid_epoch(state, params, opt_state, mstate):
                        pass
                    if self.end_trigger(state):
                        box["done"] = True
                        return
        finally:
            box.update(params=params, opt_state=opt_state, mstate=mstate,
                       nan_streak=nan_streak)

    def _clamp_superstep(self, state, k):
        """Largest j <= k such that no end/validation/checkpoint trigger
        would fire at an iteration INTERIOR to a j-step dispatch: the
        triggers are probed (side-effect-free) at the simulated counters
        neval+1 .. neval+k-1, and the dispatch is cut so any firing point
        lands exactly on a superstep boundary — host bookkeeping then
        runs at the same iteration it would under K=1. Loss/score-driven
        triggers are probed with the values as observed so far (the
        superstep-granularity lag documented in set_superstep)."""
        if k <= 1:
            return k
        triggers = [t for t in (self.end_trigger, self.validation_trigger,
                                self.checkpoint_trigger) if t is not None]
        if not triggers:
            return k
        sim = dict(state)
        sim["epoch_finished"] = False
        for i in range(1, k):
            sim["neval"] = state["neval"] + i
            for t in triggers:
                fired = t.probe(sim) if hasattr(t, "probe") \
                    else bool(t(dict(sim)))
                if fired:
                    return i
        return k

    def _run_epoch_supersteps(self, batches, state, box):
        """Superstep (K > 1) epoch loop: ``batches`` yields stacked
        ``(k, xs, ys)`` groups; each dispatch runs k fused steps inside
        one XLA program and the host resolves the whole ``[k]`` loss
        vector with ONE batched readback — per-step bookkeeping (loss
        observation, NaN policy, summaries, triggers) then replays
        host-side over the resolved vector, preserving K=1 semantics at
        1/K the sync count. Same ``box`` contract as _run_epoch_steps."""
        optim = self.optim_method
        params, opt_state, mstate = \
            box["params"], box["opt_state"], box["mstate"]
        nan_streak = box["nan_streak"]
        pending = None  # clamped remainder of a group (device slices)
        try:
            while True:
                self._step_beacon.pulse()
                self._check_halt()
                t0 = time.time()
                if pending is not None:
                    (k, xs, ys), pending = pending, None
                else:
                    with obs.span("step/data_fetch"):
                        try:
                            k, xs, ys = next(batches)
                        except StopIteration:
                            return
                j = self._clamp_superstep(state, k)
                if j < k:
                    # a trigger fires mid-group: dispatch the prefix now,
                    # park the rest (device-side slices — no host copy)
                    pending = (k - j, _tmap(lambda a: a[j:], xs),
                               _tmap(lambda a: a[j:], ys))
                    xs = _tmap(lambda a: a[:j], xs)
                    ys = _tmap(lambda a: a[:j], ys)
                    k = j
                scale = self._remediation_lr_scale  # *1.0 is bitwise-exact
                lrs = [l * scale for l in optim.current_lr_vector(k)]
                rngs = engine.next_rng_keys(k)  # one dispatch, same stream
                t1 = time.time()
                with obs.span("step/superstep", neval=state["neval"], k=k):
                    dsp = obs.span("step/dispatch")
                    with dsp:
                        losses_dev, params, opt_state, mstate = \
                            self._dispatch_guarded(
                                params, opt_state, mstate, xs, ys,
                                jnp.asarray(lrs, jnp.float32), rngs)
                    self._live_state = (params, opt_state, mstate)
                    self._tighten_stall_deadline()
                    if obs.enabled():
                        obs.counter("engine/dispatches").inc()
                    with obs.span("step/loss_sync"):
                        # sync-ok: the ONE batched [k] readback per superstep
                        losses = np.asarray(losses_dev)
                    if obs.enabled():
                        obs.counter("optim/loss_syncs").inc()
                t2 = time.time()
                self.metrics.add("data_time", t1 - t0)
                self.metrics.add("step_time", t2 - t1)
                if obs.enabled():
                    obs.counter("optim/steps").inc(k)
                    obs.gauge("optim/throughput", unit="samples/s").set(
                        k * self.batch_size / max(t2 - t0, 1e-9))
                    # one artifact covers the whole K-step program (a
                    # clamped j<K dispatch reads ITS program's artifact,
                    # not the full-K one), so flops over the FULL
                    # iteration wall IS the fused-dispatch MFU; compile
                    # dispatches are excluded like bench warmup
                    if not getattr(self._step_fn, "last_call_compiled",
                                   True):
                        obs.perf.note_step(
                            getattr(self._step_fn, "last_artifact", None),
                            wall_s=t2 - t0, host_s=t1 - t0,
                            dispatch_s=dsp.duration_s)
                    self._snap_writer.maybe_write(step=state["neval"])
                restored = False
                health_events = []
                for i, loss_val in enumerate(losses.tolist()):
                    if not np.isfinite(loss_val):
                        nan_streak += 1
                        if obs.enabled():
                            # superstep-vector aware: the host replay of
                            # the batched [k] readback feeds the recorder
                            # and detector per microstep
                            _flight.record("nan", neval=state["neval"],
                                           epoch=state["epoch"],
                                           loss=loss_val,
                                           policy=self.nan_policy,
                                           superstep_k=k, microstep=i)
                        if self._loss_monitor is not None:
                            self._loss_monitor.observe(loss_val,
                                                       state["neval"])
                        if self.nan_policy == "error":
                            raise FloatingPointError(
                                f"non-finite loss {loss_val} at iteration "
                                f"{state['neval']} — enable "
                                f"set_nan_policy('skip') to drop such steps")
                        if nan_streak > self.max_nan_retries:
                            raise FloatingPointError(
                                f"{nan_streak} consecutive non-finite steps "
                                f"(nan_policy='{self.nan_policy}') — data or "
                                "hyperparameters are unrecoverably bad")
                        if self.nan_policy == "resume":
                            self.wait_for_checkpoints()  # in-flight writes
                            snap = self._latest_checkpoint()
                            if snap is None:
                                raise FloatingPointError(
                                    "non-finite loss with nan_policy="
                                    "'resume' but no checkpoint saved yet "
                                    "— call set_checkpoint(...) first")
                            with open(snap, "rb") as f:
                                payload = pickle.load(f)
                            self.optim_method.state.update(
                                payload["optim_host_state"])
                            params, opt_state, mstate = \
                                self._restore_step_state(payload)
                            # the rest of this group's losses describe
                            # updates the restore just discarded
                            self.metrics.add("nan_resumes", 1.0)
                            obs.instant("step/nan_resume",
                                        neval=state["neval"])
                            restored = True
                            break
                        # 'skip': the in-scan guard already kept the
                        # previous state; count the iteration so end
                        # triggers advance
                        self.metrics.add("nan_skips", 1.0)
                        obs.instant("step/nan_skip", neval=state["neval"])
                        state["neval"] += 1
                        continue
                    nan_streak = 0
                    state["loss"] = loss_val
                    state["neval"] += 1
                    state["epoch_finished"] = False
                    if obs.enabled():
                        _flight.record("step", neval=state["neval"],
                                       epoch=state["epoch"], loss=loss_val,
                                       superstep_k=k, microstep=i)
                    if self._loss_monitor is not None:
                        health_events.extend(self._loss_monitor.observe(
                            loss_val, state["neval"]))
                    if self.train_summary is not None:
                        rec = self.train_summary.should_record
                        if rec("Loss", state):
                            self.train_summary.add_scalar(
                                "Loss", loss_val, state["neval"])
                        if rec("LearningRate", state):
                            self.train_summary.add_scalar(
                                "LearningRate", lrs[i], state["neval"])
                        if rec("Throughput", state):
                            self.train_summary.add_scalar(
                                "Throughput",
                                k * self.batch_size / max(t2 - t0, 1e-9),
                                state["neval"])
                if restored:
                    # the group's pre-NaN spike/plateau events describe
                    # losses that really happened — the policy must see
                    # them, or a diverging run that interleaves spikes
                    # with NaN restores starves max_spikes forever and
                    # loops checkpoint-restore indefinitely
                    if self._remediation_tick(state, params, opt_state,
                                              mstate, health_events,
                                              step_time_s=t2 - t1):
                        box["done"] = True
                        return
                    continue
                if self._profiler is not None:
                    self._profiler.maybe_tick(state["neval"])
                if self._remediation_tick(state, params, opt_state, mstate,
                                          health_events,
                                          step_time_s=t2 - t1):
                    box["done"] = True
                    return
                # checkpoint/validation/end triggers evaluate ONCE at the
                # superstep boundary, where params and the iteration
                # counter are consistent: clamping already aligned every
                # counter-driven firing point to a boundary, and a
                # loss-driven trigger (which the probe cannot foresee)
                # defers at most K-1 steps — it must never pair interior
                # counters with post-superstep params in a checkpoint
                if self._fire_mid_epoch(state, params, opt_state, mstate):
                    pass
                if self.end_trigger(state):
                    box["done"] = True
                    return
        finally:
            box.update(params=params, opt_state=opt_state, mstate=mstate,
                       nan_streak=nan_streak)

    def _fire_mid_epoch(self, state, params, opt_state, mstate):
        fired = False
        if self.validation_trigger is not None and \
                self.validation_trigger(state):
            self.model.params, self.model.state = \
                self._collect(params, mstate, opt_state)
            self._validate(state)
            fired = True
        if self.checkpoint_trigger is not None and \
                self.checkpoint_trigger(state):
            self._checkpoint(params, opt_state, mstate, state)
            fired = True
        return fired

    def _fire_epoch(self, state, params, opt_state, mstate):
        self._fire_mid_epoch(state, params, opt_state, mstate)

    # hooks overridden by DistriOptimizer
    def _to_host(self, tree):
        """Fetch a tree to host numpy for checkpointing."""
        return _tmap(np.asarray, tree)

    def _opt_state_for_checkpoint(self, opt_state):
        """Host optimizer state in CANONICAL (mesh-shape-agnostic) form;
        the local/replicated state already is — the ZeRO-1 override
        unflattens its sharded vectors."""
        return self._to_host(opt_state)

    def _prepare(self, params, opt_state, mstate):
        return params, opt_state, mstate

    def _collect(self, params, mstate, opt_state=None):
        return params, mstate

    def _params_for_checkpoint(self, params):
        return params

    def _restore_step_state(self, payload):
        """Rebuild in-step (params, opt_state, mstate) from a checkpoint
        payload WITHOUT recreating sharding machinery (the compiled step fn
        closes over it)."""
        return self._prepare(_tmap(jnp.asarray, payload["params"]),
                             _tmap(jnp.asarray, payload["opt_state"]),
                             _tmap(jnp.asarray, payload["model_state"]))


class LocalOptimizer(BaseOptimizer):
    """Single-device training (parity: optim/LocalOptimizer.scala — there,
    multi-threaded CPU minibatch stacking; here one XLA device owns the whole
    batch)."""


class DistriOptimizer(BaseOptimizer):
    """Mesh data-parallel training (parity: optim/DistriOptimizer.scala)."""

    def __init__(self, model, training_set, criterion, optim_method=None,
                 end_trigger=None, batch_size: int = 32, mesh=None,
                 parameter_mode: str = "replicated",
                 compress: str = "none", wire_dtype: str = "none",
                 sparse_embedding="auto"):
        """``compress`` / ``wire_dtype``: ZeRO-1 gradient-wire knobs
        (``parallel.allreduce`` module docstring) — ``compress`` is the
        legacy wire-dtype psum, ``wire_dtype`` the fp32-master-
        accumulation all_to_all wire. Both off by default; mutually
        exclusive.

        ``sparse_embedding``: per-layer gradient-wire path selection
        (the Parallax exchange — ``nn.sparse.
        sparse_embedding_grad_allreduce``, docs/DISTRIBUTED.md). The
        step is built as an explicit shard_map whose per-layer exchange
        picks, AT TRACE TIME from the static shapes, the cheaper wire
        for each gradient leaf: the model's leading embedding layer
        ships its touched ``(indices, value rows)`` when ``B_local *
        (H+1) < vocab * H`` elements, every other leaf (and an
        embedding whose batch would not win) rides the dense ``pmean``.
        Replicated parameter mode only — ZeRO-1's flat-vector wire has
        no per-layer seam.

        The default ``"auto"`` selects the wire by itself whenever it
        applies SAFELY — replicated mode, the model input is a leading
        ``LookupTable``'s ids, no ``w_regularizer`` on it — and rides
        the ordinary dense path otherwise. Pass ``True`` to make the
        selection a CONTRACT (a model the wire cannot serve is a typed
        refusal instead of a silent fallback), ``False`` to force the
        dense wire off entirely."""
        super().__init__(model, training_set, criterion, optim_method,
                         end_trigger, batch_size)
        from ..parallel.mesh import get_default_mesh
        self.mesh = mesh or get_default_mesh()
        if "data" not in self.mesh.axis_names:
            raise ValueError("DistriOptimizer mesh needs a 'data' axis")
        if sparse_embedding is True and parameter_mode != "replicated":
            raise ValueError(
                "sparse_embedding selects per-LAYER gradient wires — "
                "ZeRO-1 ships one flat vector and has no per-layer "
                "seam; use parameter_mode='replicated'")
        self.parameter_mode = parameter_mode
        self.compress = compress
        self.wire_dtype = wire_dtype
        self.sparse_embedding = sparse_embedding
        self._arp = None
        self._flat = None

    def _num_shards(self):
        return self.mesh.shape["data"]

    def _to_host(self, tree):
        # ZeRO-1 opt state is sharded P('data') across processes in
        # multi-controller runs; np.asarray on non-addressable shards
        # raises. gather_to_host reshards to replicated first (collective
        # — checkpoint triggers fire symmetrically on every process).
        from ..parallel.sharding import gather_to_host
        return gather_to_host(tree, self.mesh)

    def _check_split_agreement(self):
        """Multi-controller: every process feeds its own data split; if
        the per-process batch counts differ, the extra steps on the larger
        split would block forever in the cross-process psum. Fail loudly
        at setup instead of deadlocking mid-epoch."""
        from ..parallel.sharding import is_multi_process
        if not is_multi_process(self.mesh):
            return
        import jax.numpy as jnp
        from jax.experimental import multihost_utils
        src = self._batched()
        n = getattr(src, "batches_per_epoch", 0)
        n = int(n() if callable(n) else (n or 0))
        counts = np.asarray(multihost_utils.process_allgather(
            jnp.asarray([n], jnp.int32))).reshape(-1)
        if len(set(counts.tolist())) > 1:
            raise ValueError(
                "per-process dataset splits disagree on batches/epoch "
                f"{counts.tolist()}; pad or trim the local splits so every "
                "process takes the same number of steps (uneven splits "
                "deadlock in the cross-process gradient psum)")

    def _place_batch(self, x, y):
        from ..parallel.sharding import shard_batch
        return (shard_batch(x, self.mesh), shard_batch(y, self.mesh))

    def _place_group(self, xs, ys):
        from ..parallel.sharding import shard_stacked_batch
        return (shard_stacked_batch(xs, self.mesh),
                shard_stacked_batch(ys, self.mesh))

    def _prepare(self, params, opt_state, mstate):
        from ..parallel.sharding import shard_params, put_global
        self._check_split_agreement()
        if self.parameter_mode == "zero1":
            from ..parallel.allreduce import AllReduceParameter
            self._arp = AllReduceParameter(
                self.optim_method, self.mesh, compress=self.compress,
                wire_dtype=getattr(self, "wire_dtype", "none"))
            # a loaded checkpoint's optimizer state is CANONICAL
            # (params-shaped, mesh-agnostic): prepare() re-flattens and
            # re-pads it against THIS mesh's shard boundaries, so the
            # same snapshot restores under any device count — the
            # elastic-restart contract. Without a loaded checkpoint the
            # state passed in is a fresh init for the wrong (tree)
            # layout; the sharded init replaces it.
            resume = opt_state \
                if getattr(self, "_resume_opt_state", None) is not None \
                else None
            flat_w, opt_state = self._arp.prepare(params,
                                                  resume_state=resume)
            self._flat = self._arp.flat
            mstate = shard_params(mstate, self.mesh)
            return put_global(flat_w, self.mesh, P()), opt_state, mstate
        params = shard_params(params, self.mesh)
        opt_state = shard_params(opt_state, self.mesh)
        mstate = shard_params(mstate, self.mesh)
        return params, opt_state, mstate

    def _collect(self, params, mstate, opt_state=None):
        if self.parameter_mode == "zero1":
            return self._flat.unflatten(jax.device_get(params)), mstate
        return params, mstate

    def _params_for_checkpoint(self, params):
        if self.parameter_mode == "zero1":
            return self._flat.unflatten(jax.device_get(params))
        return params

    def _opt_state_for_checkpoint(self, opt_state):
        if self.parameter_mode == "zero1" and self._arp is not None:
            # gather the sharded flat vectors, then unflatten to the
            # canonical params-shaped form — the checkpoint carries no
            # shard-boundary provenance (restores under any mesh shape)
            return self._arp.state_to_canonical(self._to_host(opt_state))
        return self._to_host(opt_state)

    def _restore_step_state(self, payload):
        from ..parallel.sharding import shard_params, put_global
        params = _tmap(jnp.asarray, payload["params"])
        mstate = shard_params(_tmap(jnp.asarray, payload["model_state"]),
                              self.mesh)
        if self.parameter_mode == "zero1" and self._arp is not None:
            # reuse the existing FlatParameter/AllReduceParameter — the
            # compiled step closes over them; only re-place the data.
            # The payload's optimizer state is canonical (params-shaped;
            # legacy flat vectors are re-padded too) — widen it back to
            # THIS mesh's flat shard layout before placing.
            flat_w = put_global(self._flat.flatten(params), self.mesh, P())
            opt_state = self._arp.place_canonical_state(
                payload["opt_state"])
            return flat_w, opt_state, mstate
        opt_state = _tmap(jnp.asarray, payload["opt_state"])
        return (shard_params(params, self.mesh),
                shard_params(opt_state, self.mesh), mstate)

    def _sparse_embedding_path(self):
        """Locate the embedding layer whose ids are the model input:
        the model itself, or the first child of a leading Sequential.
        Returns ``(param_path, vocab_size)`` — the gradient leaf at
        ``param_path`` is the one whose wire the per-layer selection
        may route sparse (its row ids are ``clip(input - 1, ...)``,
        the LookupTable's 1-based convention)."""
        from ..nn.linear import LookupTable
        m = self.model
        emb, path = None, None
        if isinstance(m, LookupTable):
            emb, path = m, ("weight",)
        else:
            mods = getattr(m, "modules", None)
            if mods and isinstance(mods[0], LookupTable):
                emb, path = mods[0], ("0", "weight")
        if emb is None:
            raise ValueError(
                "sparse_embedding=True needs the model input to BE the "
                "embedding ids: a LookupTable model, or a Sequential "
                "whose first child is a LookupTable — got "
                f"{type(m).__name__}")
        if emb.w_regularizer is not None:
            # weight decay's gradient is DENSE (lambda*w on every vocab
            # row); the (indices, values) exchange ships only the rows
            # this batch touched, so a regularized embedding would
            # silently train different weights than the dense wire
            raise ValueError(
                "sparse_embedding=True cannot ride a w_regularizer'd "
                "embedding: the regularizer gradient is dense over the "
                "whole vocab, which the sparse (indices, values) "
                "exchange cannot carry — drop the regularizer or the "
                "sparse wire")
        return path, emb.n_index

    def _sparse_embedding_enabled(self) -> bool:
        """Resolve the ``sparse_embedding`` knob into a build decision.
        ``True``/``False`` are explicit; ``"auto"`` picks the per-layer
        wire exactly when ``_sparse_embedding_path`` would accept the
        model under replicated mode, and falls back to the dense path
        otherwise — the typed refusals stay reserved for the explicit
        opt-in, where a silent fallback would hide a misconfiguration
        the caller paid to rule out."""
        se = self.sparse_embedding
        if se == "auto":
            if self.parameter_mode != "replicated":
                return False
            try:
                self._sparse_embedding_path()
            except ValueError:
                return False
            return True
        return bool(se)

    def _build_sparse_step(self):
        """The per-layer gradient-wire path (sparse_embedding=True):
        an EXPLICIT shard_map data-parallel step — unlike the default
        replicated path (where XLA's sharding propagation inserts one
        implicit psum over all grads), each gradient leaf here picks
        its own wire at trace time. The embedding leaf ships
        ``(indices, value rows)`` via the Parallax exchange when that
        is fewer elements than its dense gradient; everything else
        rides ``pmean``. Trace-time byte counters
        (``collective/sparse_grad_wire_traced_bytes`` vs
        ``collective/grad_dense_traced_bytes``) make the win
        auditable per dispatch."""
        from ..utils.compat import shard_map
        from ..nn.sparse import embedding_grad_rows
        from ..parallel.allreduce import sparse_embedding_grad_allreduce
        model, criterion = self.model, self.criterion
        reg_tree = regularizer_tree(model)
        clip_const, clip_norm = self.clip_const, self.clip_norm
        optim = self.optim_method
        frozen_mask = _frozen_mask(model)
        mesh = self.mesh
        path, vocab = self._sparse_embedding_path()
        superstep_k = self.superstep

        def loss_fn(params, mstate, x, y, rng):
            out, new_state = model.apply(params, mstate, x, training=True,
                                         rng=rng)
            loss = criterion._forward(out, y)
            if reg_tree:
                loss = loss + regularization_loss(reg_tree, params)
            return loss, new_state

        def exchange(grads, x):
            ids = jnp.clip(x.reshape(-1).astype(jnp.int32) - 1, 0,
                           vocab - 1)
            picked = {"sparse": 0}

            def walk(tree, p=()):
                if isinstance(tree, dict):
                    return {k: walk(v, p + (k,)) for k, v in tree.items()}
                g = tree
                if p == path:
                    sparse_elems = ids.shape[0] * (g.shape[-1] + 1)
                    dense_elems = int(np.prod(g.shape))
                    if sparse_elems < dense_elems:
                        picked["sparse"] += 1
                        rows = embedding_grad_rows(g, ids)
                        return sparse_embedding_grad_allreduce(
                            ids, rows, vocab_size=vocab, axis="data",
                            traced_steps=superstep_k)
                if obs.enabled():
                    # trace-time: bytes this leaf ships on the dense wire
                    obs.counter("collective/grad_dense_traced_bytes",
                                unit="B").inc(
                        float(g.size * g.dtype.itemsize) * superstep_k)
                return jax.lax.pmean(g, "data")

            out = walk(grads)
            if obs.enabled():
                obs.gauge("collective/sparse_layers_selected").set(
                    picked["sparse"])
            return out

        def local_step(params, opt_state, mstate, x, y, lr, rng):
            rng = jax.random.fold_in(rng, jax.lax.axis_index("data"))
            (loss, new_mstate), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, mstate, x, y, rng)
            grads = exchange(grads, x)
            grads = _clip_grads(grads, clip_const, clip_norm)
            if frozen_mask is not None:
                grads = _tmap(lambda g, m: g * m, grads, frozen_mask)
            new_params, new_opt = optim.update(grads, params, opt_state,
                                               lr)
            if frozen_mask is not None:
                new_params = _tmap(
                    lambda n, o, m: jnp.where(m > 0, n, o),
                    new_params, params, frozen_mask)
            loss = jax.lax.pmean(loss, "data")
            new_mstate = _tmap(lambda t: jax.lax.pmean(t, "data"),
                               new_mstate)
            # same post-pmean NaN guard as the other distributed paths
            ok = jnp.isfinite(loss)
            pick = lambda new, old: _tmap(
                lambda a, b: jnp.where(ok, a, b), new, old)
            return (loss, pick(new_params, params),
                    pick(new_opt, opt_state), pick(new_mstate, mstate))

        if superstep_k > 1:
            sharded = shard_map(
                _scan_superstep(local_step), mesh=mesh,
                in_specs=(P(), P(), P(), P(None, "data"),
                          P(None, "data"), P(), P()),
                out_specs=(P(), P(), P(), P()), check_vma=False)
        else:
            sharded = shard_map(
                local_step, mesh=mesh,
                in_specs=(P(), P(), P(), P("data"), P("data"), P(), P()),
                out_specs=(P(), P(), P(), P()), check_vma=False)
        return self._instrument_step(
            jax.jit(sharded, donate_argnums=(0, 1, 2)))

    def _build_step(self):
        if self.parameter_mode != "zero1":
            if self._sparse_embedding_enabled():
                return self._build_sparse_step()
            return super()._build_step()

        from ..utils.compat import shard_map
        from jax.flatten_util import ravel_pytree
        model, criterion = self.model, self.criterion
        reg_tree = regularizer_tree(model)
        clip_const, clip_norm = self.clip_const, self.clip_norm
        arp, flat = self._arp, self._flat
        mesh = self.mesh
        fm = _frozen_mask(model)
        flat_mask = None
        if fm is not None:
            full = _tmap(lambda p, m: jnp.full(jnp.shape(p), m,
                                               jnp.float32),
                         model.params, fm)
            flat_mask = flat.flatten(full)

        def loss_fn(flat_w, mstate, x, y, rng):
            params = flat.unflatten(flat_w)
            out, new_state = model.apply(params, mstate, x, training=True,
                                         rng=rng)
            loss = criterion._forward(out, y)
            if reg_tree:
                loss = loss + regularization_loss(reg_tree, params)
            return loss, new_state

        superstep_k = self.superstep

        def local_step(flat_w, opt_slice, mstate, x, y, lr, rng):
            rng = jax.random.fold_in(rng, jax.lax.axis_index("data"))
            (loss, new_mstate), gflat = jax.value_and_grad(
                loss_fn, has_aux=True)(flat_w, mstate, x, y, rng)
            gflat = _clip_grads(gflat, clip_const, clip_norm)
            if flat_mask is not None:
                gflat = gflat * flat_mask
            new_flat, new_opt = arp.update(gflat, flat_w, opt_slice, lr,
                                           traced_steps=superstep_k)
            if flat_mask is not None:
                new_flat = jnp.where(flat_mask > 0, new_flat, flat_w)
            loss = jax.lax.pmean(loss, "data")
            new_mstate = _tmap(lambda t: jax.lax.pmean(t, "data"), new_mstate)
            # same in-step NaN guard as the local path (post-pmean, so every
            # shard takes the same branch — no divergence across the mesh)
            ok = jnp.isfinite(loss)
            pick = lambda new, old: _tmap(
                lambda a, b: jnp.where(ok, a, b), new, old)
            return (loss, pick(new_flat, flat_w), pick(new_opt, opt_slice),
                    pick(new_mstate, mstate))

        opt_specs = arp.state_specs()
        mstate_specs = _tmap(lambda _: P(), self.model.state)
        if superstep_k > 1:
            # the scan lives INSIDE the shard_map body: the ZeRO-1
            # psum_scatter/update/all_gather cycle stays in the compiled
            # loop (the cross-replica sharded update must ride the scan
            # for superstep fusion to pay off — one program, K collective
            # rounds, zero host round-trips in between). Batch stacks
            # carry the scan dim first, per-step batch dim sharded.
            sharded = shard_map(
                _scan_superstep(local_step), mesh=mesh,
                in_specs=(P(), opt_specs, mstate_specs, P(None, "data"),
                          P(None, "data"), P(), P()),
                out_specs=(P(), P(), opt_specs, mstate_specs),
                check_vma=False)
        else:
            sharded = shard_map(
                local_step, mesh=mesh,
                in_specs=(P(), opt_specs, mstate_specs, P("data"), P("data"),
                          P(), P()),
                out_specs=(P(), P(), opt_specs, mstate_specs),
                check_vma=False)
        return self._instrument_step(
            jax.jit(sharded, donate_argnums=(0, 1, 2)))


class ParallelOptimizer(DistriOptimizer):
    """Name parity: optim/ParallelOptimizer.scala — the reference's
    layer-wise-parallel gradient aggregation variant. Under XLA the jitted
    step already aggregates all gradients in one fused program, so this is
    the same engine as DistriOptimizer."""


class Optimizer(BaseOptimizer):
    """Factory with the reference's signature (optim/Optimizer.scala apply):
    picks Local vs Distri from the engine mesh size."""

    def __new__(cls, model=None, training_set=None, training_rdd=None,
                criterion=None, optim_method=None, end_trigger=None,
                batch_size: int = 32, mesh=None, **kw):
        training = training_set if training_set is not None else training_rdd
        from ..parallel.mesh import get_default_mesh
        m = mesh or (get_default_mesh() if len(jax.devices()) > 1 else None)
        if m is not None and m.size > 1:
            return DistriOptimizer(model, training, criterion, optim_method,
                                   end_trigger, batch_size, mesh=m, **kw)
        obj = object.__new__(LocalOptimizer)
        obj.__init__(model, training, criterion, optim_method, end_trigger,
                     batch_size)
        return obj

    @staticmethod
    def create(model, training_set, criterion, end_trigger=None,
               batch_size=32, optim_method=None, cores=None,
               bigdl_type="float"):
        """pyspark ``Optimizer.create`` spelling (the ``cores``/
        ``bigdl_type`` args are JVM-era and ignored; local-vs-distributed
        is picked from the engine mesh like the constructor)."""
        return Optimizer(model=model, training_set=training_set,
                         criterion=criterion, optim_method=optim_method,
                         end_trigger=end_trigger, batch_size=batch_size)
