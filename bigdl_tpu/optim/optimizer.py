"""Training drivers.

Parity: reference ``optim/Optimizer.scala``, ``optim/LocalOptimizer.scala``,
``optim/DistriOptimizer.scala``, ``optim/AbstractOptimizer.scala``,
``optim/Metrics.scala``, plus DistriOptimizer's checkpoint/summary/validation
plumbing (DistriOptimizer.scala:90-640).

Execution model (TPU-first):

* The whole training step — forward, loss (+ per-layer regularizers),
  backward, gradient clipping, optimizer update — is ONE jitted function.
  The reference re-enters the JVM interpreter per layer per step; here XLA
  compiles the step once and fuses across layer boundaries.
* ``LocalOptimizer``: single device.
* ``DistriOptimizer``: the global batch is laid out over the mesh ``data``
  axis. Two parameter modes:
  - ``replicated`` (default): params replicated, XLA GSPMD inserts the
    gradient all-reduce over ICI automatically — the hardware analog of the
    reference's block-manager all-reduce;
  - ``zero1``: params flattened to one contiguous vector and updated
    slice-per-device via psum_scatter/all_gather (see
    ``parallel/allreduce.py``) — the literal TPU translation of
    AllReduceParameter's owner-slice design, with sharded optimizer state.
* LR schedules, triggers, checkpointing, validation, summaries run host-side
  between steps (control, not compute).
"""
from __future__ import annotations

import os
import pickle
import queue
import threading
import time
from collections import deque
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import observability as obs
from ..observability import flight as _flight
from ..observability import health as _health
from .optim_method import OptimMethod, SGD
from .regularizer import regularizer_tree, regularization_loss
from .trigger import Trigger, max_epoch as _max_epoch
from ..dataset.dataset import AbstractDataSet, ShardedDataSet, DataSet
from ..dataset.minibatch import MiniBatch
from ..nn.module import Module, Criterion
from .staging import staged
from ..utils import engine
from ..utils.table import Table

_tmap = jax.tree_util.tree_map


def _atomic_pickle(path, payload):
    """tmp + fsync + rename: a crash mid-write (including OS crash/power
    loss — hence the fsync before the rename) must never tear the
    checkpoint the nan_policy='resume' path depends on."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        pickle.dump(payload, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


class _AsyncCheckpointWriter:
    """One daemon writer thread; submissions are written IN ORDER (so the
    'latest checkpoint' on disk is always the latest submitted), each via
    the atomic tmp+rename. ``flush`` drains the queue and re-raises the
    first writer error (a silently failing checkpointer is worse than a
    crashed one). The reference writes checkpoints synchronously on the
    Spark driver (Optimizer.setCheckpoint → File.save); on TPU the step
    loop should not stall on host file IO."""

    def __init__(self, max_pending: int = 2):
        # bounded: a slow disk backpressures the training loop instead of
        # accumulating one full host model copy per checkpoint interval
        self._q: queue.Queue = queue.Queue(maxsize=max_pending)
        self._err = None
        self._thread = None

    def _run(self):
        while True:
            item = self._q.get()
            try:
                if item is None:
                    return
                path, payload = item
                try:
                    _atomic_pickle(path, payload)
                except Exception as e:  # noqa: BLE001 — surfaced in flush
                    if self._err is None:
                        self._err = e
            finally:
                self._q.task_done()

    def submit(self, path, payload):
        if self._thread is None:
            self._thread = threading.Thread(target=self._run, daemon=True)
            self._thread.start()
        self._q.put((path, payload))
        if obs.enabled():
            obs.gauge("checkpoint/queue_depth").set(self._q.qsize())

    def flush(self):
        if self._thread is not None:
            self._q.join()
        if self._err is not None:
            err, self._err = self._err, None
            raise RuntimeError(
                f"async checkpoint write failed: {err}") from err

    def close(self):
        """Flush, then stop the writer thread (optimize() calls this so
        no daemon thread outlives the run)."""
        self.flush()
        if self._thread is not None:
            self._q.put(None)
            self._thread.join(timeout=30)
            self._thread = None


class Metrics:
    """Per-phase timing metrics (parity: optim/Metrics.scala).

    Retained as the optimizer-local view (``.values`` is part of the
    public surface); when observability is enabled every ``add`` also
    mirrors into the process-global registry as an
    ``optim/<name>`` histogram, so the Prometheus/Chrome exporters and
    the TensorBoard bridge see the same numbers without a second
    collection path."""

    def __init__(self, namespace: str = "optim"):
        self.values = {}
        self._namespace = namespace

    def add(self, name, value):
        self.values.setdefault(name, []).append(value)
        if obs.enabled():
            obs.histogram(f"{self._namespace}/{name}").observe(value)

    def mean(self, name):
        if name not in self.values:
            raise KeyError(
                f"no metric named {name!r} has been recorded "
                f"(seen: {sorted(self.values)})")
        v = self.values[name]
        return sum(v) / len(v)

    def summary(self):
        return {k: self.mean(k) for k in self.values}


def _frozen_mask(model):
    """Mask pytree matching ``model.params``: 0.0 under frozen modules
    (Module.freeze), 1.0 elsewhere; None when nothing is frozen.

    Per-module flags, no ancestor propagation: ``freeze()`` marks whole
    subtrees, so ``unfreeze("head")`` under a frozen root works."""
    from ..nn.module import Container
    from ..nn.recurrent import Recurrent
    model.ensure_initialized()
    if not any(getattr(m, "_frozen", False) for m in model.modules_iter()):
        return None

    def rec(m, p):
        if isinstance(m, Recurrent) and isinstance(p, dict) and "cell" in p:
            return {"cell": rec(m.cell, p["cell"])}
        if isinstance(m, Container) and isinstance(p, dict):
            out = {}
            for k, v in p.items():
                if k.isdigit() and int(k) < len(m.modules):
                    out[k] = rec(m.modules[int(k)], v)
                else:
                    out[k] = _leaf_mask(m, v)
            return out
        return _leaf_mask(m, p)

    def _leaf_mask(m, p):
        val = 0.0 if getattr(m, "_frozen", False) else 1.0
        return _tmap(lambda a: val, p)

    return rec(model, model.params)


def _scan_superstep(step):
    """Lift a single-step function ``step(params, opt_state, mstate, x, y,
    lr, rng) -> (loss, params', opt_state', mstate')`` into a superstep:
    ``lax.scan`` over K stacked microbatches threading the training state
    through K updates inside ONE XLA program. Losses come back as a
    single ``[K]`` device array — one dispatch and one batched readback
    amortize the per-step host costs K-fold. The per-microstep math (incl.
    the in-step NaN guard: a non-finite microstep keeps the previous
    state, later microsteps proceed from it — exactly the K=1 'skip'
    dataflow) is the same program the per-step loop compiles; trajectories
    match K=1 bitwise for fusion-insensitive bodies (elementwise/matmul
    MLPs — asserted in tests/test_superstep.py). XLA may re-fuse across
    microstep boundaries, which can reorder a handful of GEMM/conv
    accumulations — measured <= 4e-9 absolute drift on LeNet/CPU over 8
    steps, i.e. last-mantissa-bit float noise, never a semantic change."""

    def superstep(params, opt_state, mstate, xs, ys, lrs, rngs):
        def body(carry, inp):
            p, o, m = carry
            x, y, lr, rng = inp
            loss, p, o, m = step(p, o, m, x, y, lr, rng)
            return (p, o, m), loss

        (params, opt_state, mstate), losses = jax.lax.scan(
            body, (params, opt_state, mstate), (xs, ys, lrs, rngs))
        return losses, params, opt_state, mstate

    return superstep


def _clip_grads(grads, clip_const=None, clip_norm=None):
    if clip_const is not None:
        lo, hi = clip_const
        grads = _tmap(lambda g: jnp.clip(g, lo, hi), grads)
    if clip_norm is not None:
        leaves = jax.tree_util.tree_leaves(grads)
        total = jnp.sqrt(sum(jnp.sum(jnp.square(g)) for g in leaves))
        scale = jnp.minimum(1.0, clip_norm / (total + 1e-12))
        grads = _tmap(lambda g: g * scale, grads)
    return grads


class BaseOptimizer:
    def __init__(self, model: Module, training_set, criterion: Criterion,
                 optim_method: Optional[OptimMethod] = None,
                 end_trigger: Optional[Trigger] = None, batch_size: int = 32):
        self.model = model
        self.criterion = criterion
        self.optim_method = optim_method or SGD(learningrate=0.01)
        self.end_trigger = end_trigger or _max_epoch(1)
        self.batch_size = batch_size
        self.training_set = self._as_dataset(training_set)

        self.validation_trigger = None
        self.validation_set = None
        self.validation_methods = None
        self.checkpoint_trigger = None
        self.checkpoint_path = None
        self.checkpoint_overwrite = True
        self.checkpoint_async = False
        self._ckpt_writer = _AsyncCheckpointWriter()
        self.train_summary = None
        self.val_summary = None
        self.clip_const = None
        self.clip_norm = None
        self.nan_policy = "error"  # or "skip" / "resume"
        self.max_nan_retries = 10  # consecutive non-finite steps before abort
        self.sync_policy = "sync"  # or "async" / "window:K"
        self.prefetch_depth = 2    # >= 2 enables the lookahead stager
        self.superstep = 1         # K fused steps per dispatch (lax.scan)
        self._pending_loss = None
        self._loss_window = deque()
        self._resolved_step = None  # provenance of the last resolved loss
        self.metrics = Metrics()
        self._step_fn = None
        # health layer (active only while observability is enabled):
        # stall watchdog deadline/callback, anomaly-detector config
        # (None disables; a dict overrides SeriesMonitor defaults)
        self.stall_deadline_s = None   # None -> BIGDL_TPU_STALL_S default
        self.on_stall = None
        self.anomaly_config: Optional[dict] = {}
        self._step_beacon = _health.NULL_BEACON
        self._loss_monitor = None
        self._profiler = None

    # -- reference API surface ------------------------------------------
    def set_model(self, model):
        """Swap the model for optimizer reuse (pyspark Optimizer.set_model).
        Training PROGRESS resets with it: the epoch/iteration counters and
        any checkpoint-resume optimizer state belong to the old model —
        without the reset a second ``optimize()`` would stop at the old
        end-trigger after one step (or feed the old model's opt-state tree
        into the new step)."""
        self.model = model
        self.optim_method.state = {"neval": 0, "epoch": 1}
        self._resume_opt_state = None
        return self

    def set_criterion(self, criterion):
        """Swap the criterion for optimizer reuse (pyspark
        Optimizer.set_criterion). The step is rebuilt on the next
        ``optimize()``."""
        self.criterion = criterion
        return self

    def set_traindata(self, training_set, batch_size=None):
        """Swap the training data for optimizer reuse (pyspark
        Optimizer.set_traindata)."""
        self.training_set = self._as_dataset(training_set)
        if batch_size:
            self.batch_size = batch_size
        return self

    def set_summary_trigger(self, name, trigger):
        """Modify when a summary named tag is recorded (pyspark
        Optimizer.set_summary_trigger). Train tags: "Loss",
        "LearningRate", "Throughput". Validation: "Validation" gates all
        validation scalars; a per-method tag (its repr) gates one."""
        val_tags = {repr(m) for m in (self.validation_methods or ())}
        is_val_tag = name.startswith("Validation") or name in val_tags
        if is_val_tag:
            if self.val_summary is None:
                raise ValueError(
                    "set_summary_trigger(%r): validation tag but no "
                    "validation summary is set — call set_val_summary "
                    "first (the train loop only consults Loss/"
                    "LearningRate/Throughput)" % (name,))
            target = self.val_summary
        elif self.train_summary is not None:
            target = self.train_summary
        else:
            raise ValueError("set a train/val summary before "
                             "set_summary_trigger")
        target.set_summary_trigger(name, trigger)
        return self

    def prepare_input(self):
        """Materialise the dataset ahead of ``optimize`` (pyspark
        Optimizer.prepare_input — there, forces the cached RDD; here the
        dataset protocol is already local, so this just touches one
        batch to surface IO errors early). Open-epoch datasets (the
        native prefetchers spawn decode workers per data() call) are
        skipped — pulling one batch would leave a whole epoch's worker
        run open."""
        if getattr(self.training_set, "_epoch_open", None) is not None:
            return self
        it = iter(self.training_set.data(train=False))
        try:
            next(it, None)
        finally:
            # generator-backed datasets may hold resources (open files,
            # worker pools) in the abandoned iterator — release eagerly
            close = getattr(it, "close", None)
            if close is not None:
                close()
        return self

    def set_validation(self, trigger, dataset, methods, batch_size=None):
        self.validation_trigger = trigger
        self.validation_set = self._as_dataset(dataset)
        self.validation_methods = list(methods)
        self.validation_batch = batch_size or self.batch_size
        return self

    def set_checkpoint(self, trigger, path, overwrite=True,
                       async_write=False):
        """``async_write=True`` moves serialization + file IO onto a
        background writer thread (ordered, atomic) so the training loop
        only pays the device→host fetch; ``wait_for_checkpoints()`` (also
        called at the end of ``optimize``) flushes and surfaces errors."""
        self.checkpoint_trigger = trigger
        self.checkpoint_path = path
        self.checkpoint_overwrite = overwrite
        self.checkpoint_async = async_write
        os.makedirs(path, exist_ok=True)
        return self

    def set_train_summary(self, summary):
        self.train_summary = summary
        return self

    def set_val_summary(self, summary):
        self.val_summary = summary
        return self

    def set_end_when(self, trigger):
        self.end_trigger = trigger
        return self

    def set_gradclip_const(self, clip_min: float, clip_max: float):
        self.clip_const = (clip_min, clip_max)
        return self

    def set_gradclip_l2norm(self, clip_norm: float):
        self.clip_norm = clip_norm
        return self

    def disable_gradclip(self):
        self.clip_const = self.clip_norm = None
        return self

    def set_sync_policy(self, policy: str):
        """'sync' (default) reads each step's loss immediately — the host
        blocks on the device every iteration. 'async' reads the PREVIOUS
        step's loss instead, so the next batch is prepared and enqueued
        while the device still computes (loss logging, NaN detection and
        min-loss triggers lag one step; the in-step NaN guard keeps params
        safe on-device either way). Use 'async' for device-bound training.

        'window:K' generalizes async: up to K losses stay in flight as
        device arrays and the host resolves the OLDEST only once the
        window is full, so loss observation (logging, NaN detection,
        min-loss triggers) lags K-1 steps and the device pipeline is
        never drained by a blocking read. 'window:1' == 'sync'. The NaN
        policy semantics are preserved — a non-finite resolved loss
        raises/skips/replays-from-checkpoint exactly like sync, just K-1
        steps later (params stay safe meanwhile via the in-step guard).
        """
        if isinstance(policy, str) and policy.startswith("window:"):
            k = int(policy.split(":", 1)[1])
            if k < 1:
                raise ValueError(f"window size must be >= 1, got {k}")
        else:
            assert policy in ("sync", "async")
        self.sync_policy = policy
        return self

    def set_prefetch(self, depth: int):
        """Lookahead depth of the batch stager: with ``depth >= 2`` a
        host thread produces and device_puts batches N+1..N+depth while
        step N runs, collapsing ``step/data_fetch`` to a queue pop.
        ``0``/``1`` keep the serial fetch (exact A/B switch — the staged
        loop is order-preserving, so trajectories are identical)."""
        depth = int(depth)
        if depth < 0:
            raise ValueError(f"prefetch depth must be >= 0, got {depth}")
        self.prefetch_depth = depth
        return self

    def set_superstep(self, k: int):
        """Fuse K training steps into ONE compiled XLA program: the step
        becomes a ``lax.scan`` over K stacked microbatches that threads
        (params, opt_state, model state) through K updates on device, so
        the host pays one dispatch, one batched ``[K]`` loss readback and
        one round of bookkeeping per K steps instead of per step — the
        win when host dispatch dominates (small/medium models, remote-
        device tunnels). Semantics stay identical to K=1: LR schedules
        are precomputed as a ``[K]`` vector, the per-step RNG stream is
        unchanged, and dispatches auto-clamp so a superstep never
        straddles an epoch end or a checkpoint/validation/end-trigger
        boundary. When K > 1 the batched readback REPLACES the per-loss
        resolution of ``sync``/``async``/``window:K`` (loss observation,
        NaN detection and loss-driven triggers resolve once per
        superstep — the same K-step observation lag ``window:K`` has).
        ``1`` restores the per-step loop exactly.

        Equivalence: the scan body IS the per-step program, so the
        trajectory matches K=1 bitwise for fusion-insensitive models
        (MLPs); where XLA re-fuses across microstep boundaries (conv/
        GEMM epilogues) a handful of accumulations reorder — measured
        <= 4e-9 absolute drift on LeNet/CPU, float ulp noise."""
        k = int(k)
        if k < 1:
            raise ValueError(f"superstep must be >= 1, got {k}")
        self.superstep = k
        return self

    def _window_k(self) -> Optional[int]:
        if isinstance(self.sync_policy, str) and \
                self.sync_policy.startswith("window:"):
            return int(self.sync_policy.split(":", 1)[1])
        return None

    def set_stall_deadline(self, seconds: float, on_stall=None):
        """Arm the stall watchdog for this optimizer's loops: the step
        loop and its batch stager pulse progress beacons, and a beacon
        quiet for ``seconds`` fires a structured ``health/stall`` event
        (plus ``on_stall(beacon, age_s)`` when given) instead of the run
        silently hanging — the remote-TPU 'no output' failure mode.
        Active only while observability is enabled; the default deadline
        without this call is ``BIGDL_TPU_STALL_S`` (600s)."""
        seconds = float(seconds)
        if seconds <= 0:
            raise ValueError(f"stall deadline must be > 0, got {seconds}")
        self.stall_deadline_s = seconds
        self.on_stall = on_stall
        return self

    def set_anomaly_detection(self, enabled: bool = True, **config):
        """Configure the rolling loss anomaly detector (spikes,
        plateaus, NaN streaks — ``observability.health.SeriesMonitor``;
        kwargs override its defaults, e.g. ``spike_sigma=6``,
        ``plateau_window=500``). It consumes the loss floats the sync
        policy already resolves — zero extra device readbacks.
        ``enabled=False`` turns it off entirely."""
        self.anomaly_config = dict(config) if enabled else None
        return self

    def set_nan_policy(self, policy: str):
        """'error' raises, 'skip' drops the step, 'resume' rolls back to the
        latest checkpoint (requires set_checkpoint) — the step-level analog of
        Spark's failed-task retry (SURVEY §5 failure detection)."""
        assert policy in ("error", "skip", "resume")
        self.nan_policy = policy
        return self

    def _latest_checkpoint(self):
        if not self.checkpoint_path or not os.path.isdir(self.checkpoint_path):
            return None
        snaps = [os.path.join(self.checkpoint_path, f)
                 for f in os.listdir(self.checkpoint_path)
                 if f.startswith("checkpoint") and f.endswith(".bigdl")]
        return max(snaps, key=os.path.getmtime) if snaps else None

    # -- internals -------------------------------------------------------
    def _as_dataset(self, ds):
        if ds is None or isinstance(ds, AbstractDataSet):
            return ds
        if isinstance(ds, tuple) and len(ds) == 2:
            return DataSet.from_arrays(ds[0], ds[1])
        if isinstance(ds, (list,)):
            return DataSet.array(ds)
        if hasattr(ds, "data") and hasattr(ds, "size"):
            return ds  # batch-level dataset (e.g. native.NativePrefetcher)
        raise TypeError(f"unsupported dataset {type(ds)}")

    def _num_shards(self):
        return 1

    def _batched(self):
        if hasattr(self.training_set, "batches_per_epoch"):
            return self.training_set  # already yields MiniBatches
        return ShardedDataSet(self.training_set, self.batch_size,
                              num_shards=self._num_shards())

    def _build_step(self):
        model, criterion = self.model, self.criterion
        reg_tree = regularizer_tree(model)
        clip_const, clip_norm = self.clip_const, self.clip_norm
        optim = self.optim_method
        frozen_mask = _frozen_mask(model)

        def loss_fn(params, mstate, x, y, rng):
            out, new_state = model.apply(params, mstate, x, training=True,
                                         rng=rng)
            loss = criterion._forward(out, y)
            if reg_tree:
                loss = loss + regularization_loss(reg_tree, params)
            return loss, new_state

        def step(params, opt_state, mstate, x, y, lr, rng):
            (loss, new_mstate), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, mstate, x, y, rng)
            # trace-time span: this body runs under jit, so the span
            # appears once per compile (under the first step/dispatch)
            # and measures clip *trace* cost — the per-step clip itself
            # is fused into the compiled program
            with obs.span("step/grad_clip", traced=True):
                grads = _clip_grads(grads, clip_const, clip_norm)
            if frozen_mask is not None:
                grads = _tmap(lambda g, m: g * m, grads, frozen_mask)
            new_params, new_opt = optim.update(grads, params, opt_state, lr)
            if frozen_mask is not None:
                # weight decay must not move frozen params either — restore
                new_params = _tmap(
                    lambda n, o, m: jnp.where(m > 0, n, o),
                    new_params, params, frozen_mask)
            # NaN/Inf guard inside the compiled step (buffers are donated, so
            # the host can't roll back): a non-finite loss keeps the previous
            # params/opt-state and only the loss reports the failure.
            ok = jnp.isfinite(loss)
            pick = lambda new, old: _tmap(
                lambda a, b: jnp.where(ok, a, b), new, old)
            return (loss, pick(new_params, params), pick(new_opt, opt_state),
                    pick(new_mstate, mstate))

        if self.superstep > 1:
            return jax.jit(_scan_superstep(step), donate_argnums=(0, 1, 2))
        return jax.jit(step, donate_argnums=(0, 1, 2))

    def _place_batch(self, x, y):
        from .staging import place_host_value
        return place_host_value(x), place_host_value(y)

    def _stage_minibatch(self, mb):
        """Produce-side staging: host MiniBatch -> device-resident (x, y).
        Runs on the stager thread when prefetch is enabled (the native
        bf16_nhwc prefetcher's batches pass through as a cast-free
        device_put), inline otherwise."""
        return self._place_batch(mb.get_input(), mb.get_target())

    def _stage_minibatch_host(self, mb):
        """Superstep produce-side stage 1: extract the host (x, y) only —
        placement happens once per GROUP in ``_stage_group`` so the whole
        ``[K, batch, ...]`` stack ships in one (sharded) device_put."""
        return mb.get_input(), mb.get_target()

    def _stage_group(self, items):
        """Superstep stacking stage (runs on the stager thread): K host
        microbatches -> one ``(k, xs, ys)`` element with device-resident
        ``[k, batch, ...]`` stacks, so the hot loop dequeues one element
        per dispatch. ``np.asarray`` first: the native prefetchers may
        hand device-resident batches (direct-to-device staging); the
        stack itself must run on host memory."""
        def stack(vals):
            return _tmap(lambda *ls: np.stack([np.asarray(l) for l in ls]),
                         *vals)
        xs = stack([x for x, _ in items])
        ys = stack([y for _, y in items])
        xs, ys = self._place_group(xs, ys)
        return len(items), xs, ys

    def _place_group(self, xs, ys):
        """Host ``[k, batch, ...]`` stacks -> device (overridden by
        DistriOptimizer to shard the per-step batch dim over the mesh)."""
        from .staging import place_host_value
        return place_host_value(xs), place_host_value(ys)

    @staticmethod
    def _stage_group_key(staged):
        """Stacking compatibility key: the per-step batch size. A ragged
        final batch (batch-level datasets without drop-remainder) must
        start its own smaller group, not np.stack against full ones."""
        x, _ = staged
        leaves = jax.tree_util.tree_leaves(x)
        return leaves[0].shape[0] if leaves else 0

    def _observe_loss(self, loss, step=None):
        """Apply the sync policy to this step's device loss. Returns the
        resolved host float to examine this iteration, or None when the
        windowed policy has not filled its in-flight budget yet. Every
        resolution is one host<->device sync, counted in
        ``optim/loss_syncs`` (supersteps cut this K-fold).

        ``step`` is the iteration this DISPATCH belongs to; under
        ``async``/``window:K`` the returned float describes an OLDER
        dispatch, and ``self._resolved_step`` names it — the health
        layer (flight ring, anomaly detector) must attribute a lagged
        loss to the step that produced it, not the step that read it."""
        k = self._window_k()
        self._resolved_step = step
        if k is not None:
            self._loss_window.append((step, loss))
            if obs.enabled():
                obs.gauge("optim/loss_window_inflight").set(
                    len(self._loss_window))
            if len(self._loss_window) < k:
                return None
            if obs.enabled():
                obs.counter("optim/loss_syncs").inc()
            self._resolved_step, oldest = self._loss_window.popleft()
            # sync-ok: windowed resolve of the OLDEST in-flight loss
            return float(oldest)
        if obs.enabled():
            obs.counter("optim/loss_syncs").inc()
        if self.sync_policy == "async":
            # examine the PREVIOUS step's loss: the device keeps
            # computing while the host preps the next batch
            prev, self._pending_loss = self._pending_loss, (step, loss)
            if prev is not None:
                self._resolved_step, loss = prev
            # sync-ok: lagged read (first step resolves its own loss)
            return float(loss)
        # sync-ok: sync policy blocks on every step by definition
        return float(loss)

    def _drain_pending_losses(self, state):
        """Resolve losses still in flight when the loop ends (async's one
        pending read, window:K's up-to-K-1 tail) — a NaN pending on the
        final steps must not be swallowed."""
        pending = list(self._loss_window)
        self._loss_window.clear()
        if self._pending_loss is not None:
            pending.append(self._pending_loss)
            self._pending_loss = None
        for _step, dev in pending:
            final = float(dev)  # sync-ok: end-of-run drain
            if np.isfinite(final):
                state["loss"] = final
            elif self.nan_policy == "error":
                raise FloatingPointError(
                    f"non-finite loss {final} on a final step "
                    f"({self.sync_policy} lagged read)")
            else:
                self.metrics.add("nan_skips", 1.0)

    def _checkpoint(self, params, opt_state, mstate, state):
        tag = "" if self.checkpoint_overwrite else \
            f"_e{state['epoch']}_i{state['neval']}"
        path = os.path.join(self.checkpoint_path, f"checkpoint{tag}.bigdl")
        # the device→host fetch is the only synchronous part; serialization
        # and file IO can ride the writer thread (async_write)
        payload = {
            "params": _tmap(np.asarray, self._params_for_checkpoint(params)),
            "opt_state": self._to_host(opt_state),
            "model_state": self._to_host(mstate),
            "optim_host_state": dict(self.optim_method.state),
            "epoch": state["epoch"], "neval": state["neval"],
        }
        with obs.span("step/checkpoint_submit",
                      async_write=self.checkpoint_async):
            if self.checkpoint_async:
                self._ckpt_writer.submit(path, payload)
            else:
                _atomic_pickle(path, payload)
        if obs.enabled():
            _flight.record("checkpoint", path=path, neval=state["neval"],
                           epoch=state["epoch"],
                           async_write=self.checkpoint_async)

    def wait_for_checkpoints(self):
        """Block until every async checkpoint write has landed (re-raising
        a writer failure). No-op for synchronous checkpoints."""
        self._ckpt_writer.flush()

    def _close_checkpoints(self):
        self._ckpt_writer.close()

    def load_checkpoint(self, path):
        """Resume training state from a snapshot (parity:
        Optimizer.setCheckpoint + File.load resume flow)."""
        with open(path, "rb") as f:
            payload = pickle.load(f)
        self.model.ensure_initialized()
        self.model.params = _tmap(jnp.asarray, payload["params"])
        self.model.state = _tmap(jnp.asarray, payload["model_state"])
        self.optim_method.state.update(payload["optim_host_state"])
        self._resume_opt_state = _tmap(jnp.asarray, payload["opt_state"])
        return self

    def _validate(self, state):
        if self.validation_set is None:
            return None
        was_training = self.model.train_mode
        self.model.evaluate()
        from .evaluator import Evaluator
        with obs.span("step/validate", neval=state["neval"]):
            results = Evaluator(self.model).evaluate(
                self.validation_set, self.validation_methods,
                self.validation_batch)
        if was_training:
            self.model.training()
        scores = {}
        for method, res in zip(self.validation_methods, results):
            val, _ = res.result()
            scores[repr(method)] = val
            if self.val_summary is not None:
                # triggers gate recording: "Validation" gates every
                # validation scalar, the per-method tag gates one
                rec = self.val_summary.should_record
                if rec("Validation", state) and rec(repr(method), state):
                    self.val_summary.add_scalar(repr(method), val,
                                                state["neval"])
        if scores:
            state["score"] = list(scores.values())[0]
        return scores

    # -- main loop -------------------------------------------------------
    def optimize(self) -> Module:
        """Run training to the end trigger. With observability enabled
        the run is health-instrumented: the step loop and stager pulse
        stall beacons, the resolved losses feed the anomaly detector
        and the flight recorder, device-memory gauges register when the
        backend supports them, and an unhandled failure (including the
        NaN-policy aborts) dumps a flight-recorder crash bundle before
        re-raising — ``tools/flight_report.py`` renders it."""
        self._step_beacon = _health.beacon(
            "optim/step", deadline_s=self.stall_deadline_s,
            on_stall=self.on_stall)
        self._profiler = _health.profiler_window_from_env()
        self._loss_monitor = None
        if obs.enabled():
            _health.ensure_memory_telemetry()
            if self.anomaly_config is not None:
                self._loss_monitor = _health.SeriesMonitor(
                    "loss", **self.anomaly_config)
            st = self.optim_method.state
            _flight.record("train/start", epoch=st.get("epoch"),
                           neval=st.get("neval"), seed=engine.get_seed(),
                           batch_size=self.batch_size,
                           superstep=self.superstep,
                           sync_policy=self.sync_policy)
        try:
            return self._optimize_impl()
        except BaseException as e:
            if obs.enabled():
                st = self.optim_method.state
                _flight.dump_crash_bundle(error=e, context={
                    "component": "optimizer",
                    "epoch": st.get("epoch"), "neval": st.get("neval"),
                    "seed": engine.get_seed(),
                    "batch_size": self.batch_size,
                    "superstep": self.superstep,
                    "sync_policy": self.sync_policy,
                    "nan_policy": self.nan_policy})
            raise
        finally:
            self._step_beacon.close()
            self._step_beacon = _health.NULL_BEACON
            if self._profiler is not None:
                self._profiler.close()
                self._profiler = None

    def _optimize_impl(self) -> Module:
        self.model.ensure_initialized()
        self.model.training()
        params, mstate = self.model.params, self.model.state
        opt_state = getattr(self, "_resume_opt_state", None)
        if opt_state is None:
            opt_state = self.optim_method.init_state(params)
        params, opt_state, mstate = self._prepare(params, opt_state, mstate)
        engine.maybe_enable_compilation_cache()
        with obs.span("optimizer/build_step"):
            self._step_fn = self._build_step()
        if obs.enabled():
            obs.gauge("engine/compile_cache_entries").set(
                engine.compilation_cache_entries())
        # never consume a dead run's in-flight losses
        self._pending_loss = None
        self._loss_window.clear()

        optim = self.optim_method
        state = optim.state  # {'neval', 'epoch', ...}
        batched = self._batched()
        done = False
        nan_streak = 0
        while not done:
            batched.shuffle()
            epoch_start = time.time()
            # the stager owns produce + device placement; with
            # prefetch_depth >= 2 both run on a lookahead thread while
            # the device computes, otherwise inline (the serial loop).
            # With superstep K > 1 it also owns the stacking stage:
            # groups of K microbatches assemble into [K, batch, ...]
            # device stacks and the hot loop dequeues one per dispatch.
            if self.superstep > 1:
                batches = staged(batched.data(train=True),
                                 self._stage_minibatch_host,
                                 depth=self.prefetch_depth, name="stager",
                                 group=self.superstep,
                                 group_fn=self._stage_group,
                                 group_key=self._stage_group_key,
                                 stall_deadline_s=self.stall_deadline_s)
            else:
                batches = staged(batched.data(train=True),
                                 self._stage_minibatch,
                                 depth=self.prefetch_depth, name="stager",
                                 stall_deadline_s=self.stall_deadline_s)
            box = {"params": params, "opt_state": opt_state,
                   "mstate": mstate, "nan_streak": nan_streak, "done": done}
            try:
                if self.superstep > 1:
                    self._run_epoch_supersteps(batches, state, box)
                else:
                    self._run_epoch_steps(batches, state, box)
            finally:
                batches.close()  # join the stager thread — no leaks, ever
            params, opt_state, mstate = \
                box["params"], box["opt_state"], box["mstate"]
            nan_streak, done = box["nan_streak"], box["done"]
            if not done:
                state["epoch"] += 1
                state["epoch_finished"] = True
                self.metrics.add("epoch_time", time.time() - epoch_start)
                if obs.enabled():
                    _flight.record("epoch", epoch=state["epoch"] - 1,
                                   neval=state["neval"],
                                   epoch_time_s=time.time() - epoch_start)
                self._fire_epoch(state, params, opt_state, mstate)
                if self.end_trigger(state):
                    done = True

        # drain the async/window in-flight losses (a NaN pending on the
        # final steps must not be swallowed)
        self._drain_pending_losses(state)
        self.model.params, self.model.state = \
            self._collect(params, mstate, opt_state)
        self.model.grad_params = _tmap(jnp.zeros_like, self.model.params)
        self._close_checkpoints()  # land async writes, stop the writer
        return self.model

    def _run_epoch_steps(self, batches, state, box):
        """One epoch of the pipelined step loop. ``batches`` yields
        device-resident (x, y) (already staged by the caller's stager);
        mutable step state travels in ``box``
        (params/opt_state/mstate/nan_streak/done) so every exit path —
        exhaustion, end trigger, an exception mid-step — leaves the
        caller with the latest device handles."""
        optim = self.optim_method
        params, opt_state, mstate = \
            box["params"], box["opt_state"], box["mstate"]
        nan_streak = box["nan_streak"]
        try:
            while True:
                self._step_beacon.pulse()
                with obs.span("step", neval=state["neval"]):
                    t0 = time.time()
                    with obs.span("step/data_fetch"):
                        try:
                            x, y = next(batches)
                        except StopIteration:
                            return
                    t1 = time.time()
                    lr = optim.current_lr()
                    rng = engine.next_rng_key()
                    with obs.span("step/dispatch"):
                        loss, params, opt_state, mstate = self._step_fn(
                            params, opt_state, mstate, x, y,
                            jnp.asarray(lr, jnp.float32), rng)
                    if obs.enabled():
                        obs.counter("engine/dispatches").inc()
                    with obs.span("step/loss_sync"):
                        # step provenance: the dispatch just issued is
                        # iteration neval+1; async/window:K resolve an
                        # OLDER one — _resolved_step names it
                        loss_val = self._observe_loss(
                            loss, state["neval"] + 1)
                    t2 = time.time()
                    if loss_val is not None and not np.isfinite(loss_val):
                        nan_streak += 1
                        if obs.enabled():
                            _flight.record("nan",
                                           neval=self._resolved_step,
                                           epoch=state["epoch"],
                                           loss=loss_val,
                                           policy=self.nan_policy)
                            if self._loss_monitor is not None:
                                self._loss_monitor.observe(
                                    loss_val, self._resolved_step)
                        if self.nan_policy == "error":
                            raise FloatingPointError(
                                f"non-finite loss {loss_val} at iteration "
                                f"{state['neval']} — enable "
                                f"set_nan_policy('skip') to drop such steps")
                        if nan_streak > self.max_nan_retries:
                            raise FloatingPointError(
                                f"{nan_streak} consecutive non-finite steps "
                                f"(nan_policy='{self.nan_policy}') — data or "
                                "hyperparameters are unrecoverably bad")
                        if self.nan_policy == "resume":
                            self.wait_for_checkpoints()  # in-flight writes
                            snap = self._latest_checkpoint()
                            if snap is None:
                                raise FloatingPointError(
                                    "non-finite loss with nan_policy='resume' "
                                    "but no checkpoint saved yet — call "
                                    "set_checkpoint(...) first")
                            with open(snap, "rb") as f:
                                payload = pickle.load(f)
                            self.optim_method.state.update(
                                payload["optim_host_state"])
                            params, opt_state, mstate = \
                                self._restore_step_state(payload)
                            # in-flight losses refer to pre-restore steps
                            self._pending_loss = None
                            self._loss_window.clear()
                            self.metrics.add("nan_resumes", 1.0)
                            obs.instant("step/nan_resume", neval=state["neval"])
                            continue
                        # 'skip': the in-step guard already kept the previous
                        # params; count the iteration so end triggers advance
                        self.metrics.add("nan_skips", 1.0)
                        obs.instant("step/nan_skip", neval=state["neval"])
                        state["neval"] += 1
                        continue
                    if loss_val is not None:
                        # windowed policies have no resolved loss until K
                        # are in flight — the streak/loss state only moves
                        # on an actually-observed value
                        nan_streak = 0
                        state["loss"] = loss_val
                    state["neval"] += 1
                    state["epoch_finished"] = False
                    if loss_val is not None and obs.enabled():
                        # provenance rides the already-resolved host
                        # float — no extra readback; under async/
                        # window:K the loss belongs to _resolved_step,
                        # up to K-1 before the current iteration
                        _flight.record("step", neval=self._resolved_step,
                                       epoch=state["epoch"], loss=loss_val)
                        if self._loss_monitor is not None:
                            self._loss_monitor.observe(
                                loss_val, self._resolved_step)
                    if self._profiler is not None:
                        self._profiler.maybe_tick(state["neval"])
                    self.metrics.add("data_time", t1 - t0)
                    self.metrics.add("step_time", t2 - t1)
                    if obs.enabled():
                        obs.counter("optim/steps").inc()
                        obs.gauge("optim/throughput", unit="samples/s").set(
                            self.batch_size / max(t2 - t0, 1e-9))
                    if self.train_summary is not None:
                        rec = self.train_summary.should_record
                        if loss_val is not None and rec("Loss", state):
                            self.train_summary.add_scalar("Loss", loss_val,
                                                          state["neval"])
                        if rec("LearningRate", state):
                            self.train_summary.add_scalar("LearningRate", lr,
                                                          state["neval"])
                        if rec("Throughput", state):
                            self.train_summary.add_scalar(
                                "Throughput",
                                self.batch_size / max(t2 - t0, 1e-9),
                                state["neval"])
                    if self._fire_mid_epoch(state, params, opt_state, mstate):
                        pass
                    if self.end_trigger(state):
                        box["done"] = True
                        return
        finally:
            box.update(params=params, opt_state=opt_state, mstate=mstate,
                       nan_streak=nan_streak)

    def _clamp_superstep(self, state, k):
        """Largest j <= k such that no end/validation/checkpoint trigger
        would fire at an iteration INTERIOR to a j-step dispatch: the
        triggers are probed (side-effect-free) at the simulated counters
        neval+1 .. neval+k-1, and the dispatch is cut so any firing point
        lands exactly on a superstep boundary — host bookkeeping then
        runs at the same iteration it would under K=1. Loss/score-driven
        triggers are probed with the values as observed so far (the
        superstep-granularity lag documented in set_superstep)."""
        if k <= 1:
            return k
        triggers = [t for t in (self.end_trigger, self.validation_trigger,
                                self.checkpoint_trigger) if t is not None]
        if not triggers:
            return k
        sim = dict(state)
        sim["epoch_finished"] = False
        for i in range(1, k):
            sim["neval"] = state["neval"] + i
            for t in triggers:
                fired = t.probe(sim) if hasattr(t, "probe") \
                    else bool(t(dict(sim)))
                if fired:
                    return i
        return k

    def _run_epoch_supersteps(self, batches, state, box):
        """Superstep (K > 1) epoch loop: ``batches`` yields stacked
        ``(k, xs, ys)`` groups; each dispatch runs k fused steps inside
        one XLA program and the host resolves the whole ``[k]`` loss
        vector with ONE batched readback — per-step bookkeeping (loss
        observation, NaN policy, summaries, triggers) then replays
        host-side over the resolved vector, preserving K=1 semantics at
        1/K the sync count. Same ``box`` contract as _run_epoch_steps."""
        optim = self.optim_method
        params, opt_state, mstate = \
            box["params"], box["opt_state"], box["mstate"]
        nan_streak = box["nan_streak"]
        pending = None  # clamped remainder of a group (device slices)
        try:
            while True:
                self._step_beacon.pulse()
                t0 = time.time()
                if pending is not None:
                    (k, xs, ys), pending = pending, None
                else:
                    with obs.span("step/data_fetch"):
                        try:
                            k, xs, ys = next(batches)
                        except StopIteration:
                            return
                j = self._clamp_superstep(state, k)
                if j < k:
                    # a trigger fires mid-group: dispatch the prefix now,
                    # park the rest (device-side slices — no host copy)
                    pending = (k - j, _tmap(lambda a: a[j:], xs),
                               _tmap(lambda a: a[j:], ys))
                    xs = _tmap(lambda a: a[:j], xs)
                    ys = _tmap(lambda a: a[:j], ys)
                    k = j
                lrs = optim.current_lr_vector(k)
                rngs = engine.next_rng_keys(k)  # one dispatch, same stream
                t1 = time.time()
                with obs.span("step/superstep", neval=state["neval"], k=k):
                    with obs.span("step/dispatch"):
                        losses_dev, params, opt_state, mstate = \
                            self._step_fn(params, opt_state, mstate, xs, ys,
                                          jnp.asarray(lrs, jnp.float32),
                                          rngs)
                    if obs.enabled():
                        obs.counter("engine/dispatches").inc()
                    with obs.span("step/loss_sync"):
                        # sync-ok: the ONE batched [k] readback per superstep
                        losses = np.asarray(losses_dev)
                    if obs.enabled():
                        obs.counter("optim/loss_syncs").inc()
                t2 = time.time()
                self.metrics.add("data_time", t1 - t0)
                self.metrics.add("step_time", t2 - t1)
                if obs.enabled():
                    obs.counter("optim/steps").inc(k)
                    obs.gauge("optim/throughput", unit="samples/s").set(
                        k * self.batch_size / max(t2 - t0, 1e-9))
                restored = False
                for i, loss_val in enumerate(losses.tolist()):
                    if not np.isfinite(loss_val):
                        nan_streak += 1
                        if obs.enabled():
                            # superstep-vector aware: the host replay of
                            # the batched [k] readback feeds the recorder
                            # and detector per microstep
                            _flight.record("nan", neval=state["neval"],
                                           epoch=state["epoch"],
                                           loss=loss_val,
                                           policy=self.nan_policy,
                                           superstep_k=k, microstep=i)
                            if self._loss_monitor is not None:
                                self._loss_monitor.observe(loss_val,
                                                           state["neval"])
                        if self.nan_policy == "error":
                            raise FloatingPointError(
                                f"non-finite loss {loss_val} at iteration "
                                f"{state['neval']} — enable "
                                f"set_nan_policy('skip') to drop such steps")
                        if nan_streak > self.max_nan_retries:
                            raise FloatingPointError(
                                f"{nan_streak} consecutive non-finite steps "
                                f"(nan_policy='{self.nan_policy}') — data or "
                                "hyperparameters are unrecoverably bad")
                        if self.nan_policy == "resume":
                            self.wait_for_checkpoints()  # in-flight writes
                            snap = self._latest_checkpoint()
                            if snap is None:
                                raise FloatingPointError(
                                    "non-finite loss with nan_policy="
                                    "'resume' but no checkpoint saved yet "
                                    "— call set_checkpoint(...) first")
                            with open(snap, "rb") as f:
                                payload = pickle.load(f)
                            self.optim_method.state.update(
                                payload["optim_host_state"])
                            params, opt_state, mstate = \
                                self._restore_step_state(payload)
                            # the rest of this group's losses describe
                            # updates the restore just discarded
                            self.metrics.add("nan_resumes", 1.0)
                            obs.instant("step/nan_resume",
                                        neval=state["neval"])
                            restored = True
                            break
                        # 'skip': the in-scan guard already kept the
                        # previous state; count the iteration so end
                        # triggers advance
                        self.metrics.add("nan_skips", 1.0)
                        obs.instant("step/nan_skip", neval=state["neval"])
                        state["neval"] += 1
                        continue
                    nan_streak = 0
                    state["loss"] = loss_val
                    state["neval"] += 1
                    state["epoch_finished"] = False
                    if obs.enabled():
                        _flight.record("step", neval=state["neval"],
                                       epoch=state["epoch"], loss=loss_val,
                                       superstep_k=k, microstep=i)
                        if self._loss_monitor is not None:
                            self._loss_monitor.observe(loss_val,
                                                       state["neval"])
                    if self.train_summary is not None:
                        rec = self.train_summary.should_record
                        if rec("Loss", state):
                            self.train_summary.add_scalar(
                                "Loss", loss_val, state["neval"])
                        if rec("LearningRate", state):
                            self.train_summary.add_scalar(
                                "LearningRate", lrs[i], state["neval"])
                        if rec("Throughput", state):
                            self.train_summary.add_scalar(
                                "Throughput",
                                k * self.batch_size / max(t2 - t0, 1e-9),
                                state["neval"])
                if restored:
                    continue
                if self._profiler is not None:
                    self._profiler.maybe_tick(state["neval"])
                # checkpoint/validation/end triggers evaluate ONCE at the
                # superstep boundary, where params and the iteration
                # counter are consistent: clamping already aligned every
                # counter-driven firing point to a boundary, and a
                # loss-driven trigger (which the probe cannot foresee)
                # defers at most K-1 steps — it must never pair interior
                # counters with post-superstep params in a checkpoint
                if self._fire_mid_epoch(state, params, opt_state, mstate):
                    pass
                if self.end_trigger(state):
                    box["done"] = True
                    return
        finally:
            box.update(params=params, opt_state=opt_state, mstate=mstate,
                       nan_streak=nan_streak)

    def _fire_mid_epoch(self, state, params, opt_state, mstate):
        fired = False
        if self.validation_trigger is not None and \
                self.validation_trigger(state):
            self.model.params, self.model.state = \
                self._collect(params, mstate, opt_state)
            self._validate(state)
            fired = True
        if self.checkpoint_trigger is not None and \
                self.checkpoint_trigger(state):
            self._checkpoint(params, opt_state, mstate, state)
            fired = True
        return fired

    def _fire_epoch(self, state, params, opt_state, mstate):
        self._fire_mid_epoch(state, params, opt_state, mstate)

    # hooks overridden by DistriOptimizer
    def _to_host(self, tree):
        """Fetch a tree to host numpy for checkpointing."""
        return _tmap(np.asarray, tree)

    def _prepare(self, params, opt_state, mstate):
        return params, opt_state, mstate

    def _collect(self, params, mstate, opt_state=None):
        return params, mstate

    def _params_for_checkpoint(self, params):
        return params

    def _restore_step_state(self, payload):
        """Rebuild in-step (params, opt_state, mstate) from a checkpoint
        payload WITHOUT recreating sharding machinery (the compiled step fn
        closes over it)."""
        return self._prepare(_tmap(jnp.asarray, payload["params"]),
                             _tmap(jnp.asarray, payload["opt_state"]),
                             _tmap(jnp.asarray, payload["model_state"]))


class LocalOptimizer(BaseOptimizer):
    """Single-device training (parity: optim/LocalOptimizer.scala — there,
    multi-threaded CPU minibatch stacking; here one XLA device owns the whole
    batch)."""


class DistriOptimizer(BaseOptimizer):
    """Mesh data-parallel training (parity: optim/DistriOptimizer.scala)."""

    def __init__(self, model, training_set, criterion, optim_method=None,
                 end_trigger=None, batch_size: int = 32, mesh=None,
                 parameter_mode: str = "replicated",
                 compress: str = "none"):
        super().__init__(model, training_set, criterion, optim_method,
                         end_trigger, batch_size)
        from ..parallel.mesh import get_default_mesh
        self.mesh = mesh or get_default_mesh()
        if "data" not in self.mesh.axis_names:
            raise ValueError("DistriOptimizer mesh needs a 'data' axis")
        self.parameter_mode = parameter_mode
        self.compress = compress
        self._arp = None
        self._flat = None

    def _num_shards(self):
        return self.mesh.shape["data"]

    def _to_host(self, tree):
        # ZeRO-1 opt state is sharded P('data') across processes in
        # multi-controller runs; np.asarray on non-addressable shards
        # raises. gather_to_host reshards to replicated first (collective
        # — checkpoint triggers fire symmetrically on every process).
        from ..parallel.sharding import gather_to_host
        return gather_to_host(tree, self.mesh)

    def _check_split_agreement(self):
        """Multi-controller: every process feeds its own data split; if
        the per-process batch counts differ, the extra steps on the larger
        split would block forever in the cross-process psum. Fail loudly
        at setup instead of deadlocking mid-epoch."""
        from ..parallel.sharding import is_multi_process
        if not is_multi_process(self.mesh):
            return
        import jax.numpy as jnp
        from jax.experimental import multihost_utils
        src = self._batched()
        n = getattr(src, "batches_per_epoch", 0)
        n = int(n() if callable(n) else (n or 0))
        counts = np.asarray(multihost_utils.process_allgather(
            jnp.asarray([n], jnp.int32))).reshape(-1)
        if len(set(counts.tolist())) > 1:
            raise ValueError(
                "per-process dataset splits disagree on batches/epoch "
                f"{counts.tolist()}; pad or trim the local splits so every "
                "process takes the same number of steps (uneven splits "
                "deadlock in the cross-process gradient psum)")

    def _place_batch(self, x, y):
        from ..parallel.sharding import shard_batch
        return (shard_batch(x, self.mesh), shard_batch(y, self.mesh))

    def _place_group(self, xs, ys):
        from ..parallel.sharding import shard_stacked_batch
        return (shard_stacked_batch(xs, self.mesh),
                shard_stacked_batch(ys, self.mesh))

    def _prepare(self, params, opt_state, mstate):
        from ..parallel.sharding import shard_params, put_global
        self._check_split_agreement()
        if self.parameter_mode == "zero1":
            from ..parallel.allreduce import AllReduceParameter
            self._arp = AllReduceParameter(self.optim_method, self.mesh,
                                           compress=self.compress)
            flat_w, opt_state = self._arp.prepare(params)
            self._flat = self._arp.flat
            mstate = shard_params(mstate, self.mesh)
            return put_global(flat_w, self.mesh, P()), opt_state, mstate
        params = shard_params(params, self.mesh)
        opt_state = shard_params(opt_state, self.mesh)
        mstate = shard_params(mstate, self.mesh)
        return params, opt_state, mstate

    def _collect(self, params, mstate, opt_state=None):
        if self.parameter_mode == "zero1":
            return self._flat.unflatten(jax.device_get(params)), mstate
        return params, mstate

    def _params_for_checkpoint(self, params):
        if self.parameter_mode == "zero1":
            return self._flat.unflatten(jax.device_get(params))
        return params

    def _restore_step_state(self, payload):
        from ..parallel.sharding import shard_params, put_global
        params = _tmap(jnp.asarray, payload["params"])
        opt_state = _tmap(jnp.asarray, payload["opt_state"])
        mstate = shard_params(_tmap(jnp.asarray, payload["model_state"]),
                              self.mesh)
        if self.parameter_mode == "zero1" and self._arp is not None:
            # reuse the existing FlatParameter/AllReduceParameter — the
            # compiled step closes over them; only re-place the data
            flat_w = put_global(self._flat.flatten(params), self.mesh, P())
            opt_specs = self._arp.state_specs()
            opt_state = jax.tree_util.tree_map(
                lambda a, sp: put_global(a, self.mesh, sp),
                opt_state, opt_specs)
            return flat_w, opt_state, mstate
        return (shard_params(params, self.mesh),
                shard_params(opt_state, self.mesh), mstate)

    def _build_step(self):
        if self.parameter_mode != "zero1":
            return super()._build_step()

        from ..utils.compat import shard_map
        from jax.flatten_util import ravel_pytree
        model, criterion = self.model, self.criterion
        reg_tree = regularizer_tree(model)
        clip_const, clip_norm = self.clip_const, self.clip_norm
        arp, flat = self._arp, self._flat
        mesh = self.mesh
        fm = _frozen_mask(model)
        flat_mask = None
        if fm is not None:
            full = _tmap(lambda p, m: jnp.full(jnp.shape(p), m,
                                               jnp.float32),
                         model.params, fm)
            flat_mask = flat.flatten(full)

        def loss_fn(flat_w, mstate, x, y, rng):
            params = flat.unflatten(flat_w)
            out, new_state = model.apply(params, mstate, x, training=True,
                                         rng=rng)
            loss = criterion._forward(out, y)
            if reg_tree:
                loss = loss + regularization_loss(reg_tree, params)
            return loss, new_state

        superstep_k = self.superstep

        def local_step(flat_w, opt_slice, mstate, x, y, lr, rng):
            rng = jax.random.fold_in(rng, jax.lax.axis_index("data"))
            (loss, new_mstate), gflat = jax.value_and_grad(
                loss_fn, has_aux=True)(flat_w, mstate, x, y, rng)
            gflat = _clip_grads(gflat, clip_const, clip_norm)
            if flat_mask is not None:
                gflat = gflat * flat_mask
            new_flat, new_opt = arp.update(gflat, flat_w, opt_slice, lr,
                                           traced_steps=superstep_k)
            if flat_mask is not None:
                new_flat = jnp.where(flat_mask > 0, new_flat, flat_w)
            loss = jax.lax.pmean(loss, "data")
            new_mstate = _tmap(lambda t: jax.lax.pmean(t, "data"), new_mstate)
            # same in-step NaN guard as the local path (post-pmean, so every
            # shard takes the same branch — no divergence across the mesh)
            ok = jnp.isfinite(loss)
            pick = lambda new, old: _tmap(
                lambda a, b: jnp.where(ok, a, b), new, old)
            return (loss, pick(new_flat, flat_w), pick(new_opt, opt_slice),
                    pick(new_mstate, mstate))

        opt_specs = arp.state_specs()
        mstate_specs = _tmap(lambda _: P(), self.model.state)
        if superstep_k > 1:
            # the scan lives INSIDE the shard_map body: the ZeRO-1
            # psum_scatter/update/all_gather cycle stays in the compiled
            # loop (the cross-replica sharded update must ride the scan
            # for superstep fusion to pay off — one program, K collective
            # rounds, zero host round-trips in between). Batch stacks
            # carry the scan dim first, per-step batch dim sharded.
            sharded = shard_map(
                _scan_superstep(local_step), mesh=mesh,
                in_specs=(P(), opt_specs, mstate_specs, P(None, "data"),
                          P(None, "data"), P(), P()),
                out_specs=(P(), P(), opt_specs, mstate_specs),
                check_vma=False)
        else:
            sharded = shard_map(
                local_step, mesh=mesh,
                in_specs=(P(), opt_specs, mstate_specs, P("data"), P("data"),
                          P(), P()),
                out_specs=(P(), P(), opt_specs, mstate_specs),
                check_vma=False)
        return jax.jit(sharded, donate_argnums=(0, 1, 2))


class ParallelOptimizer(DistriOptimizer):
    """Name parity: optim/ParallelOptimizer.scala — the reference's
    layer-wise-parallel gradient aggregation variant. Under XLA the jitted
    step already aggregates all gradients in one fused program, so this is
    the same engine as DistriOptimizer."""


class Optimizer(BaseOptimizer):
    """Factory with the reference's signature (optim/Optimizer.scala apply):
    picks Local vs Distri from the engine mesh size."""

    def __new__(cls, model=None, training_set=None, training_rdd=None,
                criterion=None, optim_method=None, end_trigger=None,
                batch_size: int = 32, mesh=None, **kw):
        training = training_set if training_set is not None else training_rdd
        from ..parallel.mesh import get_default_mesh
        m = mesh or (get_default_mesh() if len(jax.devices()) > 1 else None)
        if m is not None and m.size > 1:
            return DistriOptimizer(model, training, criterion, optim_method,
                                   end_trigger, batch_size, mesh=m, **kw)
        obj = object.__new__(LocalOptimizer)
        obj.__init__(model, training, criterion, optim_method, end_trigger,
                     batch_size)
        return obj

    @staticmethod
    def create(model, training_set, criterion, end_trigger=None,
               batch_size=32, optim_method=None, cores=None,
               bigdl_type="float"):
        """pyspark ``Optimizer.create`` spelling (the ``cores``/
        ``bigdl_type`` args are JVM-era and ignored; local-vs-distributed
        is picked from the engine mesh like the constructor)."""
        return Optimizer(model=model, training_set=training_set,
                         criterion=criterion, optim_method=optim_method,
                         end_trigger=end_trigger, batch_size=batch_size)
