"""Predictor (parity: reference ``optim/Predictor.scala`` /
``optim/LocalPredictor.scala`` / ``optim/PredictionService.scala``)."""
from __future__ import annotations


import jax
import jax.numpy as jnp
import numpy as np

from .. import observability as obs
from ..dataset.dataset import AbstractDataSet, ShardedDataSet, DataSet
from ..utils.table import Table


class Predictor:
    def __init__(self, model, batch_per_partition: int = 4):
        self.model = model
        self._fwd = None

    def _forward_fn(self):
        if self._fwd is None:
            model = self.model

            def fwd(params, state, x):
                out, _ = model.apply(params, state, x, training=False)
                return out
            self._fwd = jax.jit(fwd)
        return self._fwd

    def _iter_outputs(self, dataset, batch_size):
        if isinstance(dataset, np.ndarray):
            dataset = DataSet.from_arrays(dataset)
        self.model.ensure_initialized()
        fwd = self._forward_fn()
        batched = ShardedDataSet(dataset, batch_size, drop_last=False)
        for mb in batched.data(train=False):
            sp = obs.span("predict/batch")
            with sp:
                x = mb.get_input()
                x = jax.tree_util.tree_map(jnp.asarray, x) \
                    if isinstance(x, Table) else jnp.asarray(x)
                out = np.asarray(fwd(self.model.params, self.model.state, x))
            if obs.enabled():
                obs.histogram("predict/batch_s", unit="s").observe(
                    sp.duration_s)
            yield out

    def predict(self, dataset, batch_size: int = 32):
        outs = list(self._iter_outputs(dataset, batch_size))
        return np.concatenate(outs, axis=0)

    def predict_class(self, dataset, batch_size: int = 32):
        """1-based argmax class, parity with predictClass."""
        return np.argmax(self.predict(dataset, batch_size), axis=-1) + 1


class PredictionService(Predictor):
    """Thread-safe serving facade (parity: optim/PredictionService.scala).
    XLA compiled functions are thread-safe; this is a thin alias."""
