"""Predictor (parity: reference ``optim/Predictor.scala`` /
``optim/LocalPredictor.scala`` / ``optim/PredictionService.scala``)."""
from __future__ import annotations


import jax
import jax.numpy as jnp
import numpy as np

from .. import observability as obs
from ..dataset.dataset import AbstractDataSet, ShardedDataSet, DataSet
from .staging import staged
from ..utils import engine
from ..utils.table import Table


class Predictor:
    def __init__(self, model, batch_per_partition: int = 4,
                 prefetch_depth: int = 2):
        """``batch_per_partition`` (reference parity: Predictor.scala's
        batchPerPartition) sets the default per-device batch —
        ``predict(ds)`` without an explicit ``batch_size`` runs
        ``batch_per_partition * device_count`` samples per forward, the
        XLA analog of the reference's per-Spark-partition batching."""
        self.model = model
        self.batch_per_partition = batch_per_partition
        self.prefetch_depth = prefetch_depth
        self._fwd = None

    def _default_batch(self):
        return self.batch_per_partition * max(1, len(jax.devices()))

    def _forward_fn(self):
        if self._fwd is None:
            model = self.model
            engine.maybe_enable_compilation_cache()

            def fwd(params, state, x):
                out, _ = model.apply(params, state, x, training=False)
                return out
            self._fwd = jax.jit(fwd)
        return self._fwd

    @staticmethod
    def _stage(mb):
        from .staging import place_host_value
        return place_host_value(mb.get_input())

    def _iter_outputs(self, dataset, batch_size):
        """Yields DEVICE-resident per-batch outputs: the dispatch loop
        never blocks on a device→host copy, so batch N+1's forward (and
        the stager's transfers) overlap batch N's compute. Consumers
        that want host arrays fetch at the end (``predict`` does ONE
        ``device_get`` over the whole run) or per batch themselves."""
        if isinstance(dataset, np.ndarray):
            dataset = DataSet.from_arrays(dataset)
        self.model.ensure_initialized()
        fwd = self._forward_fn()
        batched = ShardedDataSet(dataset, batch_size, drop_last=False)
        batches = staged(batched.data(train=False), self._stage,
                         depth=self.prefetch_depth, name="predict_stager")
        try:
            for x in batches:
                sp = obs.span("predict/batch")
                with sp:
                    out = fwd(self.model.params, self.model.state, x)
                if obs.enabled():
                    obs.histogram("predict/batch_s", unit="s").observe(
                        sp.duration_s)
                yield out
        finally:
            # an abandoned generator (predict_class slicing, early break)
            # must still join the stager thread
            batches.close()

    def predict(self, dataset, batch_size=None):
        from collections import deque
        depth = max(1, self.prefetch_depth)
        outs = []
        window = deque()  # device outputs in flight (bounds HBM residency)
        for out in self._iter_outputs(dataset,
                                      batch_size or self._default_batch()):
            window.append(out)
            if len(window) > depth:
                # sync-ok: LAGGED fetch — this output is `depth` batches
                # old, so the device pipeline never drains (the old code
                # blocked on the CURRENT batch every iteration), while
                # only depth+1 outputs ever live in device memory
                outs.append(np.asarray(window.popleft()))
                if obs.enabled():
                    obs.counter("predict/readbacks").inc()
        if window:
            # sync-ok: end-of-run drain of the in-flight window
            outs.extend(np.asarray(o) for o in jax.device_get(list(window)))
            if obs.enabled():
                obs.counter("predict/readbacks").inc()
        if not outs:
            return np.empty((0,), np.float32)
        return np.concatenate(outs, axis=0)

    def predict_class(self, dataset, batch_size=None):
        """1-based argmax class, parity with predictClass."""
        return np.argmax(self.predict(dataset, batch_size), axis=-1) + 1


class PredictionService(Predictor):
    """Thread-safe serving facade (parity: optim/PredictionService.scala).
    XLA compiled functions are thread-safe; this is a thin alias."""
