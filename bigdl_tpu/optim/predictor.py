"""Predictor (parity: reference ``optim/Predictor.scala`` /
``optim/LocalPredictor.scala`` / ``optim/PredictionService.scala``).

Also home of the ONE compiled inference forward per model
(:func:`shared_forward`) and the pad-to-bucket shape discipline both
``Predictor.predict()`` and the online serving engine
(``bigdl_tpu/serving/``) ride: every forward dispatch uses a shape from
a bounded bucket set, so the compiled-executable population stays small
and the persistent compile cache (``engine/compile_cache_hits|misses``)
stays hot across processes.
"""
from __future__ import annotations

import threading
import weakref

import jax
import jax.numpy as jnp
import numpy as np

from .. import observability as obs
from ..dataset.dataset import AbstractDataSet, ShardedDataSet, DataSet
from .staging import staged
from ..utils import engine
from ..utils.table import Table


# --------------------------------------------------------------------------
# shape buckets: the bounded set of compiled batch shapes
# --------------------------------------------------------------------------

def bucket_for(n: int, max_batch: int) -> int:
    """Smallest power-of-two >= ``n``, capped at ``max_batch`` — the
    padded batch size a ragged batch of ``n`` rows dispatches as. The
    reachable shape set is {1, 2, 4, ..., 2^k, max_batch}: bounded, so
    warmup can precompile it and a ragged epoch tail (or a serving
    micro-batch of any occupancy) never pays a fresh XLA compile beyond
    that set."""
    if n <= 0:
        raise ValueError(f"batch rows must be positive, got {n}")
    if n >= max_batch:
        return max_batch
    b = 1
    while b < n:
        b <<= 1
    return min(b, max_batch)


def shape_buckets(max_batch: int):
    """The full bucket set for ``max_batch``: ascending powers of two
    plus ``max_batch`` itself (deduplicated) — what serving warmup
    compiles at startup."""
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    out = []
    b = 1
    while b < max_batch:
        out.append(b)
        b <<= 1
    out.append(max_batch)
    return tuple(out)


def leading_dim(x) -> int:
    """Rows in a (possibly Table-structured) batch."""
    if isinstance(x, Table):
        leaves = jax.tree_util.tree_leaves(x)
        return int(leaves[0].shape[0]) if leaves else 0
    return int(np.shape(x)[0])


def pad_leading(x, bucket: int):
    """Zero-pad a batch (array or Table of arrays) along axis 0 up to
    ``bucket`` rows. Host-side (numpy) when given host values — do this
    BEFORE device placement so the transfer and the compiled shape are
    both the bucket shape. Rows past the true count are zeros; callers
    slice them away after the forward (padded rows are compute waste,
    never a correctness input)."""
    def _pad(a):
        n = a.shape[0]
        if n == bucket:
            return a
        if n > bucket:
            raise ValueError(f"batch of {n} rows exceeds bucket {bucket}")
        pad = [(0, bucket - n)] + [(0, 0)] * (a.ndim - 1)
        return (np.pad(a, pad) if isinstance(a, np.ndarray)
                else jnp.pad(a, pad))
    if isinstance(x, Table):
        return jax.tree_util.tree_map(_pad, x)
    return _pad(np.asarray(x) if not isinstance(x, jnp.ndarray) else x)


# --------------------------------------------------------------------------
# the shared compiled forward
# --------------------------------------------------------------------------

class CompiledForward:
    """ONE jit'd ``(params, state, x) -> output`` inference forward for a
    model instance. ``Predictor.predict()`` and the serving engine both
    call through here, so a bucket shape compiles ONCE per process no
    matter which consumer touches it first (and lands in the persistent
    compile cache for the next process). Taking ``params`` explicitly is
    what makes serving hot-swap free: a new model version is new params
    through the SAME compiled executable, zero recompiles."""

    def __init__(self, model):
        # weakly held: this object is the VALUE in a WeakKeyDictionary
        # keyed by the model — a strong ref here would keep the key (and
        # its executables) alive forever, defeating the weak keying
        self._model_ref = weakref.ref(model)
        self._jit = None
        self._lock = threading.Lock()

    @property
    def model(self):
        return self._model_ref()

    def fn(self):
        if self._jit is None:
            with self._lock:
                if self._jit is None:
                    model_ref = self._model_ref
                    engine.maybe_enable_compilation_cache()

                    def fwd(params, state, x):
                        # runs at TRACE time only (once per bucket shape);
                        # anyone compiling a new shape necessarily still
                        # holds params, but the model may be gone if only
                        # this wrapper was retained
                        model = model_ref()
                        if model is None:
                            raise RuntimeError(
                                "model was garbage-collected; cannot "
                                "trace a new input shape")
                        out, _ = model.apply(params, state, x,
                                             training=False)
                        return out
                    # every bucket shape (Predictor batches, serving
                    # warmup/live buckets) records its own
                    # CompiledArtifact — params/state are shape-stable,
                    # so the signature key is the input alone
                    model = model_ref()
                    name = f"predict/forward/{type(model).__name__}" \
                        if model is not None else "predict/forward"
                    self._jit = obs.perf.instrument_jit(
                        jax.jit(fwd), name=name, kind="forward",
                        key_argnums=(2,))
        return self._jit

    def __call__(self, params, state, x):
        return self.fn()(params, state, x)

    def compiled_shape_count(self) -> int:
        """Distinct input shapes compiled so far (tests assert the
        bucket discipline keeps this bounded). Counts both the
        instrumented AOT entries (observability on) and the inner jit
        cache (observability off)."""
        if self._jit is None:
            return 0
        n = self._jit.compiled_shape_count()
        try:
            return n + int(self._jit._jit._cache_size())
        except AttributeError:  # older jax: no introspection
            return n if n else -1


_shared_forwards = weakref.WeakKeyDictionary()
_shared_lock = threading.Lock()


def shared_forward(model) -> CompiledForward:
    """The process-wide :class:`CompiledForward` for ``model`` (weakly
    keyed — dropping the model drops its executable cache)."""
    fwd = _shared_forwards.get(model)
    if fwd is None:
        with _shared_lock:
            fwd = _shared_forwards.get(model)
            if fwd is None:
                fwd = CompiledForward(model)
                _shared_forwards[model] = fwd
    return fwd


class Predictor:
    def __init__(self, model, batch_per_partition: int = 4,
                 prefetch_depth: int = 2):
        """``batch_per_partition`` (reference parity: Predictor.scala's
        batchPerPartition) sets the default per-device batch —
        ``predict(ds)`` without an explicit ``batch_size`` runs
        ``batch_per_partition * device_count`` samples per forward, the
        XLA analog of the reference's per-Spark-partition batching."""
        self.model = model
        self.batch_per_partition = batch_per_partition
        self.prefetch_depth = prefetch_depth
        self._superstep = 1
        self._scan_jit = None

    def set_superstep(self, k: int):
        """Fuse K prediction batches into ONE compiled ``lax.scan``
        dispatch (the Evaluator's superstep, for the output path): the
        stager stacks K same-shape staged batches to [K, B, ...], one
        program runs all K forwards, and the per-batch outputs come back
        as device-resident slices of the [K, B, ...] stack — the lagged
        readback window in :meth:`predict` is unchanged.
        ``predict/dispatches`` counts compiled calls (K-fold drop
        asserted in tests/test_superstep.py)."""
        if k < 1:
            raise ValueError(f"superstep must be >= 1, got {k}")
        self._superstep = int(k)
        return self

    def _default_batch(self):
        return self.batch_per_partition * max(1, len(jax.devices()))

    def _forward_fn(self):
        return shared_forward(self.model)

    def _scan_forward_fn(self):
        if self._scan_jit is None:
            model = self.model
            engine.maybe_enable_compilation_cache()

            def fwd_scan(params, state, xs):
                def body(_, x):
                    out, _s = model.apply(params, state, x, training=False)
                    return None, out
                return jax.lax.scan(body, None, xs)[1]
            self._scan_jit = obs.perf.instrument_jit(
                jax.jit(fwd_scan),
                name=f"predict/forward_scan/{type(model).__name__}",
                kind="forward", key_argnums=(2,))
        return self._scan_jit

    def _iter_outputs(self, dataset, batch_size):
        """Yields DEVICE-resident per-batch ``(output, rows)`` pairs: the
        dispatch loop never blocks on a device→host copy, so batch N+1's
        forward (and the stager's transfers) overlap batch N's compute.
        A ragged final batch is zero-padded on the HOST to its power-of-
        two bucket (``bucket_for``), so every dispatch reuses a compiled
        shape from the bounded bucket set; ``rows`` is the true count the
        consumer slices back to. Consumers that want host arrays fetch at
        the end (``predict`` does ONE ``device_get`` over the whole run)
        or per batch themselves."""
        if isinstance(dataset, np.ndarray):
            dataset = DataSet.from_arrays(dataset)
        self.model.ensure_initialized()
        fwd = self._forward_fn()
        max_batch = batch_size

        def _stage(mb):
            from .staging import place_host_value
            x = mb.get_input()
            n = leading_dim(x)
            if 0 < n < max_batch:
                x = pad_leading(x, bucket_for(n, max_batch))
            return place_host_value(x), n

        k = self._superstep

        def _group(items):
            # [(x, n), ...] -> ([K, B, ...] device stack, (n, ...)) on
            # the stager thread (equal padded shapes via the group key)
            from .evaluator import _stack_tree
            return (_stack_tree([x for x, _ in items]),
                    tuple(n for _, n in items))

        def _gkey(item):
            from .evaluator import _tree_shape_key
            return _tree_shape_key(item[0])

        batched = ShardedDataSet(dataset, batch_size, drop_last=False)
        batches = staged(batched.data(train=False), _stage,
                         depth=self.prefetch_depth, name="predict_stager",
                         group=k, group_fn=_group if k > 1 else None,
                         group_key=_gkey if k > 1 else None)
        scan_fwd = self._scan_forward_fn() if k > 1 else None
        try:
            for item in batches:
                sp = obs.span("predict/batch")
                if k > 1:
                    xs, ns = item
                    with sp:
                        outs = scan_fwd(self.model.params,
                                        self.model.state, xs)
                    if obs.enabled():
                        obs.counter("predict/dispatches").inc()
                        obs.histogram("predict/batch_s", unit="s").observe(
                            sp.duration_s)
                    # device-resident slices of the [K, B, ...] stack —
                    # the consumer's lagged-fetch window is unchanged
                    for i, n in enumerate(ns):
                        yield jax.tree_util.tree_map(lambda o, i=i: o[i],
                                                     outs), n
                    continue
                x, n = item
                with sp:
                    out = fwd(self.model.params, self.model.state, x)
                if obs.enabled():
                    obs.counter("predict/dispatches").inc()
                    obs.histogram("predict/batch_s", unit="s").observe(
                        sp.duration_s)
                yield out, n
        finally:
            # an abandoned generator (predict_class slicing, early break)
            # must still join the stager thread
            batches.close()

    def predict(self, dataset, batch_size=None):
        from collections import deque
        depth = max(1, self.prefetch_depth)
        outs = []
        window = deque()  # device outputs in flight (bounds HBM residency)
        for out, n in self._iter_outputs(dataset,
                                         batch_size or self._default_batch()):
            window.append((out, n))
            if len(window) > depth:
                # sync-ok: LAGGED fetch — this output is `depth` batches
                # old, so the device pipeline never drains (the old code
                # blocked on the CURRENT batch every iteration), while
                # only depth+1 outputs ever live in device memory
                o, k = window.popleft()
                outs.append(np.asarray(o)[:k])
                if obs.enabled():
                    obs.counter("predict/readbacks").inc()
        if window:
            # sync-ok: end-of-run drain of the in-flight window
            fetched = jax.device_get([o for o, _ in window])
            outs.extend(np.asarray(o)[:k]
                        for o, (_, k) in zip(fetched, window))
            if obs.enabled():
                obs.counter("predict/readbacks").inc()
        if not outs:
            return np.empty((0,), np.float32)
        return np.concatenate(outs, axis=0)

    def predict_class(self, dataset, batch_size=None):
        """1-based argmax class, parity with predictClass."""
        return np.argmax(self.predict(dataset, batch_size), axis=-1) + 1


class PredictionService(Predictor):
    """Thread-safe serving facade (parity: optim/PredictionService.scala).
    XLA compiled functions are thread-safe; this is a thin alias — the
    full online engine (micro-batching, buckets, backpressure, hot swap)
    lives in ``bigdl_tpu/serving/``."""
