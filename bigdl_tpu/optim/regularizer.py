"""Regularizers (parity: reference ``optim/Regularizer.scala``).

The reference adds the penalty gradient inside each layer's
accGradParameters; here the penalty is added to the (differentiated) loss in
the train step — same update, autodiff does the work.
"""
from __future__ import annotations

import jax.numpy as jnp


class Regularizer:
    def loss(self, w):
        return 0.0


class L1L2Regularizer(Regularizer):
    def __init__(self, l1: float = 0.0, l2: float = 0.0):
        self.l1, self.l2 = l1, l2

    def loss(self, w):
        out = 0.0
        if self.l1:
            out = out + self.l1 * jnp.sum(jnp.abs(w))
        if self.l2:
            out = out + 0.5 * self.l2 * jnp.sum(jnp.square(w))
        return out


class L1Regularizer(L1L2Regularizer):
    def __init__(self, l1: float):
        super().__init__(l1=l1, l2=0.0)


class L2Regularizer(L1L2Regularizer):
    def __init__(self, l2: float):
        super().__init__(l1=0.0, l2=l2)


def regularizer_tree(module):
    """Build a nested dict mirroring ``module``'s params containing
    Regularizer objects (or missing keys where none)."""
    from ..nn.module import Container
    if isinstance(module, Container):
        tree = {}
        for i, child in enumerate(module.modules):
            sub = regularizer_tree(child)
            if sub:
                tree[str(i)] = sub
        return tree
    if hasattr(module, "_regularizers"):
        return {k: v for k, v in module._regularizers().items()
                if v is not None}
    return {}


def regularization_loss(reg_tree, params):
    """Sum penalty over params matching the regularizer tree."""
    total = 0.0
    for k, v in reg_tree.items():
        if k not in params:
            continue
        if isinstance(v, dict):
            total = total + regularization_loss(v, params[k])
        else:
            total = total + v.loss(params[k])
    return total
