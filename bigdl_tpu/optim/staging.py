"""Asynchronous batch staging: overlap host batch prep with device compute.

The serial loop pays ``produce batch -> device_put -> dispatch`` every
iteration, so the device idles while the host decodes/places the next
batch — the executor-side stall BigDL's Spark pipeline hid behind RDD
prefetch. ``BatchStager`` moves produce+place onto one bounded lookahead
thread: while step N runs on the device, the stager pulls batches
N+1..N+depth from the dataset iterator and stages them (sharded
``device_put`` via the caller's place function), so the hot loop's
``step/data_fetch`` collapses to a queue pop of an already-on-device
batch. The native ``bf16_nhwc`` prefetcher composes directly: its decode
workers emit accelerator-ready buffers and the stager's place call is a
cast-free, transpose-free ``device_put``.

Correctness invariants:

* **Order-preserving.** One worker thread, one FIFO queue — the consumer
  sees batches in exactly the serial order, so training trajectories are
  bitwise identical to the serial loop (tests/test_pipeline_loop.py).
* **Error-transparent.** An exception in the dataset iterator or the
  place function is re-raised in the consumer at the matching ``next()``.
* **No thread leaks.** ``close()`` (idempotent, also called on iterator
  exhaustion) unblocks and joins the worker; threads are named
  ``bigdl_tpu-stager`` so tests can assert none survive.
"""
from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Iterable, Iterator, Optional

from .. import observability as obs
from ..observability import health as _health

THREAD_NAME = "bigdl_tpu-stager"

_SENTINEL = object()


class BatchStager:
    """Bounded lookahead stager: a daemon thread pulls items from
    ``source``, maps them through ``stage_fn`` (host decode + device
    placement) and parks up to ``depth`` staged results in a FIFO queue.

    Iterate it like the source iterable; call :meth:`close` (or use as a
    context manager) to shut the worker down early — e.g. when an end
    trigger fires mid-epoch.

    Stacking stage (superstep fusion): with ``group=K`` and a
    ``group_fn``, the worker collects up to K staged items and emits ONE
    ``group_fn([item, ...])`` result per group — the optimizer's group
    fn assembles the ``[K, batch, ...]`` stacked device arrays a
    superstep dispatch consumes, so the whole stack+place cost rides the
    stager thread and the hot loop still dequeues one element. The final
    group of an epoch may be smaller than K (epoch-end clamping)."""

    def __init__(self, source: Iterable, stage_fn: Callable, depth: int = 2,
                 name: str = "stager", group: int = 1,
                 group_fn: Optional[Callable] = None,
                 group_key: Optional[Callable] = None,
                 stall_deadline_s: Optional[float] = None):
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        if group < 1:
            raise ValueError(f"group must be >= 1, got {group}")
        if group > 1 and group_fn is None:
            raise ValueError("group > 1 requires a group_fn")
        self._source = source
        self._stage_fn = stage_fn
        self._group = group
        self._group_fn = group_fn
        # items whose key differs cannot share a stack (a prefetcher's
        # ragged final batch must not np.stack against full ones): a key
        # change flushes the pending group and starts a new one
        self._group_key = group_key or (lambda item: None)
        self._name = name
        # per-instance metric names: a mid-training eval/predict stager
        # must not clobber the training stager's queue-depth signal
        self._depth_gauge = f"optim/{name}_queue_depth"
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._err = None
        self._done = False
        # stall watchdog: the worker pulses per source item AND while
        # healthily blocked on a full queue (the consumer owns that
        # wait) — silence therefore means the worker is wedged inside
        # next(source) or stage_fn (a hung decode or device_put), the
        # exact "training stopped, no error" case the watchdog pages on.
        # No-op beacon when observability is disabled.
        self._beacon = _health.beacon(f"stager/{name}",
                                      deadline_s=stall_deadline_s)
        self._thread = threading.Thread(
            target=self._run, name=THREAD_NAME, daemon=True)
        self._thread.start()

    # -- worker ----------------------------------------------------------
    def _run(self):
        it = iter(self._source)
        pending = []  # staged items awaiting a full group (group > 1)
        try:
            exhausted = False
            while not self._stop.is_set() and not exhausted:
                with obs.span(f"{self._name}/source_wait"):
                    t0 = time.perf_counter()
                    try:
                        item = next(it)
                    except StopIteration:
                        exhausted = True
                self._beacon.pulse()  # per source ITEM — a group-mode
                # iteration may `continue` below while still pending
                if obs.enabled():
                    # time the worker spent blocked on the upstream
                    # iterator (dataset produce): large values mean the
                    # stager itself is input-bound and a deeper queue
                    # won't help
                    obs.histogram(f"optim/{self._name}_source_wait_s",
                                  unit="s").observe(time.perf_counter() - t0)
                if exhausted:
                    emit = []
                    if pending:  # epoch tail: a smaller final group
                        emit, pending = [self._group_fn(pending)], []
                elif self._group > 1:
                    staged = self._stage_fn(item)
                    emit = []
                    if pending and self._group_key(staged) != \
                            self._group_key(pending[0]):
                        emit, pending = [self._group_fn(pending)], []
                    pending.append(staged)
                    if len(pending) == self._group:
                        emit.append(self._group_fn(pending))
                        pending = []
                    if not emit:
                        continue
                else:
                    emit = [self._stage_fn(item)]
                for staged in emit:
                    while not self._stop.is_set():
                        try:
                            self._q.put(staged, timeout=0.1)
                            break
                        except queue.Full:
                            # a full queue is the CONSUMER's wait, not a
                            # stager stall — keep the beacon fresh
                            self._beacon.pulse()
                            continue
                if obs.enabled():
                    obs.gauge(self._depth_gauge).set(self._q.qsize())
        except BaseException as e:  # noqa: BLE001 — re-raised in consumer
            self._err = e
        finally:
            self._beacon.close()
            close = getattr(it, "close", None)
            if close is not None:
                try:
                    close()
                except Exception:
                    pass
            while not self._stop.is_set():
                try:
                    self._q.put(_SENTINEL, timeout=0.1)
                    break
                except queue.Full:
                    continue

    # -- consumer --------------------------------------------------------
    def __iter__(self) -> Iterator:
        return self

    def __next__(self):
        if self._done:
            raise StopIteration
        while True:
            try:
                item = self._q.get(timeout=0.5)
                break
            except queue.Empty:
                if not self._thread.is_alive():
                    # worker died between put attempts; whatever is
                    # queued was consumed already — surface its error
                    self._done = True
                    self._reraise()
                    raise StopIteration
        if item is _SENTINEL:
            self._done = True
            self._thread.join(timeout=30)
            self._reraise()
            raise StopIteration
        if obs.enabled():
            obs.gauge(self._depth_gauge).set(self._q.qsize())
        return item

    def _reraise(self):
        if self._err is not None:
            err, self._err = self._err, None
            raise err

    def close(self):
        """Stop the worker and join it (idempotent, never raises). Any
        staged-but-unconsumed batches are dropped."""
        self._stop.set()
        try:  # drain so a worker blocked on a full queue wakes promptly
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=30)
        if self._thread.is_alive():
            # the worker is wedged inside stage_fn (e.g. a device_put over
            # a hung tunnel) — surface the leak instead of pretending the
            # join succeeded
            import logging
            logging.getLogger(__name__).warning(
                "stager %r worker did not join within 30s (blocked in "
                "stage_fn?) — daemon thread leaked", self._name)
        # a wedged worker never reaches its own finally — the closed
        # run must not keep paging the watchdog
        self._beacon.close()
        self._done = True

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False


class _SerialStager:
    """Depth-0/1 fallback with the same iterator + ``close()`` surface:
    stages each item inline at ``next()`` — the serial loop, unchanged,
    so ``set_prefetch(0)`` is an exact A/B switch. ``group``/``group_fn``
    stack inline with the same semantics as the threaded stager."""

    def __init__(self, source: Iterable, stage_fn: Callable,
                 group: int = 1, group_fn: Optional[Callable] = None,
                 group_key: Optional[Callable] = None):
        if group > 1 and group_fn is None:
            raise ValueError("group > 1 requires a group_fn")
        self._it = iter(source)
        self._stage_fn = stage_fn
        self._group = group
        self._group_fn = group_fn
        self._group_key = group_key or (lambda item: None)
        self._carry = []  # lookahead item that broke the previous group

    def __iter__(self):
        return self

    def __next__(self):
        if self._group <= 1:
            return self._stage_fn(next(self._it))
        pending, self._carry = self._carry, []
        while len(pending) < self._group:
            try:
                staged = self._stage_fn(next(self._it))
            except StopIteration:
                if pending:
                    break  # epoch tail: a smaller final group
                raise
            if pending and self._group_key(staged) != \
                    self._group_key(pending[0]):
                self._carry = [staged]  # shape break: next group starts here
                break
            pending.append(staged)
        return self._group_fn(pending)

    def close(self):
        close = getattr(self._it, "close", None)
        if close is not None:
            try:
                close()
            except Exception:
                pass

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False


def staged(source: Iterable, stage_fn: Callable, depth: int = 2,
           name: str = "stager", group: int = 1,
           group_fn: Optional[Callable] = None,
           group_key: Optional[Callable] = None,
           stall_deadline_s: Optional[float] = None):
    """Pick the pipelined or serial staging wrapper by ``depth``
    (>= 2 spawns the lookahead thread; 0/1 stays inline). ``group``/
    ``group_fn``/``group_key`` enable the superstep stacking stage on
    either. ``stall_deadline_s`` arms the threaded stager's watchdog
    beacon (None = the ``BIGDL_TPU_STALL_S`` default); the serial
    stager runs inline under the caller's own beacon."""
    if depth >= 2:
        return BatchStager(source, stage_fn, depth=depth, name=name,
                           group=group, group_fn=group_fn,
                           group_key=group_key,
                           stall_deadline_s=stall_deadline_s)
    return _SerialStager(source, stage_fn, group=group, group_fn=group_fn,
                         group_key=group_key)


def stager_threads_alive() -> int:
    """Live stager worker threads (tests assert 0 after shutdown)."""
    return sum(1 for t in threading.enumerate()
               if t.name == THREAD_NAME and t.is_alive())


def place_host_value(x):
    """Table-aware host→device placement — the ONE spelling shared by the
    optimizer/evaluator/predictor stage functions, so a future placement
    change (pinned buffers, explicit shardings) lands everywhere at once."""
    import jax
    import jax.numpy as jnp
    from ..utils.table import Table
    if x is None:
        return None
    return (jax.tree_util.tree_map(jnp.asarray, x)
            if isinstance(x, Table) else jnp.asarray(x))
