"""Triggers (parity: reference ``optim/Trigger.scala``).

A trigger is a predicate over the optimizer state table
{'epoch', 'neval', 'epoch_finished', 'score', 'loss'}.
"""
from __future__ import annotations


class Trigger:
    def __init__(self, fn):
        self._fn = fn

    def __call__(self, state) -> bool:
        return bool(self._fn(state))

    def probe(self, state) -> bool:
        """Side-effect-free preview: would this trigger fire at ``state``?
        Used by superstep boundary clamping (the optimizer simulates the
        next K iteration counters to size a dispatch so it never
        straddles a firing point). The state dict is copied so the
        predicate cannot mutate the caller's live table; stateful
        triggers override this to avoid advancing their own bookkeeping."""
        return bool(self._fn(dict(state)))


class _EveryEpoch(Trigger):
    """Fires when an epoch boundary was just crossed (Trigger.scala:37)."""

    def __init__(self):
        self.last_epoch = -1

        def fn(state):
            if state.get("epoch_finished", False):
                if state["epoch"] != self.last_epoch:
                    self.last_epoch = state["epoch"]
                    return True
            return False
        super().__init__(fn)

    def probe(self, state) -> bool:
        # pure: does NOT advance last_epoch (mid-superstep probes carry
        # epoch_finished=False, so this is False everywhere the clamp asks)
        return bool(state.get("epoch_finished", False)) and \
            state["epoch"] != self.last_epoch


class _SeveralIteration(Trigger):
    def __init__(self, interval: int):
        super().__init__(lambda s: s["neval"] > 0 and
                         s["neval"] % interval == 0)


def every_epoch():
    return _EveryEpoch()


def several_iteration(interval: int):
    return _SeveralIteration(interval)


def max_epoch(maximum: int):
    return Trigger(lambda s: s["epoch"] > maximum)


def max_iteration(maximum: int):
    return Trigger(lambda s: s["neval"] >= maximum)


def max_score(maximum: float):
    return Trigger(lambda s: s.get("score", float("-inf")) > maximum)


def min_loss(minimum: float):
    return Trigger(lambda s: s.get("loss", float("inf")) < minimum)


def and_(first, *others):
    return Trigger(lambda s: first(s) and all(o(s) for o in others))


def or_(first, *others):
    return Trigger(lambda s: first(s) or any(o(s) for o in others))


# reference-style namespace: Trigger.everyEpoch etc.
Trigger.every_epoch = staticmethod(every_epoch)
Trigger.several_iteration = staticmethod(several_iteration)
Trigger.max_epoch = staticmethod(max_epoch)
Trigger.max_iteration = staticmethod(max_iteration)
Trigger.max_score = staticmethod(max_score)
Trigger.min_loss = staticmethod(min_loss)
Trigger.and_ = staticmethod(and_)
Trigger.or_ = staticmethod(or_)


# pyspark API spellings (reference pyspark/bigdl/optim/optimizer.py:
# EveryEpoch/SeveralIteration/MaxEpoch/MaxIteration/MaxScore/MinLoss/
# TriggerAnd/TriggerOr construct the same trigger objects)
EveryEpoch = every_epoch
SeveralIteration = several_iteration
MaxEpoch = max_epoch
MaxIteration = max_iteration
MaxScore = max_score
MinLoss = min_loss
TriggerAnd = and_
TriggerOr = or_
