"""Validation methods and results.

Parity: reference ``optim/ValidationMethod.scala`` (Top1Accuracy,
Top5Accuracy, Loss, MAE, HitRatio, NDCG, TreeNNAccuracy) and
``optim/EvaluateMethods.scala``. Results merge with ``+`` across batches
(and across mesh shards in DistriValidator).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np


class ValidationResult:
    def result(self):
        raise NotImplementedError

    def __add__(self, other):
        raise NotImplementedError


class AccuracyResult(ValidationResult):
    def __init__(self, correct: int, count: int):
        self.correct, self.count = int(correct), int(count)

    def result(self):
        return (self.correct / max(self.count, 1), self.count)

    def __add__(self, other):
        return AccuracyResult(self.correct + other.correct,
                              self.count + other.count)

    def __repr__(self):
        acc, cnt = self.result()
        return f"Accuracy(correct: {self.correct}, count: {cnt}, " \
               f"accuracy: {acc})"

    def __eq__(self, other):
        return (self.correct, self.count) == (other.correct, other.count)


class LossResult(ValidationResult):
    def __init__(self, loss: float, count: int):
        self.loss, self.count = float(loss), int(count)

    def result(self):
        return (self.loss / max(self.count, 1), self.count)

    def __add__(self, other):
        return LossResult(self.loss + other.loss, self.count + other.count)

    def __repr__(self):
        return f"Loss(loss: {self.loss}, count: {self.count}, " \
               f"average: {self.result()[0]})"


class ContiguousResult(LossResult):
    pass


class ValidationMethod:
    """Apply to (output, target) of one batch → ValidationResult.

    Device-side accumulation protocol (ROADMAP open item #4): a method
    that overrides :meth:`device_stats` exposes its per-batch statistics
    as a small jit-traceable device vector; the Evaluator then keeps a
    RUNNING SUM of those vectors on device across the whole batch loop
    and reads the total back ONCE per epoch (instead of syncing
    output→host every batch for the numpy path). ``result_from_stats``
    turns the summed host vector back into a ValidationResult. Methods
    without an override (rank-based metrics like HitRatio/NDCG) keep the
    per-batch numpy path — the Evaluator falls back automatically."""

    def __call__(self, output, target):
        raise NotImplementedError

    def device_stats(self, output, target):
        """Traced under jit with device ``(output, target)``; return a
        1-D summable stats vector (float32), or leave unimplemented for
        the host path. Must agree with ``__call__``'s result when summed
        across batches and fed to ``result_from_stats``."""
        raise NotImplementedError

    def result_from_stats(self, stats) -> "ValidationResult":
        raise NotImplementedError

    def supports_device_stats(self) -> bool:
        return type(self).device_stats is not ValidationMethod.device_stats

    def __repr__(self):
        return type(self).__name__


def _to_class_pred(output):
    out = np.asarray(output)
    if out.ndim == 1:
        return out  # already class scores? treat as binary
    return np.argmax(out, axis=-1) + 1  # 1-based


def _target_classes(target, n_classes):
    """1-based class indices from either index targets or one-hot rows
    (the keras categorical_crossentropy path feeds one-hot; reference
    Top1Accuracy does the same 2-D discrimination,
    ValidationMethod.scala:183-190).

    A trailing dim equal to n_classes is NOT enough to call it one-hot —
    integer sequence labels shaped (B, T) with T == C would misread. Only
    rows that are actually indicator vectors (0/1 entries, row-sum 1)
    take the argmax path."""
    t = np.asarray(target)
    if t.ndim >= 2 and t.shape[-1] == n_classes and n_classes > 1:
        flat = t.reshape(-1, n_classes)
        is_01 = np.logical_or(flat == 0, flat == 1).all()
        if is_01 and np.all(flat.sum(-1) == 1):
            return np.argmax(flat, axis=-1) + 1
    return t.reshape(-1)


def _device_logits_targets(output, target):
    """Traced analog of the host reshape + ``_target_classes``
    discrimination: returns ``(logits (N, C), classes (N,))``. The
    one-hot-vs-index choice is made on STATIC shapes — the only case the
    host's additional 0/1 data check can decide is one where the index
    branch would be shape-inconsistent anyway (see _target_classes)."""
    out = output if output.ndim > 1 else output[None]
    out = out.reshape(-1, out.shape[-1])
    n_classes, n_rows = out.shape[-1], out.shape[0]
    t = target
    if t.ndim >= 2 and t.shape[-1] == n_classes and n_classes > 1 and \
            t.size // n_classes == n_rows:
        t = jnp.argmax(t.reshape(-1, n_classes), axis=-1) + 1
    else:
        t = t.reshape(-1)
    return out, t


class Top1Accuracy(ValidationMethod):
    """optim/ValidationMethod.scala:170."""

    def __call__(self, output, target):
        out = np.asarray(output)
        if out.ndim == 1:
            out = out[None]
        out = out.reshape(-1, out.shape[-1])  # (B*T..., C)
        t = _target_classes(target, out.shape[-1])
        pred = np.argmax(out, axis=-1) + 1
        correct = int(np.sum(pred == t.astype(np.int64)))
        return AccuracyResult(correct, t.size)

    def device_stats(self, output, target):
        out, t = _device_logits_targets(output, target)
        pred = jnp.argmax(out, axis=-1) + 1
        correct = jnp.sum(pred == t.astype(jnp.int32))
        return jnp.stack([correct.astype(jnp.float32),
                          jnp.float32(t.size)])

    def result_from_stats(self, stats):
        return AccuracyResult(int(stats[0]), int(stats[1]))

    def __repr__(self):
        return "Top1Accuracy"


class Top5Accuracy(ValidationMethod):
    """optim/ValidationMethod.scala:224."""

    def __call__(self, output, target):
        out = np.asarray(output)
        if out.ndim == 1:
            out = out[None]
        out = out.reshape(-1, out.shape[-1])  # (B*T..., C)
        t = _target_classes(target, out.shape[-1]).astype(np.int64)
        # stable sort: equal logits rank by class index on BOTH the host
        # and device paths (jnp.argsort is stable; numpy's default is
        # not), so device-accumulated Top5 agrees exactly with this one
        top5 = np.argsort(-out, axis=-1, kind="stable")[:, :5] + 1
        correct = int(np.sum(np.any(top5 == t[:, None], axis=-1)))
        return AccuracyResult(correct, t.size)

    def device_stats(self, output, target):
        out, t = _device_logits_targets(output, target)
        top5 = jnp.argsort(-out, axis=-1)[:, :5] + 1
        correct = jnp.sum(jnp.any(top5 == t.astype(jnp.int32)[:, None],
                                  axis=-1))
        return jnp.stack([correct.astype(jnp.float32),
                          jnp.float32(t.size)])

    def result_from_stats(self, stats):
        return AccuracyResult(int(stats[0]), int(stats[1]))

    def __repr__(self):
        return "Top5Accuracy"


class Loss(ValidationMethod):
    """optim/ValidationMethod.scala:475 — average criterion loss."""

    def __init__(self, criterion=None):
        if criterion is None:
            from ..nn.criterion import ClassNLLCriterion
            criterion = ClassNLLCriterion()
        self.criterion = criterion

    def __call__(self, output, target):
        l = float(self.criterion._forward(jnp.asarray(output),
                                          jnp.asarray(target)))
        n = np.asarray(output).shape[0]
        return LossResult(l * n, n)

    def device_stats(self, output, target):
        l = self.criterion._forward(output, target)
        n = output.shape[0]
        return jnp.stack([l.astype(jnp.float32) * n, jnp.float32(n)])

    def result_from_stats(self, stats):
        return LossResult(float(stats[0]), int(stats[1]))

    def __repr__(self):
        return "Loss"


class MAE(ValidationMethod):
    """optim/ValidationMethod.scala:500 — mean absolute error."""

    def __call__(self, output, target):
        out = np.asarray(output)
        t = np.asarray(target)
        l = float(np.mean(np.abs(out - t)))
        n = out.shape[0]
        return LossResult(l * n, n)

    def device_stats(self, output, target):
        l = jnp.mean(jnp.abs(output - target))
        n = output.shape[0]
        return jnp.stack([l.astype(jnp.float32) * n, jnp.float32(n)])

    def result_from_stats(self, stats):
        return LossResult(float(stats[0]), int(stats[1]))

    def __repr__(self):
        return "MAE"


def _device_pos_ranks(output, target):
    """The sorted-scores rank formulation shared by the on-device
    HitRatio/NDCG stats (ROADMAP: "a sorted-scores formulation could
    move them on-device"): instead of the host path's per-positive
    O(N) scan (``sum(out > p)``), sort the scores ONCE and read each
    element's strictly-greater count off ``searchsorted`` —
    O(N log N) total, fully traced, no data-dependent shapes. Returns
    (pos_mask (N,), rank (N,) 1-based) matching the host arithmetic
    exactly (strict > comparison, ties share a rank)."""
    out = output.reshape(-1).astype(jnp.float32)
    t = target.reshape(-1)
    pos = t > 0.5
    asc = jnp.sort(out)
    n_greater = out.size - jnp.searchsorted(asc, out, side="right")
    return pos, n_greater + 1


class HitRatio(ValidationMethod):
    """optim/ValidationMethod.scala:279 — HR@k for recommendation: each row of
    output scores 1 positive + negNum negatives; target marks the positive."""

    def __init__(self, k: int = 10, neg_num: int = 100):
        self.k, self.neg_num = k, neg_num

    def __call__(self, output, target):
        out = np.asarray(output).reshape(-1)
        t = np.asarray(target).reshape(-1)
        pos = out[t > 0.5]
        hits = 0.0
        count = 0
        for p in np.atleast_1d(pos):
            rank = int(np.sum(out > p)) + 1
            hits += 1.0 if rank <= self.k else 0.0
            count += 1
        return AccuracyResult(int(hits), max(count, 1))

    def device_stats(self, output, target):
        pos, rank = _device_pos_ranks(output, target)
        hits = jnp.sum(jnp.where(pos & (rank <= self.k), 1.0, 0.0))
        return jnp.stack([hits, jnp.sum(pos.astype(jnp.float32))])

    def result_from_stats(self, stats):
        # count clamps at the AGGREGATE (the host path clamps per batch;
        # they differ only for positive-free batches, which the ranking
        # protocol — one positive per candidate list — never produces)
        return AccuracyResult(int(stats[0]), max(int(stats[1]), 1))

    def __repr__(self):
        return f"HitRate@{self.k}"


class NDCG(ValidationMethod):
    """optim/ValidationMethod.scala:346 — NDCG@k, same setup as HitRatio."""

    def __init__(self, k: int = 10, neg_num: int = 100):
        self.k, self.neg_num = k, neg_num

    def __call__(self, output, target):
        out = np.asarray(output).reshape(-1)
        t = np.asarray(target).reshape(-1)
        pos = out[t > 0.5]
        total = 0.0
        count = 0
        for p in np.atleast_1d(pos):
            rank = int(np.sum(out > p)) + 1
            total += float(np.log(2) / np.log(rank + 1)) if rank <= self.k \
                else 0.0
            count += 1
        r = LossResult(total, max(count, 1))
        return r

    def device_stats(self, output, target):
        pos, rank = _device_pos_ranks(output, target)
        # f32 log vs the host's f64: the summed gain agrees to ~1e-6
        # relative — the device path trades the last float digits for
        # zero per-batch readbacks (see Evaluator._evaluate_device)
        gain = jnp.where(rank <= self.k,
                         jnp.log(2.0) / jnp.log(rank.astype(jnp.float32)
                                                + 1.0), 0.0)
        total = jnp.sum(jnp.where(pos, gain, 0.0))
        return jnp.stack([total, jnp.sum(pos.astype(jnp.float32))])

    def result_from_stats(self, stats):
        return LossResult(float(stats[0]), max(int(stats[1]), 1))

    def __repr__(self):
        return f"NDCG@{self.k}"


class TreeNNAccuracy(ValidationMethod):
    """optim/ValidationMethod.scala:118 — accuracy on the root (last)
    prediction of a tree/sequence output."""

    def __call__(self, output, target):
        out = np.asarray(output)
        if out.ndim == 3:
            out = out[:, 0, :]
        t = np.asarray(target)
        if t.ndim >= 2:
            t = t[:, 0]
        if out.shape[-1] == 1:  # binary head: threshold at 0.5 (reference)
            pred = (out[..., 0] >= 0.5).astype(np.int64)
        else:
            pred = np.argmax(out, axis=-1) + 1
        correct = int(np.sum(pred == t.reshape(-1).astype(np.int64)))
        return AccuracyResult(correct, t.size)

    def __repr__(self):
        return "TreeNNAccuracy"
