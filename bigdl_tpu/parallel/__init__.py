from .mesh import (make_mesh, make_hybrid_mesh, data_parallel_mesh, get_default_mesh,
                   set_default_mesh, axis_size)
from .collective import (all_reduce_sum, all_reduce_mean, all_gather,
                         reduce_scatter, ppermute_ring, all_to_all, psum,
                         pmean)
from .allreduce import AllReduceParameter, FP16CompressPolicy
from .sharding import (replicated, data_sharding, shard_batch, shard_params,
                       tp_linear_rules, transformer_tp_specs, fsdp_specs,
                       surviving_devices, mesh_after_loss,
                       serving_batch_spec, serving_param_specs,
                       place_with_specs, batch_shard_count,
                       SERVING_BATCH_AXES)
from .ring_attention import ring_attention
from .failure import (probe_mesh, MeshProbeResult, Heartbeat, HeartbeatLost,
                      StragglerMonitor, TransientDeviceError, TrainingHalted,
                      FaultPolicy, classify_failure, TRANSIENT, PERMANENT)
from . import chaos
from .chaos import ChaosError, ChaosPlan
from .elastic import (ElasticRunner, find_latest_checkpoint,
                      data_parallel_factory)
from .pipeline import gpipe, stack_stage_params, unstack_stage_params
from .moe import moe_ffn, top1_routing
from .ring_flash import ring_flash_attention, make_ring_flash_attention
from .seq_all_to_all import a2a_attention
from .seq_decode import make_seq_sharded_decoder
from .allreduce import sparse_embedding_grad_allreduce
