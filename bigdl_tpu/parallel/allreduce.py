"""Sharded parameter aggregation — the TPU-native AllReduceParameter.

Parity: reference ``parameters/AllReduceParameter.scala`` +
``parameters/FP16CompressedTensor.scala`` + ``optim/ParallelOptimizer``'s
sharded update. The reference's design: the flat parameter vector is split
into N slices, one per partition; each node ships gradient slices to slice
owners (Spark shuffle), owners aggregate, run the OptimMethod on their slice,
and broadcast updated weights back.

TPU-native realisation of the *same* dataflow, as one compiled program:

* flatten params to one contiguous vector (``ravel_pytree`` — the analog of
  the reference's compacted getParameters storage), pad to a multiple of the
  mesh ``data`` axis;
* inside ``shard_map``: ``psum_scatter`` the local gradient vector → each
  device holds the *aggregated* gradient for its own 1/N slice (this is the
  shuffle+aggregate, done by the ICI all-reduce-scatter hardware op);
* run the OptimMethod update on the slice (ZeRO-1: optimizer state lives only
  sharded — N× memory saving, the same saving ParallelAdam chases);
* ``all_gather`` the updated slices back to the full replicated vector.

Wire compression parity: FP16CompressedTensor halves network bytes. Two
knobs, both off by default:

* ``compress="bf16"/"fp16"`` (legacy) — the gradient is cast before the
  ``psum_scatter``, halving ICI bytes; the hardware reduce ACCUMULATES
  in the wire dtype (accumulation error grows with the shard count);
* ``wire_dtype="bf16"/"fp16"`` — the faithful FP16CompressedTensor
  dataflow: each device ships its COMPRESSED per-owner gradient slices
  (``all_to_all`` — same wire bytes as the reduce-scatter, each device
  sends the full vector once), and the slice OWNER decompresses and
  sums in f32 — fp32 master accumulation regardless of the wire dtype,
  exactly the reference's "workers send fp16, owner aggregates in
  full precision". The optimizer update and the weight ``all_gather``
  stay f32 (master weights uncompressed), so only the gradient leg is
  rounded. Per-dispatch byte accounting
  (``collective/grad_wire_traced_bytes``) proves the ~2x cut; the
  ulp-equivalence harness in tests/test_distributed.py pins the math.
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from ..utils.compat import axis_size, shard_map
from jax.sharding import Mesh, PartitionSpec as P

from jax.flatten_util import ravel_pytree

from .. import observability as obs


class FP16CompressPolicy:
    """Gradient wire-compression policies (parity: FP16CompressedTensor)."""
    NONE = "none"
    BF16 = "bf16"
    FP16 = "fp16"

    @staticmethod
    def compress(x, policy):
        if policy == FP16CompressPolicy.BF16:
            return x.astype(jnp.bfloat16)
        if policy == FP16CompressPolicy.FP16:
            return x.astype(jnp.float16)
        return x

    @staticmethod
    def decompress(x, dtype):
        return x.astype(dtype)


class FlatParameter:
    """Contiguous flat view of a params pytree (parity: Module.getParameters
    compacting into one Storage)."""

    def __init__(self, params, n_shards: int):
        flat, self.unravel = ravel_pytree(params)
        self.orig_size = flat.shape[0]
        self.n_shards = n_shards
        pad = (-self.orig_size) % n_shards
        self.padded_size = self.orig_size + pad
        self.shard_size = self.padded_size // n_shards

    def flatten(self, tree):
        flat, _ = ravel_pytree(tree)
        return jnp.pad(flat, (0, self.padded_size - self.orig_size))

    def unflatten(self, flat):
        return self.unravel(flat[: self.orig_size])


class AllReduceParameter:
    """ZeRO-1-style sharded optimizer update over a mesh ``data`` axis."""

    def __init__(self, optim_method, mesh: Mesh, axis: str = "data",
                 compress: str = FP16CompressPolicy.NONE,
                 wire_dtype: str = FP16CompressPolicy.NONE):
        """``compress``: legacy wire compression — the psum_scatter runs
        (and ACCUMULATES) in the compressed dtype. ``wire_dtype``: the
        fp32-master-accumulation wire (module docstring) — compressed
        slices travel, the owner sums in f32. Mutually exclusive; both
        off by default."""
        valid = (FP16CompressPolicy.NONE, FP16CompressPolicy.BF16,
                 FP16CompressPolicy.FP16)
        if compress not in valid or wire_dtype not in valid:
            raise ValueError(f"compress/wire_dtype must be one of {valid}, "
                             f"got {compress!r}/{wire_dtype!r}")
        if compress != FP16CompressPolicy.NONE \
                and wire_dtype != FP16CompressPolicy.NONE:
            raise ValueError(
                "compress= and wire_dtype= are two implementations of the "
                "same wire — set one (wire_dtype keeps f32 accumulation "
                "and is the one to prefer)")
        self.optim = optim_method
        self.mesh = mesh
        self.axis = axis
        self.compress = compress
        self.wire_dtype = wire_dtype
        self.n = mesh.shape[axis]
        self.flat: Optional[FlatParameter] = None

    def prepare(self, params, resume_state=None):
        """Build the flat view and the sharded optimizer state.

        ``resume_state``: a CANONICAL host optimizer-state tree (see
        :meth:`state_to_canonical`) from a checkpoint — possibly written
        under a *different* mesh shape. Vector state is re-flattened and
        re-padded against THIS mesh's shard boundaries, so a checkpoint
        saved under N-way ZeRO-1 restores bitwise onto N', including
        after an elastic mesh reshape. ``None`` (fresh run) initializes
        the per-slice state on device as before."""
        self.flat = FlatParameter(params, self.n)
        flat_w = self.flat.flatten(params)
        if obs.enabled():
            # per-step per-device wire budget: the gradient leg
            # (psum_scatter or all_to_all — either way each device ships
            # the full, possibly compressed, vector once) plus the
            # all_gather shipping the updated f32 weight slices back
            wire = (self.wire_dtype
                    if self.wire_dtype != FP16CompressPolicy.NONE
                    else self.compress)
            gbytes = 2 if wire in (FP16CompressPolicy.BF16,
                                   FP16CompressPolicy.FP16) else 4
            obs.gauge("allreduce/param_elems").set(self.flat.orig_size)
            obs.gauge("allreduce/shard_elems").set(self.flat.shard_size)
            obs.gauge("allreduce/bytes_per_step", unit="B").set(
                self.flat.padded_size * (gbytes + 4))
            obs.gauge("allreduce/n_shards").set(self.n)

        if resume_state is not None:
            return flat_w, self.place_canonical_state(resume_state)

        def init_slice(w_full):
            i = lax.axis_index(self.axis)
            sl = lax.dynamic_slice_in_dim(w_full, i * self.flat.shard_size,
                                          self.flat.shard_size)
            return self.optim.init_state(sl)

        specs_in = P()
        init = shard_map(init_slice, mesh=self.mesh, in_specs=(specs_in,),
                         out_specs=self.state_specs(),
                         check_vma=False)
        return flat_w, init(flat_w)

    def _slice_state_shapes(self):
        """Shape witness for the PER-SLICE optimizer state: which outer
        leaves are flat parameter vectors (ndim >= 1) vs replicated
        scalars (step counters). The canonical<->sharded conversions
        walk this structure with ``tree_map``, which flattens the other
        tree UP TO this one's leaves — so a canonical tree may hold a
        whole params-shaped subtree where the witness has one vector
        leaf."""
        return jax.eval_shape(
            self.optim.init_state,
            jax.ShapeDtypeStruct((self.flat.shard_size,), jnp.float32))

    def state_to_canonical(self, gathered_state):
        """Gathered host optimizer state (flat ``[padded]`` vectors per
        THIS mesh's padding) -> the canonical mesh-shape-agnostic form:
        each vector leaf unflattened into a params-shaped subtree,
        scalars untouched. This is the form checkpoints store — it
        carries no shard-boundary provenance, so any future mesh shape
        (including LocalOptimizer's unsharded state) restores from it."""
        def canon(shape_leaf, leaf):
            if shape_leaf.ndim >= 1:
                return jax.tree_util.tree_map(
                    np.asarray, self.flat.unflatten(np.asarray(leaf)))
            return np.asarray(leaf)
        return jax.tree_util.tree_map(canon, self._slice_state_shapes(),
                                      gathered_state)

    def state_from_canonical(self, canonical):
        """Canonical host state -> full flat vectors padded to THIS
        mesh's boundaries (host-side; caller places them with
        :meth:`state_specs`). Also accepts legacy flat-vector leaves
        (pre-canonical checkpoints): they are trimmed to the true
        parameter count and re-padded for the new shard count."""
        def widen(shape_leaf, sub):
            if shape_leaf.ndim >= 1:
                if hasattr(sub, "ndim") and getattr(sub, "ndim", 0) >= 1:
                    vec = jnp.asarray(np.asarray(sub).ravel()
                                      [: self.flat.orig_size])
                    return jnp.pad(
                        vec, (0, self.flat.padded_size - vec.shape[0]))
                return self.flat.flatten(sub)
            return jnp.asarray(sub)
        return jax.tree_util.tree_map(widen, self._slice_state_shapes(),
                                      canonical)

    def place_canonical_state(self, canonical):
        """Canonical host state → device-placed state sharded for THIS
        mesh: widen to the current shard boundaries
        (:meth:`state_from_canonical`) and place each leaf per
        :meth:`state_specs`. The single placement path both fresh
        restores (``prepare(resume_state=...)``) and the optimizer's
        mid-run restore (nan-resume, Tier-2 replay, elastic resume)
        share — the two must never drift."""
        from .sharding import put_global
        full = self.state_from_canonical(canonical)
        return jax.tree_util.tree_map(
            lambda a, sp: put_global(a, self.mesh, sp),
            full, self.state_specs())

    def state_specs(self):
        """Per-leaf PartitionSpecs for the sharded optimizer state: vector
        state sharded over the axis, scalar state (step counters) replicated."""
        shapes = jax.eval_shape(
            lambda w: self.optim.init_state(w[: self.flat.shard_size]),
            jnp.zeros((self.flat.padded_size,), jnp.float32))
        return jax.tree_util.tree_map(
            lambda s: P(self.axis) if s.ndim >= 1 else P(), shapes)

    def update(self, grads_flat, params_flat, opt_state, lr,
               traced_steps: int = 1):
        """Runs INSIDE shard_map over the mesh: grads_flat/params_flat are
        the full (replicated) vectors on each device; opt_state is the local
        slice. Returns (new full params, new state slice).

        ``traced_steps``: how many times this traced body executes per
        dispatch (K under a superstep ``lax.scan`` — the body traces once
        but the hardware reduce-scatter runs every scan iteration), so the
        trace-time byte counter stays an honest per-dispatch wire total."""
        i = lax.axis_index(self.axis)
        dtype = grads_flat.dtype
        if self.wire_dtype != FP16CompressPolicy.NONE:
            # fp32-master-accumulation wire: ship each owner its
            # COMPRESSED slice (all_to_all — the same per-device wire
            # bytes as a reduce-scatter of the compressed vector), then
            # the owner decompresses and sums in f32. The wire is
            # rounded once; the accumulation never is.
            g = FP16CompressPolicy.compress(grads_flat, self.wire_dtype)
            if obs.enabled():
                # trace-time accounting: bytes each device sends on the
                # gradient leg of one dispatch
                obs.counter("collective/grad_wire_traced_bytes",
                            unit="B").inc(
                    float(g.size * g.dtype.itemsize) * traced_steps)
            pieces = lax.all_to_all(
                g.reshape(self.n, self.flat.shard_size), self.axis,
                split_axis=0, concat_axis=0)
            gslice = jnp.sum(
                FP16CompressPolicy.decompress(pieces, dtype), axis=0
            ) / self.n
        else:
            g = FP16CompressPolicy.compress(grads_flat, self.compress)
            if obs.enabled():
                # trace-time accounting (this body runs under jit, once
                # per compile): bytes entering the hardware reduce-scatter
                obs.counter("collective/reduce_scatter_traced_bytes",
                            unit="B").inc(
                    float(g.size * g.dtype.itemsize) * traced_steps)
            # aggregated gradient for my slice (mean over data shards)
            gslice = lax.psum_scatter(g, self.axis, scatter_dimension=0,
                                      tiled=True)
            gslice = FP16CompressPolicy.decompress(gslice, dtype) / self.n
        wslice = lax.dynamic_slice_in_dim(
            params_flat, i * self.flat.shard_size, self.flat.shard_size)
        new_slice, new_state = self.optim.update(gslice, wslice, opt_state, lr)
        new_full = lax.all_gather(new_slice, self.axis, tiled=True)
        return new_full, new_state


def sparse_embedding_grad_allreduce(ids, row_grads, vocab_size: int,
                                    axis: str, mean: bool = True,
                                    traced_steps: int = 1):
    """Sparsity-aware embedding-gradient aggregation (Parallax,
    arXiv:1808.02621 — PAPERS.md): data-parallel shards exchange the
    (token ids, gradient rows) pairs instead of the dense (vocab, H)
    gradient, then scatter-add locally.

    Wire cost per device: n * B_local * (H + 1) elements over ICI
    (all_gather of the touched rows) versus vocab * H for a dense psum —
    the win for recommender/LM embedding tables where the batch touches
    a tiny fraction of the vocabulary (reference analog: the pyspark
    LookupTable's sparse gradient path on parameter servers).

    Runs INSIDE shard_map over ``axis``. ids: (B,) int local token ids
    (flatten (B, T) inputs first); row_grads: (B, H) local per-token
    gradient rows (dL/d(embed[id])). Returns the aggregated dense
    (vocab_size, H) gradient, identical on every device — the same
    result a dense ``psum`` of per-device scatter-adds would give.
    ``mean=True`` divides by the axis size (matching grad-mean data
    parallelism). ``traced_steps``: executions of this traced body per
    dispatch (K under a superstep scan), keeping the trace-time byte
    counter an honest per-dispatch wire total — the same convention as
    :meth:`AllReduceParameter.update`."""
    if obs.enabled():
        # trace-time accounting: bytes each device sends on this
        # exchange — the (indices, values) legs of the two all_gathers
        obs.counter("collective/sparse_grad_wire_traced_bytes",
                    unit="B").inc(
            float(ids.size * 4
                  + row_grads.size * row_grads.dtype.itemsize)
            * traced_steps)
    all_ids = lax.all_gather(ids.astype(jnp.int32), axis, tiled=True)
    all_rows = lax.all_gather(row_grads, axis, tiled=True)
    dense = jnp.zeros((vocab_size, row_grads.shape[-1]),
                      row_grads.dtype).at[all_ids].add(all_rows)
    if mean:
        dense = dense / axis_size(axis)
    return dense
