"""Deterministic fault-injection plane (ISSUE 13).

A recovery path that has only ever seen the failure its author imagined
is not a recovery path — it is a hope. The TensorFlow system paper
treats *injected*-failure recovery as a design obligation, and the
reference BigDL inherited Spark's task-rerun model precisely so faults
were routine; this module gives the TPU-native stack the same
discipline: a process-global registry of **named injection sites**
threaded through the real seams of the system —

============================  ==============================================
site                          where it fires
============================  ==============================================
``serving/scheduler_step``    DecodeScheduler decode-group dispatch
``serving/prefill``           DecodeScheduler prefill-chunk dispatch
``serving/spec_round``        DecodeScheduler speculative round
``serving/engine_dispatch``   ServingEngine micro-batch forward
``kv/page_copy``              PagedKVCache.defrag page move
``kv/cow_fork``               PagedKVCache.fork_blocks copy-on-write
``kv/swap_out``               KVSwapManager host-RAM spill fetch (stager)
``kv/swap_in``                KVSwapManager refill verify + adopt
``prefix/insert``             PrefixCache.insert (index registration)
``prefix/evict``              PrefixCache.evict (reclaim under pressure)
``router/dispatch``           Router replica submit
``checkpoint/write``          optimizer ``_atomic_pickle`` snapshot write
``heartbeat/beat``            failure.Heartbeat.beat exchange
``fleet/agent_beat``          fleet.ReplicaAgent membership beat loop
``fleet/transport``           fleet transport client send
``fleet/handoff``             fleet prefill-export / decode-adopt KV handoff
``fleet/controller_tick``     controller.FleetController reconcile tick
``fleet/spawn``               controller replica spawn (scale-up launch)
============================  ==============================================

— with **seeded, deterministic schedules** (nth-call, every-k,
seeded-probability, wedge-for-duration) and **typed fault kinds**
reusing :func:`~.failure.classify_failure`'s taxonomy: a ``transient``
rule raises :class:`~.failure.TransientDeviceError` (the replay tiers
must absorb it), a ``permanent`` rule raises :class:`ChaosError` (whose
message deliberately matches no transient marker, so classification
lands PERMANENT — the halt/failover tiers must own it), and a ``wedge``
rule sleeps in place (the stall watchdog must page).

Disarmed cost is ONE module-global read per site — :func:`maybe_fire`
returns immediately when no plan is armed, so production hot loops pay
a single flag read (enforced by ``tools/check_no_sync.py``; there is no
per-call allocation, lock, or dict lookup on the disarmed path).

Arming::

    # programmatic (tests, tools/chaos_smoke.py)
    chaos.arm({"seed": 7, "sites": {
        "serving/scheduler_step": [
            {"kind": "transient", "every": 5, "max_fires": 4}],
        "router/dispatch": [
            {"kind": "transient", "nth": 3, "tag": "r1"}],
    }})
    ...
    chaos.disarm()

    # or from the environment (campaign files)
    BIGDL_TPU_CHAOS=/path/to/plan.json python serve.py

Rules carry an optional ``tag`` filter matched against the tag the call
site passes (replica names, usually) — ``{"kind": "permanent", "nth":
6, "tag": "r0"}`` kills replica ``r0``'s sixth step and nobody else's.
Each rule keeps its OWN call counter over the calls its tag matches, so
two interleaved replicas cannot skew each other's schedules. Every
injection is counted (:func:`stats`, :func:`fires`) and emitted as a
``health/chaos_injected`` event, which is how the campaign gates in
``make chaos-smoke`` prove the faults actually landed. See
docs/RESILIENCE.md "Serving faults".
"""
from __future__ import annotations

import json
import logging
import os
import random
import threading
import time
from typing import Dict, List, Optional

from .failure import PERMANENT, TRANSIENT, TransientDeviceError

_LOG = logging.getLogger("bigdl_tpu.parallel.chaos")

#: the schedule kind that sleeps in place instead of raising — the
#: injected analog of a wedged collective/device copy (the stall
#: watchdog, not the retry tiers, owns this failure mode)
WEDGE = "wedge"

KINDS = (TRANSIENT, PERMANENT, WEDGE)

#: canonical site catalog (call sites may use others — the registry is
#: open — but the documented campaign surface is this list)
SITES = (
    "serving/scheduler_step",
    "serving/prefill",
    "serving/spec_round",
    "serving/engine_dispatch",
    "kv/page_copy",
    "kv/cow_fork",
    "kv/swap_out",
    "kv/swap_in",
    "prefix/insert",
    "prefix/evict",
    "router/dispatch",
    "checkpoint/write",
    "heartbeat/beat",
    "fleet/agent_beat",
    "fleet/transport",
    "fleet/handoff",
    "fleet/controller_tick",
    "fleet/spawn",
)


class ChaosError(RuntimeError):
    """An injected PERMANENT fault. The message carries none of the
    transient gRPC/absl markers, so ``classify_failure`` maps it to
    PERMANENT by the unknown-error default — exactly the class a dead
    chip or a wedged mesh presents as."""


class Rule:
    """One injection rule at one site.

    Parameters
    ----------
    kind : ``"transient"`` | ``"permanent"`` | ``"wedge"``.
    nth : fire ONCE, at the first matching call >= nth (1-based). The
        at-or-after semantics matter when two rules at one site want
        the same call: only the first takes effect that call, and the
        suppressed nth rule then fires on the NEXT call instead of
        being starved forever.
    every : fire on every ``every``-th matching call.
    prob : fire with this probability per matching call, drawn from the
        plan's seeded stream (deterministic for a fixed seed AND a fixed
        call interleaving — prefer nth/every for bitwise campaigns).
    wedge_s : sleep duration for ``kind="wedge"``.
    max_fires : stop firing after this many injections (None = no cap).
    tag : only calls passing this tag match (None matches every call) —
        how a campaign targets one replica of a fleet.
    """

    __slots__ = ("kind", "nth", "every", "prob", "wedge_s", "max_fires",
                 "tag", "calls", "fired")

    def __init__(self, kind: str = TRANSIENT, nth: Optional[int] = None,
                 every: Optional[int] = None, prob: Optional[float] = None,
                 wedge_s: float = 0.0, max_fires: Optional[int] = None,
                 tag: Optional[str] = None):
        if kind not in KINDS:
            raise ValueError(f"kind must be one of {KINDS}, got {kind!r}")
        if sum(x is not None for x in (nth, every, prob)) != 1:
            raise ValueError(
                "exactly one of nth/every/prob must be set per rule")
        if nth is not None and nth < 1:
            raise ValueError(f"nth must be >= 1, got {nth}")
        if every is not None and every < 1:
            raise ValueError(f"every must be >= 1, got {every}")
        if prob is not None and not 0.0 < prob <= 1.0:
            raise ValueError(f"prob must be in (0, 1], got {prob}")
        if kind == WEDGE and wedge_s <= 0:
            raise ValueError("wedge rules need wedge_s > 0")
        self.kind = kind
        self.nth = nth
        self.every = every
        self.prob = prob
        self.wedge_s = float(wedge_s)
        self.max_fires = max_fires
        self.tag = tag
        self.calls = 0
        self.fired = 0

    def matches(self, tag: Optional[str]) -> bool:
        return self.tag is None or self.tag == tag

    def should_fire(self, rng: random.Random) -> bool:
        """Advance this rule's call counter and decide (caller holds the
        engine lock). ``fired`` counts EFFECTIVE injections only — a
        rule that wanted a call another rule took keeps its budget and
        (for nth) its one shot."""
        self.calls += 1
        if self.max_fires is not None and self.fired >= self.max_fires:
            return False
        if self.nth is not None:
            return self.calls >= self.nth and self.fired == 0
        if self.every is not None:
            return self.calls % self.every == 0
        return rng.random() < self.prob

    @classmethod
    def from_dict(cls, d: Dict) -> "Rule":
        allowed = {"kind", "nth", "every", "prob", "wedge_s", "max_fires",
                   "tag"}
        unknown = set(d) - allowed
        if unknown:
            raise ValueError(f"unknown rule keys {sorted(unknown)} "
                             f"(allowed: {sorted(allowed)})")
        return cls(**d)


class ChaosPlan:
    """A seeded campaign: ``{site: [Rule, ...]}`` plus the RNG seed the
    probability schedules draw from."""

    def __init__(self, sites: Dict[str, List[Rule]], seed: int = 0):
        self.seed = int(seed)
        self.sites = {str(s): list(rules) for s, rules in sites.items()}

    @classmethod
    def from_dict(cls, d: Dict) -> "ChaosPlan":
        sites = {}
        for site, rules in (d.get("sites") or {}).items():
            sites[site] = [r if isinstance(r, Rule) else Rule.from_dict(r)
                           for r in rules]
        return cls(sites, seed=d.get("seed", 0))

    @classmethod
    def from_json(cls, path: str) -> "ChaosPlan":
        with open(path) as f:
            return cls.from_dict(json.load(f))


class _Engine:
    """The armed plan: per-site rule lists, one seeded RNG, counters."""

    def __init__(self, plan: ChaosPlan):
        self.plan = plan
        self._rng = random.Random(plan.seed)
        self._lock = threading.Lock()
        self._fires: List[Dict] = []        # bounded injection LOG
        self._calls: Dict[str, int] = {}
        # exact counters, never truncated — the campaign gates read
        # these, the log is a debugging convenience
        self._total = 0
        self._by_site: Dict[str, int] = {}
        self._by_kind: Dict[str, int] = {}

    def fire(self, site: str, tag: Optional[str]):
        rule = None
        with self._lock:
            self._calls[site] = self._calls.get(site, 0) + 1
            call_no = self._calls[site]
            for r in self.plan.sites.get(site, ()):
                if not r.matches(tag):
                    continue
                if r.should_fire(self._rng) and rule is None:
                    r.fired += 1
                    rule = r
            if rule is not None:
                self._total += 1
                self._by_site[site] = self._by_site.get(site, 0) + 1
                self._by_kind[rule.kind] = \
                    self._by_kind.get(rule.kind, 0) + 1
                if len(self._fires) < 4096:
                    self._fires.append({"site": site, "kind": rule.kind,
                                        "tag": tag, "call": call_no})
        if rule is None:
            return
        # structured provenance for the campaign gates: every injection
        # is observable (health listeners work with observability off)
        from ..observability import health as _health
        _health.emit("chaos_injected", site=site, fault=rule.kind,
                     tag=tag, call=call_no)
        if rule.kind == WEDGE:
            _LOG.warning("chaos: wedging %.2fs at %s (tag=%s, call %d)",
                         rule.wedge_s, site, tag, call_no)
            time.sleep(rule.wedge_s)
            return
        msg = (f"chaos: injected {rule.kind} fault at {site} "
               f"(tag={tag}, call {call_no})")
        _LOG.warning("%s", msg)
        if rule.kind == TRANSIENT:
            raise TransientDeviceError(msg)
        raise ChaosError(msg)

    def stats(self) -> Dict:
        with self._lock:
            return {"fires": self._total, "by_site": dict(self._by_site),
                    "by_kind": dict(self._by_kind),
                    "calls": dict(self._calls)}

    def fires(self) -> List[Dict]:
        """The injection log — bounded at 4096 entries (the exact
        counters in :meth:`stats` never truncate)."""
        with self._lock:
            return list(self._fires)


#: the single armed engine; None = disarmed (the hot-path flag)
_engine: Optional[_Engine] = None


def maybe_fire(site: str, tag: Optional[str] = None):
    """The hot-path seam. Disarmed: one module-global read, nothing
    else. Armed: evaluate this site's rules and inject the scheduled
    fault (raise typed / wedge in place)."""
    eng = _engine
    if eng is None:
        return
    eng.fire(site, tag)


def arm(plan) -> _Engine:
    """Install a plan process-wide. Accepts a :class:`ChaosPlan`, a
    plan dict, or a path to a plan JSON file. Re-arming replaces the
    previous plan (counters reset)."""
    global _engine
    if isinstance(plan, str):
        plan = ChaosPlan.from_json(plan)
    elif isinstance(plan, dict):
        plan = ChaosPlan.from_dict(plan)
    elif not isinstance(plan, ChaosPlan):
        raise TypeError(f"cannot arm a {type(plan).__name__}")
    _engine = _Engine(plan)
    _LOG.warning("chaos armed: %d sites, seed=%d",
                 len(plan.sites), plan.seed)
    return _engine


def disarm():
    """Remove the armed plan (maybe_fire returns to the one-flag-read
    no-op)."""
    global _engine
    _engine = None


def armed() -> bool:
    return _engine is not None


def stats() -> Dict:
    """Injection accounting for the armed plan ({} when disarmed)."""
    eng = _engine
    return eng.stats() if eng is not None else {}


def fires() -> List[Dict]:
    """The injection log: [{site, kind, tag, call}, ...]."""
    eng = _engine
    return eng.fires() if eng is not None else []


def sites_fired() -> List[str]:
    """Distinct sites that have injected at least one fault — the
    campaign-breadth gate (``make chaos-smoke`` demands >= 5)."""
    return sorted(stats().get("by_site", ()))


def arm_from_env(env=None) -> Optional[_Engine]:
    """Arm from ``BIGDL_TPU_CHAOS=<plan.json>`` when set (called once at
    import; exposed for tests). Malformed plans log and stay disarmed —
    a typo'd campaign file must not take production down harder than
    the faults it meant to inject."""
    env = env if env is not None else os.environ
    path = env.get("BIGDL_TPU_CHAOS")
    if not path:
        return None
    try:
        return arm(ChaosPlan.from_json(path))
    except Exception as e:  # noqa: BLE001 — stay disarmed, loudly
        _LOG.error("ignoring malformed BIGDL_TPU_CHAOS=%r: %s", path, e)
        return None


arm_from_env()
