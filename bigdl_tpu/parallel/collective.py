"""Collective wrappers for use inside ``shard_map``-ped functions.

Parity note: the reference implements gradient aggregation as a Spark shuffle
to per-partition owners (``parameters/AllReduceParameter.scala:putGradients``)
— a software parameter server. Here every collective is an XLA primitive that
lowers to ICI hardware collectives; these wrappers only fix axis-name plumbing.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def psum(x, axis: str = "data"):
    return lax.psum(x, axis_name=axis)


def pmean(x, axis: str = "data"):
    return lax.pmean(x, axis_name=axis)


def all_reduce_sum(tree, axis: str = "data"):
    return jax.tree_util.tree_map(lambda t: lax.psum(t, axis), tree)


def all_reduce_mean(tree, axis: str = "data"):
    return jax.tree_util.tree_map(lambda t: lax.pmean(t, axis), tree)


def all_gather(x, axis: str = "data", tiled: bool = True):
    return lax.all_gather(x, axis_name=axis, tiled=tiled)


def reduce_scatter(x, axis: str = "data", scatter_dimension: int = 0):
    return lax.psum_scatter(x, axis_name=axis,
                            scatter_dimension=scatter_dimension, tiled=True)


def ppermute_ring(x, axis: str = "data", shift: int = 1):
    """Rotate shards around the ring (basis of ring attention)."""
    n = lax.axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name=axis, perm=perm)


def all_to_all(x, axis: str, split_axis: int, concat_axis: int):
    return lax.all_to_all(x, axis_name=axis, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)
