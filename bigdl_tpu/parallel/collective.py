"""Collective wrappers for use inside ``shard_map``-ped functions.

Parity note: the reference implements gradient aggregation as a Spark shuffle
to per-partition owners (``parameters/AllReduceParameter.scala:putGradients``)
— a software parameter server. Here every collective is an XLA primitive that
lowers to ICI hardware collectives; these wrappers only fix axis-name plumbing.

Observability: when tracing is enabled each wrapper records call count and
bytes into the global registry (``collective/<op>_calls`` /
``collective/<op>_traced_bytes``). These wrappers execute at *trace* time
(inside jit), so the numbers are per-compilation accounting of what the
compiled program moves per step — not a per-step runtime counter. That is
exactly the number an operator needs to budget ICI bandwidth; multiply by
steps/sec for the live rate.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .. import observability as obs

from ..utils.compat import axis_size


def _record(op: str, x):
    """Trace-time byte accounting (no-op unless observability is on;
    symbolic shapes simply skip the bytes counter)."""
    if not obs.enabled():
        return
    obs.counter(f"collective/{op}_calls").inc()
    try:
        nbytes = float(x.size * x.dtype.itemsize)
    except (AttributeError, TypeError):
        return
    obs.counter(f"collective/{op}_traced_bytes", unit="B").inc(nbytes)


def _record_tree(op: str, tree):
    for leaf in jax.tree_util.tree_leaves(tree):
        _record(op, leaf)


def psum(x, axis: str = "data"):
    _record("psum", x)
    return lax.psum(x, axis_name=axis)


def pmean(x, axis: str = "data"):
    _record("pmean", x)
    return lax.pmean(x, axis_name=axis)


def all_reduce_sum(tree, axis: str = "data"):
    _record_tree("psum", tree)
    return jax.tree_util.tree_map(lambda t: lax.psum(t, axis), tree)


def all_reduce_mean(tree, axis: str = "data"):
    _record_tree("pmean", tree)
    return jax.tree_util.tree_map(lambda t: lax.pmean(t, axis), tree)


def all_gather(x, axis: str = "data", tiled: bool = True):
    _record("all_gather", x)
    return lax.all_gather(x, axis_name=axis, tiled=tiled)


def reduce_scatter(x, axis: str = "data", scatter_dimension: int = 0):
    _record("reduce_scatter", x)
    return lax.psum_scatter(x, axis_name=axis,
                            scatter_dimension=scatter_dimension, tiled=True)


def ppermute_ring(x, axis: str = "data", shift: int = 1):
    """Rotate shards around the ring (basis of ring attention)."""
    _record("ppermute", x)
    n = axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name=axis, perm=perm)


def all_to_all(x, axis: str, split_axis: int, concat_axis: int):
    _record("all_to_all", x)
    return lax.all_to_all(x, axis_name=axis, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)
