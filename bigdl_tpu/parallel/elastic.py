"""Elastic restart (Tier 3 of self-healing training).

Tier 1 (``optim/optimizer.py`` remediation) turns a dead host into a
clean :class:`~bigdl_tpu.parallel.failure.TrainingHalted` exit with a
remediation checkpoint and a flight bundle; Tier 2
(:class:`~bigdl_tpu.parallel.failure.FaultPolicy`) replays transient
faults in place. This module owns the step neither can take: **resume
on fewer hosts**. The reference inherited this from Spark — a lost
executor's partitions were rescheduled onto survivors and the
DistriOptimizer never noticed; a TPU SPMD program is compiled FOR a
mesh shape, so losing a host means a new mesh, new placements, new
ZeRO-1 shard boundaries, and a new compile. The pieces:

* **Membership** — ``TrainingHalted.lost_processes`` (from
  :class:`~bigdl_tpu.parallel.failure.Heartbeat` staleness) names the
  dead peers; :func:`~bigdl_tpu.parallel.sharding.mesh_after_loss`
  re-derives a mesh over the survivors (data axis shrunk, model/seq
  groups kept whole).
* **State** — checkpoints store optimizer state in CANONICAL
  params-shaped form (``AllReduceParameter.state_to_canonical``), so a
  snapshot written under N-way ZeRO-1 restores bitwise under N', any
  N' — the restore re-pads and re-shards against the new boundaries.
* **Supervision** — :class:`ElasticRunner` drives the loop: build an
  optimizer for the current mesh (caller's factory), load the latest
  checkpoint, ``optimize()``; on :class:`TrainingHalted` shrink the
  mesh from the membership signal, aggregate the per-process crash
  bundles into one rank-0 post-mortem
  (``observability.flight.aggregate_bundles``), back off, and go
  again. Resumed training is bitwise-identical to a run launched fresh
  at the reduced shape from the same checkpoint (asserted by
  ``tests/test_resilience.py`` and ``make fault-smoke``).

Works identically on real multi-host meshes and on the CPU
``--xla_force_host_platform_device_count`` simulation the fault drill
uses (each virtual device standing in for a host).
"""
from __future__ import annotations

import logging
import os
import time
from typing import Callable, List, Optional

import jax

from .. import observability as obs
from ..observability import cluster as _cluster
from ..observability import flight as _flight
from ..observability import health as _health
from .failure import TrainingHalted
from .sharding import mesh_after_loss

_LOG = logging.getLogger("bigdl_tpu.parallel.elastic")


def find_latest_checkpoint(checkpoint_dir: str) -> Optional[str]:
    """Newest ``checkpoint*.bigdl`` under ``checkpoint_dir`` (the same
    pattern the optimizer's nan-resume path trusts — remediation-tagged
    halt checkpoints match it too), or None."""
    if not checkpoint_dir or not os.path.isdir(checkpoint_dir):
        return None
    snaps = [os.path.join(checkpoint_dir, f)
             for f in os.listdir(checkpoint_dir)
             if f.startswith("checkpoint") and f.endswith(".bigdl")]
    return max(snaps, key=os.path.getmtime) if snaps else None


def shrink_devices(devices: List, halt: TrainingHalted) -> List:
    """Default membership update: drop the devices owned by the halt's
    ``lost_processes``. A halt that names no peers (a local stall, a
    spike abort) keeps the device set — the restart is then a plain
    retry at the same shape."""
    if not halt.lost_processes:
        return list(devices)
    lost = set(halt.lost_processes)
    return [d for d in devices if d.process_index not in lost]


class ElasticRunner:
    """Restart supervisor closing the Tier-3 loop.

    Parameters
    ----------
    factory : ``factory(devices, attempt) -> BaseOptimizer`` — build a
        FRESH optimizer (model, dataset, optim method) wired for a mesh
        over ``devices``. Must configure its own ``set_checkpoint``
        into ``checkpoint_dir`` (and whatever remediation/fault
        policies the run wants); the runner only loads checkpoints and
        supervises. A fresh optimizer per attempt is the contract — the
        old one's compiled step closes over the dead mesh.
    checkpoint_dir : where checkpoints land and restarts resume from.
    max_restarts : restart budget; the halt that exhausts it re-raises.
    membership : ``membership(devices, halt) -> devices`` — the
        surviving device set after a halt. Defaults to
        :func:`shrink_devices` (heartbeat-named peers dropped); the CPU
        fault drill injects its own to simulate host loss on one
        process.
    min_devices : a membership update below this aborts (re-raising the
        halt) instead of limping on — e.g. keep at least half the pod.
    backoff_s : sleep between restart attempts (cluster managers need a
        beat to fence the dead host).
    aggregate_bundles : on restart, merge the per-process crash bundles
        in the flight dir into one rank-0 post-mortem artifact.
    """

    def __init__(self, factory: Callable, checkpoint_dir: str,
                 max_restarts: int = 2,
                 membership: Optional[Callable] = None,
                 devices: Optional[List] = None, min_devices: int = 1,
                 backoff_s: float = 0.0, aggregate_bundles: bool = True):
        if max_restarts < 0:
            raise ValueError(f"max_restarts must be >= 0, "
                             f"got {max_restarts}")
        self.factory = factory
        self.checkpoint_dir = checkpoint_dir
        self.max_restarts = int(max_restarts)
        self.membership = membership or shrink_devices
        self.devices = list(devices if devices is not None
                            else jax.devices())
        self.min_devices = int(min_devices)
        self.backoff_s = float(backoff_s)
        self.aggregate = aggregate_bundles
        self.restarts = 0
        self.halts: List[TrainingHalted] = []

    def run(self):
        """Supervise training to completion; returns the trained model.
        Raises the final :class:`TrainingHalted` when the restart
        budget or ``min_devices`` floor is exhausted, and propagates
        any non-halt failure immediately (a crash is not a membership
        event — Tier 1 exists to convert real host loss into halts)."""
        devices = list(self.devices)
        resume_from = None  # the LAST halt's own checkpoint wins
        for attempt in range(self.max_restarts + 1):
            opt = self.factory(devices, attempt)
            # prefer the checkpoint the halt itself wrote: an async
            # scheduled write from before the failure can land AFTER the
            # remediation checkpoint with a newer mtime, and mtime-newest
            # would silently resume pre-remediation state
            ckpt = resume_from \
                if resume_from and os.path.exists(resume_from) \
                else find_latest_checkpoint(self.checkpoint_dir)
            if ckpt is not None:
                opt.load_checkpoint(ckpt)
                _LOG.info("elastic attempt %d: resuming %s on %d devices",
                          attempt, os.path.basename(ckpt), len(devices))
            try:
                return opt.optimize()
            except TrainingHalted as halt:
                self.halts.append(halt)
                resume_from = halt.checkpoint_path
                if self.aggregate and jax.process_index() == 0:
                    _flight.aggregate_bundles()
                    # merge the per-process metric snapshots too: the
                    # snapshot files survive the restart, so successive
                    # aggregates keep ONE timeline across mesh reshapes
                    # (which attempt/cause each view belongs to rides in
                    # its context)
                    _cluster.write_aggregate(context={
                        "elastic_attempt": attempt,
                        "cause": halt.cause,
                        "neval": halt.neval,
                        "lost_processes": list(halt.lost_processes)})
                survivors = list(self.membership(devices, halt))
                # terminal halts re-raise BEFORE counting/announcing a
                # restart — monitoring must not see an elastic_restart
                # event (or runner.restarts tick) for a restart that
                # never happens
                if attempt >= self.max_restarts:
                    _LOG.error("restart budget (%d) exhausted; halting",
                               self.max_restarts)
                    raise
                if len(survivors) < self.min_devices:
                    _LOG.error(
                        "only %d devices survive (< min_devices=%d); "
                        "halting", len(survivors), self.min_devices)
                    raise
                self.restarts += 1
                if obs.enabled():
                    # live DURING recovery — the window an operator
                    # actually watches — not only after a clean finish
                    obs.gauge("elastic/restarts").set(self.restarts)
                _health.emit(
                    "elastic_restart", attempt=attempt + 1,
                    cause=halt.cause, neval=halt.neval,
                    devices_before=len(devices),
                    devices_after=len(survivors),
                    checkpoint=halt.checkpoint_path,
                    lost_processes=list(halt.lost_processes))
                devices = survivors
                if self.backoff_s > 0:
                    time.sleep(self.backoff_s)
        raise AssertionError("unreachable")  # the loop returns or raises


def data_parallel_factory(make_optimizer):
    """Convenience adapter for the common case: wrap
    ``make_optimizer(mesh) -> optimizer`` into an :class:`ElasticRunner`
    factory that builds a 1-D data mesh over the surviving devices. For
    multi-axis meshes build the mesh in your own factory with
    :func:`~bigdl_tpu.parallel.sharding.mesh_after_loss`."""
    from .mesh import make_mesh

    def factory(devices, attempt):
        mesh = make_mesh((len(devices),), ("data",), devices=devices)
        return make_optimizer(mesh)

    return factory


__all__ = ["ElasticRunner", "find_latest_checkpoint", "shrink_devices",
           "data_parallel_factory", "mesh_after_loss", "TrainingHalted"]
