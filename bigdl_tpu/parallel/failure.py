"""Failure detection and straggler metrics (SURVEY §2.5 / §5).

The reference inherits failure handling from Spark: a lost executor's tasks
are re-run, and per-task timing feeds Spark's straggler (speculation)
machinery. A TPU SPMD program has no per-task retry — failure handling moves
to three layers, implemented here and in the optimizers:

1. **Step-level**: the train step guards against NaN/Inf inside the compiled
   function (non-finite loss ⇒ parameters keep their previous value), and the
   optimizer's ``nan_policy`` ('error' | 'skip' | 'resume') decides whether to
   raise, drop the step, or roll back to the latest checkpoint
   (optim/optimizer.py).
2. **Mesh-level**: ``probe_mesh`` runs a tiny collective with a timeout — a
   hung or lost chip surfaces as a probe failure instead of an indefinite
   stall inside a training collective.
3. **Host-level**: ``Heartbeat`` exchanges per-process counters over the
   jax.distributed channel (gated to multi-process runs); ``StragglerMonitor``
   aggregates per-host step times and flags hosts slower than
   ``threshold × median`` — the metric Spark speculation keys on.
"""
from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import observability as obs
from ..observability import health as _health

_LOG = logging.getLogger("bigdl_tpu.parallel.failure")

# ------------------------------------------------------ failure classes
#: the failure taxonomy the remediation tiers branch on: TRANSIENT
#: failures (a flaky collective, a dropped tunnel connection, a
#: preempted RPC) are worth replaying in place (FaultPolicy, Tier 2);
#: PERMANENT failures (a dead host, a wedged mesh) need checkpoint-and-
#: exit followed by an elastic restart on a reshaped mesh (Tier 3,
#: ``parallel/elastic.py``).
TRANSIENT = "transient"
PERMANENT = "permanent"


class TransientDeviceError(RuntimeError):
    """A device/collective failure worth retrying in place: the chip is
    believed alive, the dispatch just failed (dropped tunnel packet,
    preempted RPC, flaky barrier). Raised by fault-injection harnesses
    and recognized by :class:`FaultPolicy` (the trainer replays the
    in-flight step group) and by the serving engine's one-shot batch
    retry — one typed classification shared by both consumers."""


#: substrings that mark a runtime error as transient. Drawn from the
#: gRPC/absl status-code vocabulary jaxlib surfaces for connection-level
#: failures (XlaRuntimeError stringifies the status) — deliberately NOT
#: including RESOURCE_EXHAUSTED (OOM replays identically) or
#: INVALID_ARGUMENT (a program bug replays identically).
_TRANSIENT_MARKERS = (
    "transient", "unavailable", "deadline_exceeded", "deadline exceeded",
    "aborted", "cancelled", "connection reset", "connection refused",
    "socket closed", "broken pipe", "temporarily", "preempt",
    "too many pings", "keepalive", "network is unreachable",
)


def classify_failure(exc: BaseException) -> str:
    """Map an exception from the dispatch path onto the failure
    taxonomy. Typed signals win: :class:`TransientDeviceError` is
    transient by construction, :class:`HeartbeatLost` / a failed mesh
    probe mean a peer is gone — permanent. Everything else falls back
    to matching the runtime's status-code vocabulary in the message;
    unknown errors classify PERMANENT (replaying a deterministic bug
    burns the retry budget and then fails identically — the safe
    default is to surface it)."""
    if isinstance(exc, TransientDeviceError):
        return TRANSIENT
    if isinstance(exc, HeartbeatLost):
        return PERMANENT
    msg = f"{type(exc).__name__}: {exc}".lower()
    if any(m in msg for m in _TRANSIENT_MARKERS):
        return TRANSIENT
    return PERMANENT


class FaultPolicy:
    """Tier-2 retry/backoff budget for the training dispatch path.

    Armed via ``Optimizer.set_fault_policy``: each dispatch first
    snapshots the resolved host-side state, and a failure classified
    into ``retry_classes`` (default: transient only) replays the
    in-flight step (or whole superstep group) from that snapshot after
    an exponential backoff — ``backoff_base_s * 2^k`` capped at
    ``backoff_max_s``. ``max_restarts`` bounds CONSECUTIVE failed
    attempts; any success resets the budget, so a long run tolerates
    occasional flakes without accumulating toward an abort. Failures
    outside ``retry_classes`` (permanent by default) raise immediately
    — Tier 3 (checkpoint + elastic restart) owns those.

    ``sleep`` is injectable so fault-injection tests run at full speed.
    """

    def __init__(self, max_restarts: int = 3, backoff_base_s: float = 0.5,
                 backoff_max_s: float = 30.0,
                 retry_classes=(TRANSIENT,), sleep=time.sleep):
        if max_restarts < 0:
            raise ValueError(f"max_restarts must be >= 0, got {max_restarts}")
        self.max_restarts = int(max_restarts)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self.retry_classes = tuple(retry_classes)
        self.sleep = sleep
        self.consecutive = 0   # failed attempts since the last success
        self.total_retries = 0

    def classify(self, exc: BaseException) -> str:
        return classify_failure(exc)

    def should_retry(self, failure_class: str) -> bool:
        return (failure_class in self.retry_classes
                and self.consecutive < self.max_restarts)

    def backoff_s(self) -> float:
        """Backoff before the NEXT attempt, from the consecutive-failure
        count (first retry waits ``backoff_base_s``)."""
        return min(self.backoff_base_s * (2.0 ** max(self.consecutive - 1, 0)),
                   self.backoff_max_s)

    def record_failure(self) -> None:
        self.consecutive += 1
        self.total_retries += 1

    def record_success(self) -> None:
        self.consecutive = 0

    def reset(self) -> None:
        """Start a fresh unit of work. The consecutive budget is meant
        to bound retries of ONE dispatch unit; a consumer that SURVIVES
        an exhausted budget (the serving batcher fails the batch and
        moves on — unlike the trainer, whose run ends) must reset, or
        the tripped fuse would deny every later unit its retry."""
        self.consecutive = 0


class TrainingHalted(RuntimeError):
    """Tier-1 remediation verdict: training stopped ITSELF — checkpoint
    written (when a checkpoint path is set), flight bundle dumped —
    instead of hanging in a dead collective or dying without artifacts.
    Carries everything a supervisor (``parallel/elastic.ElasticRunner``
    or an external launcher) needs to decide the restart: the cause,
    the failure class, the remediation checkpoint and bundle paths, the
    iteration provenance, and the lost peer processes when the
    membership signal named them."""

    def __init__(self, cause: str, failure_class: str = PERMANENT,
                 checkpoint_path: Optional[str] = None,
                 bundle_path: Optional[str] = None,
                 epoch: Optional[int] = None, neval: Optional[int] = None,
                 lost_processes=()):
        self.cause = cause
        self.failure_class = failure_class
        self.checkpoint_path = checkpoint_path
        self.bundle_path = bundle_path
        self.epoch = epoch
        self.neval = neval
        self.lost_processes = list(lost_processes)
        super().__init__(
            f"training halted by remediation: cause={cause} "
            f"class={failure_class} epoch={epoch} neval={neval} "
            f"checkpoint={checkpoint_path} bundle={bundle_path}"
            + (f" lost_processes={self.lost_processes}"
               if self.lost_processes else ""))


def _run_with_timeout(fn, timeout_s: float) -> Dict:
    """Run ``fn`` on a daemon watchdog thread. Returns {'value': ...} on
    success, {'error': str} if fn raised, {'timeout': True} if it did not
    finish — the shared machinery behind probe_mesh and Heartbeat (a hung
    collective cannot be cancelled; the daemon thread is abandoned and the
    caller escalates)."""
    result: Dict = {}

    def run():
        try:
            result["value"] = fn()
        except Exception as e:  # noqa: BLE001 — report, don't crash
            result["error"] = f"{type(e).__name__}: {e}"

    th = threading.Thread(target=run, daemon=True)
    th.start()
    th.join(timeout_s)
    if th.is_alive():
        return {"timeout": True}
    return result


class MeshProbeResult:
    def __init__(self, ok: bool, n_devices: int, latency_s: float,
                 error: Optional[str] = None):
        self.ok, self.n_devices = ok, n_devices
        self.latency_s, self.error = latency_s, error

    def __repr__(self):
        return (f"MeshProbeResult(ok={self.ok}, n={self.n_devices}, "
                f"latency={self.latency_s:.4f}s, error={self.error})")


def probe_mesh(mesh, timeout_s: float = 30.0) -> MeshProbeResult:
    """Run a psum of ones over every mesh axis with a timeout. A dead or hung
    device makes the collective never complete — the timeout converts that
    into a detectable failure instead of a stall."""
    from ..utils.compat import shard_map
    from jax.sharding import PartitionSpec as P

    axes = tuple(mesh.axis_names)
    n = int(np.prod([mesh.shape[a] for a in axes]))

    def ones_sum():
        def f(x):
            s = x
            for a in axes:
                s = jax.lax.psum(s, a)
            return s
        probe = shard_map(f, mesh=mesh, in_specs=P(), out_specs=P(),
                          check_vma=False)
        t0 = time.time()
        val = int(jax.jit(probe)(jnp.ones(())))
        return val, time.time() - t0

    t0 = time.time()
    result = _run_with_timeout(ones_sum, timeout_s)
    if result.get("timeout"):
        res = MeshProbeResult(False, n, time.time() - t0,
                              f"collective did not complete in {timeout_s}s")
    elif "error" in result:
        res = MeshProbeResult(False, n, time.time() - t0, result["error"])
    else:
        val, latency = result["value"]
        ok = val == n
        res = MeshProbeResult(ok, n, latency,
                              None if ok else
                              f"psum returned {val}, expected {n}")
    if obs.enabled():
        obs.histogram("failure/probe_latency_s", unit="s").observe(
            res.latency_s)
        obs.gauge("failure/probe_ok").set(1.0 if res.ok else 0.0)
        if not res.ok:
            # a failed mesh probe is a first-class health event: it is
            # the "chip is gone" signal the stall watchdog cannot see
            _health.emit("probe_failed", n_devices=res.n_devices,
                         latency_s=round(res.latency_s, 3),
                         error=res.error)
    return res


class HeartbeatLost(RuntimeError):
    """A heartbeat exchange did not complete: a peer process is dead or
    unresponsive (the all-gather hung past the timeout, or the coordination
    service surfaced the peer's failure as an error). The training loop
    should halt cleanly — checkpoint and exit — rather than stall inside
    the next collective."""


class Heartbeat:
    """Multi-host liveness: each process contributes an incrementing counter
    via an all-gather across processes; a host whose counter stops advancing
    for ``stale_after`` beats is reported dead. Single-process runs are a
    no-op (always healthy).

    A DEAD peer does not advance a counter — it hangs the all-gather itself.
    ``beat(timeout_s=...)`` therefore runs the exchange on a watchdog thread:
    a hang past the timeout, or a coordination-service error, raises
    :class:`HeartbeatLost` (detection), converting an indefinite stall into
    a clean halt. The timed-out gather thread is a daemon — it cannot be
    cancelled, which is fine because detection is followed by process exit."""

    def __init__(self, stale_after: int = 3,
                 expected_interval_s: Optional[float] = None):
        self.stale_after = stale_after
        # when set, a beat arriving more than expected_interval_s after
        # the previous one logs a structured late-beat warning (the loop
        # stalled — slow step, GC pause, hung host IO)
        self.expected_interval_s = expected_interval_s
        self.beat_no = 0
        self.last_seen: Dict[int, int] = {}
        self.counters: Dict[int, int] = {}
        self._last_beat_t: Optional[float] = None
        self._beacon = None

    @property
    def last_beat_age_s(self) -> float:
        """Seconds since the last completed beat (monotonic clock);
        ``inf`` before the first beat. Exported as the
        ``failure/last_beat_age_s`` gauge — the number a liveness alert
        should page on."""
        if self._last_beat_t is None:
            return float("inf")
        return time.monotonic() - self._last_beat_t

    def _register_gauge(self):
        # a LIVE gauge (computed at export time): the age must keep
        # growing while the loop that would have written it is hung —
        # precisely the condition the alert exists to catch. Held via
        # weakref so the registry never pins a finished run's Heartbeat:
        # once it is collected the gauge reads NaN (distinguishable from
        # both "healthy" and "hung"). With several Heartbeats the most
        # recent beat owns the gauge.
        import weakref
        ref = weakref.ref(self)

        def age() -> float:
            hb = ref()
            return hb.last_beat_age_s if hb is not None else float("nan")

        obs.gauge("failure/last_beat_age_s", unit="s").set_fn(age)

    def _ensure_beacon(self):
        # the prober registers with the stall watchdog like any other
        # long-running component: deadline = a full staleness budget
        # (expected_interval_s * stale_after) when an interval is
        # declared, else the global default. weakref.finalize
        # unregisters on GC so a finished run's heartbeat never pages.
        if self._beacon is not None or not obs.enabled():
            return
        import weakref
        deadline = (self.expected_interval_s * self.stale_after
                    if self.expected_interval_s is not None else None)
        self._beacon = _health.beacon("failure/heartbeat",
                                      deadline_s=deadline)
        if self._beacon is not _health.NULL_BEACON:
            weakref.finalize(self, self._beacon.close)

    @property
    def n_processes(self) -> int:
        return jax.process_count()

    def _gather(self, value: int) -> List[int]:
        if self.n_processes == 1:
            return [value]
        from jax.experimental import multihost_utils
        out = multihost_utils.process_allgather(
            np.array(value, np.int64))
        return [int(v) for v in np.asarray(out).reshape(-1)]

    def _gather_with_timeout(self, value: int, timeout_s: float) -> List[int]:
        result = _run_with_timeout(lambda: self._gather(value), timeout_s)
        if result.get("timeout"):
            raise HeartbeatLost(
                f"heartbeat exchange did not complete in {timeout_s}s — "
                f"a peer process is dead or unresponsive")
        if "error" in result:
            # peer death often surfaces as a coordination-service error
            raise HeartbeatLost(
                f"heartbeat exchange failed ({result['error']}) — "
                f"a peer process died")
        return result["value"]

    def beat(self, timeout_s: Optional[float] = None) -> List[int]:
        """Advance the local counter, exchange, and return stale host ids.

        With ``timeout_s``, a hung or failed exchange raises
        :class:`HeartbeatLost` instead of stalling forever."""
        # chaos site. An injected fault surfaces the way a REAL
        # exchange failure does — as HeartbeatLost — so the trainer's
        # remediation tier (which types on HeartbeatLost, not on the
        # transport error underneath) handles the drill exactly like
        # the fault it simulates; a wedge rule sleeps here and pages
        # the prober's watchdog beacon instead.
        try:
            _chaos.maybe_fire("heartbeat/beat")
        except Exception as e:  # noqa: BLE001 — typed re-surface
            raise HeartbeatLost(
                f"injected heartbeat fault: {type(e).__name__}: {e}") \
                from e
        self.beat_no += 1
        now = time.monotonic()
        if (self.expected_interval_s is not None
                and self._last_beat_t is not None
                and now - self._last_beat_t > self.expected_interval_s):
            _LOG.warning(
                "late heartbeat: beat_no=%d age_s=%.3f "
                "expected_interval_s=%.3f process=%d",
                self.beat_no, now - self._last_beat_t,
                self.expected_interval_s, jax.process_index())
            if obs.enabled():
                obs.counter("failure/late_beats").inc()
                _health.emit("heartbeat_late", beat_no=self.beat_no,
                             age_s=round(now - self._last_beat_t, 3),
                             expected_interval_s=self.expected_interval_s)
        if timeout_s is not None:
            counters = self._gather_with_timeout(self.beat_no, timeout_s)
        else:
            counters = self._gather(self.beat_no)
        self._last_beat_t = time.monotonic()
        if obs.enabled():
            self._register_gauge()
            self._ensure_beacon()
            if self._beacon is not None:
                self._beacon.pulse()
            obs.counter("failure/beats").inc()
        stale = []
        for pid, c in enumerate(counters):
            if c > self.counters.get(pid, -1):
                self.counters[pid] = c
                self.last_seen[pid] = self.beat_no
            elif self.beat_no - self.last_seen.get(pid, 0) >= \
                    self.stale_after:
                stale.append(pid)
        if stale:
            _LOG.warning(
                "stale heartbeat peers: processes=%s beat_no=%d "
                "stale_after=%d", stale, self.beat_no, self.stale_after)
            if obs.enabled():
                _health.emit("heartbeat_stale", peers=stale,
                             beat_no=self.beat_no,
                             stale_after=self.stale_after)
        return stale


class FileHeartbeat:
    """File-based liveness for processes OUTSIDE one jax.distributed
    job — the serving fleet's membership signal (``serving/fleet.py``).

    :class:`Heartbeat` needs a coordination channel every participant
    shares; independent replica processes on one machine have none, but
    they share a filesystem. Each member ``beat()``s by atomically
    rewriting ONE file (tmp + rename, the crash-bundle discipline) with
    an incrementing counter, a wall-clock stamp, and the caller's
    payload (the fleet agent puts its serving section there); anyone
    can :meth:`read` a member's file and judge :meth:`age_s` — a stale
    or missing file is the lost-heartbeat signal, exactly the semantics
    ``Heartbeat.beat()`` derives from a stalled counter. A member that
    finishes CLEANLY writes ``final: true`` (optionally ``dead: true``
    for a crash-with-last-words), so a monitor can tell "exited" from
    "wedged" — the same distinction the cluster aggregate's
    straggler/suspect-dead join needs (``cluster.write_aggregate``)."""

    def __init__(self, path: str):
        self.path = path
        self.beat_no = 0

    def beat(self, payload: Optional[Dict] = None, *,
             final: bool = False) -> Dict:
        """Atomic rewrite of the member file; returns the written doc.
        Never raises — liveness reporting must not take the member
        down (a failed write just leaves the previous beat in place,
        which reads as a late beat, the honest signal)."""
        import os
        self.beat_no += 1
        doc = dict(payload or {})
        doc.update(beat=self.beat_no, written_at=time.time(),
                   pid=os.getpid())
        if final:
            doc["final"] = True
        try:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            tmp = f"{self.path}.{os.getpid()}.tmp"
            import json
            with open(tmp, "w") as f:
                json.dump(doc, f, default=str)
            os.replace(tmp, self.path)
            if obs.enabled():
                obs.counter("failure/file_beats").inc()
        except OSError:
            _LOG.exception("file heartbeat write failed: %s", self.path)
        return doc

    @staticmethod
    def read(path: str) -> Optional[Dict]:
        """The member's latest doc, or None for missing/half-written
        files (a dying peer's torn write reads as absent, like the
        snapshot merge)."""
        import json
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    @staticmethod
    def age_s(doc: Optional[Dict], now: Optional[float] = None) -> float:
        """Seconds since the doc's beat; ``inf`` for no doc."""
        if not doc or not isinstance(doc.get("written_at"), (int, float)):
            return float("inf")
        return max(0.0, (time.time() if now is None else now)
                   - doc["written_at"])


class StragglerMonitor:
    """Per-host step-time collection + straggler flagging (the metric Spark's
    speculation uses, over the jax.distributed channel instead of the Spark
    driver).

    A host flagged in ``persist_after`` CONSECUTIVE ``report()`` calls
    fires a structured ``health/straggler`` event (host id, imbalance,
    per-host means) so the remediation policy — which only sees health
    events, never pulls reports — can act on it; a single slow report
    (GC pause, one cold batch) never pages. Re-arms when the host drops
    back under the threshold."""

    def __init__(self, threshold: float = 1.5, window: int = 50,
                 persist_after: int = 3):
        self.threshold = threshold
        self.window = window
        self.persist_after = max(1, int(persist_after))
        self.times: List[float] = []
        self._consecutive: Dict[int, int] = {}

    def record(self, step_time_s: float) -> None:
        self.times.append(float(step_time_s))
        if len(self.times) > self.window:
            self.times.pop(0)

    def _local_mean(self) -> float:
        return float(np.mean(self.times)) if self.times else 0.0

    def _gather_means(self) -> np.ndarray:
        local = self._local_mean()
        if jax.process_count() == 1:
            return np.array([local])
        from jax.experimental import multihost_utils
        out = multihost_utils.process_allgather(
            np.array(local, np.float64))
        return np.asarray(out).reshape(-1)

    @staticmethod
    def analyze(per_host_means: np.ndarray, threshold: float = 1.5) -> Dict:
        means = np.asarray(per_host_means, np.float64)
        med = float(np.median(means)) if means.size else 0.0
        stragglers = [int(i) for i, m in enumerate(means)
                      if med > 0 and m > threshold * med]
        return {"per_host_mean_s": [float(m) for m in means],
                "median_s": med,
                "max_s": float(means.max()) if means.size else 0.0,
                "imbalance": float(means.max() / med) if med > 0 else 1.0,
                "stragglers": stragglers}

    def report(self) -> Dict:
        rep = self.analyze(self._gather_means(), self.threshold)
        flagged = set(rep["stragglers"])
        for pid in flagged:
            self._consecutive[pid] = self._consecutive.get(pid, 0) + 1
            if self._consecutive[pid] == self.persist_after:
                _health.emit(
                    "straggler", host=pid,
                    consecutive_reports=self._consecutive[pid],
                    mean_s=round(rep["per_host_mean_s"][pid], 6),
                    median_s=round(rep["median_s"], 6),
                    imbalance=round(rep["imbalance"], 3),
                    threshold=self.threshold)
        for pid in list(self._consecutive):
            if pid not in flagged:
                del self._consecutive[pid]  # re-arm: one clean report
        return rep


# imported LAST: chaos.py imports this module's taxonomy, so a top-of-
# file import would be circular — by this point every name chaos needs
# exists, and beat()'s disarmed cost stays the documented single
# module-global read instead of a per-call sys.modules lookup
from . import chaos as _chaos  # noqa: E402
