"""Flash attention dispatch: custom Pallas kernel on TPU, einsum elsewhere.

The kernels themselves live in ``bigdl_tpu.kernels`` (hand-written
Pallas; ``flash_attention`` for training/prefill, ``paged_attention``
for the serving tier's paged decode). This module is only the
dispatcher:

* TPU-class backends ("tpu", and the axon PJRT plugin's "axon") run the
  compiled kernels;
* ``BIGDL_TPU_FLASH=interpret`` / ``BIGDL_TPU_PAGED_ATTN=interpret``
  force the same kernels through the Pallas interpreter (how the CPU
  test suite exercises the kernel code);
* ``BIGDL_TPU_FLASH=off`` / ``BIGDL_TPU_PAGED_ATTN=off`` or any non-TPU
  backend falls back to the reference einsum / dense-gather paths in
  ``nn.attention`` — and the fallback is LOGGED, never silent, so a TPU
  run that degrades to O(T^2) attention (or to the O(T) paged-gather
  round-trip) is visible.
"""
from __future__ import annotations

import contextlib
import contextvars
import logging
import os

import jax
import jax.numpy as jnp

logger = logging.getLogger("bigdl_tpu")
_warned = set()


def _warn_once(key, msg, *args):
    if key not in _warned:
        _warned.add(key)
        logger.warning(msg, *args)


def _einsum_fallback(q, k, v, causal):
    import numpy as np
    from ..nn.attention import dot_product_attention
    mask = None
    if causal:
        t = q.shape[-2]
        mask = jnp.where(np.tril(np.ones((t, t), np.bool_))[None, None],
                         0.0, -1e9)
    return dot_product_attention(q, k, v, mask)


def flash_mode() -> str:
    """Resolved dispatch mode: 'pallas' | 'interpret' | 'einsum'.

    The ONE policy decision shared by every flash consumer (this
    dispatcher and parallel/ring_flash.py): BIGDL_TPU_FLASH=off forces
    einsum, =interpret runs the Pallas kernels in the interpreter, and
    otherwise TPU-class backends get the compiled kernels."""
    mode = os.environ.get("BIGDL_TPU_FLASH", "auto")
    if mode == "off":
        return "einsum"
    if mode == "interpret":
        return "interpret"
    try:
        backend = jax.default_backend()
    except Exception:
        backend = "cpu"
    return "pallas" if backend in ("tpu", "axon") else "einsum"


def _flash_blocks():
    """Kernel tile-size overrides for on-chip sweeps (trace-time env, like
    BIGDL_TPU_FUSED_BLOCK_*): BIGDL_TPU_FLASH_BLOCK_Q / _K."""
    return {"block_q": int(os.environ.get("BIGDL_TPU_FLASH_BLOCK_Q", 512)),
            "block_k": int(os.environ.get("BIGDL_TPU_FLASH_BLOCK_K", 512))}


def _dispatch(name, kernel_fn, fallback_fn):
    """The ONE dispatch policy (off / interpret / pallas-with-logged-
    fallback / einsum) shared by every flash entry point.
    ``kernel_fn(interpret)`` runs the Pallas kernel; ``fallback_fn()``
    the einsum path."""
    mode = flash_mode()
    if os.environ.get("BIGDL_TPU_FLASH") == "off":
        return fallback_fn()          # explicit opt-out: no warning
    if mode == "interpret":
        return kernel_fn(True)
    try:
        backend = jax.default_backend()
    except Exception:
        backend = "cpu"
    if mode == "pallas":
        try:
            return kernel_fn(False)
        except Exception as e:
            _warn_once((name, "kernel", backend),
                       "Pallas %s kernel failed on backend %r (%s); "
                       "falling back to the einsum path", name, backend, e)
            return fallback_fn()
    _warn_once((name, "backend", backend),
               "%s: non-TPU backend %r uses the einsum path (set "
               "BIGDL_TPU_FLASH=interpret to run the Pallas kernel in "
               "interpreter mode)", name, backend)
    return fallback_fn()


def flash_attention(q, k, v, causal: bool = False):
    """q, k, v: (B, H, T, D)."""

    def kernel(interpret):
        # import inside the branch: a jax build without pallas must not
        # break the einsum path for non-TPU callers
        from ..kernels.flash_attention import flash_attention_fused
        return flash_attention_fused(q, k, v, causal=causal,
                                     interpret=interpret, **_flash_blocks())

    return _dispatch("flash attention", kernel,
                     lambda: _einsum_fallback(q, k, v, causal))


def _einsum_chunk_fallback(q, k, v, q_offset, kv_len):
    from ..nn.attention import dot_product_attention
    k, v = k[:, :, :kv_len], v[:, :, :kv_len]
    s = q.shape[-2]
    mask = jnp.where(
        jnp.arange(kv_len)[None, :] <= q_offset + jnp.arange(s)[:, None],
        0.0, -1e9)[None, None]
    return dot_product_attention(q, k, v, mask)


def flash_chunk_attention(q, k, v, q_offset: int, kv_len: int = None):
    """Rectangular-causal chunk attention over the first ``kv_len``
    positions of a KV cache (Transformer.prefill_chunked):
    q (B, H, S, D) at global positions q_offset... Same dispatch policy
    as :func:`flash_attention`; the einsum fallback materialises the
    (S, kv_len) mask/logits the kernel exists to avoid."""
    if kv_len is None:
        kv_len = k.shape[2]

    def kernel(interpret):
        from ..kernels.flash_attention import flash_chunk_attention as fck
        return fck(q, k, v, q_offset, kv_len=kv_len, interpret=interpret,
                   **_flash_blocks())

    return _dispatch("chunk attention", kernel,
                     lambda: _einsum_chunk_fallback(q, k, v, q_offset,
                                                    kv_len))


# ---------------------------------------------------------------------------
# paged decode attention (serving tier)
# ---------------------------------------------------------------------------

# Trace-time serving context: the DecodeScheduler's compiled step sets
# (mesh, kv-head shard axis) around its trace so the dispatch below can
# shard_map the kernel per kv-head group under TP serving. A contextvar
# (not a model attribute) keeps shared model objects placement-free —
# two schedulers serving the same model at different placements never
# see each other's mesh.
_PAGED_CTX = contextvars.ContextVar("bigdl_tpu_paged_ctx",
                                    default=(None, None))


@contextlib.contextmanager
def paged_serving_context(mesh=None, shard_axis=None):
    """Trace-time context: set by the serving step around its
    ``decode_paged`` trace. ``shard_axis``: mesh axis the KV pages'
    kv-head dim is sharded over (None = pages replicated)."""
    tok = _PAGED_CTX.set((mesh, shard_axis))
    try:
        yield
    finally:
        _PAGED_CTX.reset(tok)


def paged_mode() -> str:
    """Resolved paged-decode dispatch mode: 'pallas' | 'interpret' |
    'dense'. Same policy shape as :func:`flash_mode`, gated by its own
    env knob (``BIGDL_TPU_PAGED_ATTN`` = auto/on/off/interpret) so the
    serving kernel can be A/B'd independently of the training kernels.
    The dense gather path stays the fallback AND the oracle."""
    mode = os.environ.get("BIGDL_TPU_PAGED_ATTN", "auto")
    if mode == "off":
        return "dense"
    if mode == "interpret":
        return "interpret"
    if mode == "on":
        return "pallas"
    try:
        backend = jax.default_backend()
    except Exception:
        backend = "cpu"
    return "pallas" if backend in ("tpu", "axon") else "dense"


def _paged_obs(counter: str):
    """Trace-time dispatch accounting: one bump per program BUILT on
    each path (execution never re-enters Python, so per-program is the
    honest unit — serve/decode_steps counts the dispatches riding it)."""
    from .. import observability as obs
    if obs.enabled():
        obs.counter(f"kernels/{counter}").inc()


def paged_attention(q, k_pages, v_pages, block_tables, positions,
                    dense_fn):
    """The serving tier's paged-decode attention seam.

    q: (B, nH, S, D); k_pages/v_pages: (num_blocks, kvH, block_size, D)
    ALREADY holding this chunk's scattered K/V; block_tables:
    (B, max_blocks) int32; positions: (B,) int32. ``dense_fn()`` is the
    caller's gathered-view einsum — the fallback and the oracle.

    Under a :func:`paged_serving_context` mesh the kernel runs inside
    ``shard_map`` per kv-head group: attention is head-local, so a
    kvH-sharded page pool needs no cross-shard communication — each
    shard streams its own heads' blocks. Pages replicated on the mesh
    (FSDP placement, or kvH not divisible by the axis) shard_map with
    replicated specs instead; any kernel failure falls back to the
    dense path with a logged warning, never silently."""
    mode = paged_mode()
    if mode == "dense":
        if os.environ.get("BIGDL_TPU_PAGED_ATTN") != "off":
            try:
                backend = jax.default_backend()
            except Exception:
                backend = "cpu"
            _warn_once(("paged attention", "backend", backend),
                       "paged attention: non-TPU backend %r uses the dense "
                       "gather path (set BIGDL_TPU_PAGED_ATTN=interpret to "
                       "run the Pallas kernel in interpreter mode)", backend)
        _paged_obs("paged_attn_dense_programs")
        return dense_fn()
    interpret = mode == "interpret"
    mesh, axis = _PAGED_CTX.get()
    try:
        from ..kernels.paged_attention import paged_decode_attention
        if mesh is None:
            out = paged_decode_attention(q, k_pages, v_pages, block_tables,
                                         positions, interpret=interpret)
        else:
            from jax.sharding import PartitionSpec as P
            from ..utils.compat import shard_map
            head = P(None, axis) if axis else P()

            def body(q, kp, vp, tbl, pos):
                return paged_decode_attention(
                    q, kp, vp, tbl, pos, interpret=interpret,
                    vma={axis} if axis else None)

            out = shard_map(body, mesh=mesh,
                            in_specs=(head, head, head, P(), P()),
                            out_specs=head, check_vma=False)(
                q, k_pages, v_pages, block_tables, positions)
        _paged_obs("paged_attn_programs")
        return out
    except Exception as e:
        _warn_once(("paged attention", "kernel", mode),
                   "Pallas paged-attention kernel failed (%s); falling "
                   "back to the dense gather path", e)
        _paged_obs("paged_attn_fallbacks")
        return dense_fn()
