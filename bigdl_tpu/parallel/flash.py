"""Flash attention dispatch: custom Pallas kernel on TPU, einsum elsewhere.

The kernel itself lives in ``bigdl_tpu.kernels.flash_attention`` (hand-written
Pallas forward + backward, O(T) memory). This module is only the dispatcher:

* TPU-class backends ("tpu", and the axon PJRT plugin's "axon") run the
  compiled kernel;
* ``BIGDL_TPU_FLASH=interpret`` forces the same kernel through the Pallas
  interpreter (how the CPU test suite exercises the kernel code);
* ``BIGDL_TPU_FLASH=off`` or any non-TPU backend falls back to the reference
  einsum path in ``nn.attention`` — and the fallback is LOGGED, never silent,
  so a TPU run that degrades to O(T^2) attention is visible.
"""
from __future__ import annotations

import logging
import os

import jax
import jax.numpy as jnp

logger = logging.getLogger("bigdl_tpu")
_warned = set()


def _warn_once(key, msg, *args):
    if key not in _warned:
        _warned.add(key)
        logger.warning(msg, *args)


def _einsum_fallback(q, k, v, causal):
    import numpy as np
    from ..nn.attention import dot_product_attention
    mask = None
    if causal:
        t = q.shape[-2]
        mask = jnp.where(np.tril(np.ones((t, t), np.bool_))[None, None],
                         0.0, -1e9)
    return dot_product_attention(q, k, v, mask)


def flash_mode() -> str:
    """Resolved dispatch mode: 'pallas' | 'interpret' | 'einsum'.

    The ONE policy decision shared by every flash consumer (this
    dispatcher and parallel/ring_flash.py): BIGDL_TPU_FLASH=off forces
    einsum, =interpret runs the Pallas kernels in the interpreter, and
    otherwise TPU-class backends get the compiled kernels."""
    mode = os.environ.get("BIGDL_TPU_FLASH", "auto")
    if mode == "off":
        return "einsum"
    if mode == "interpret":
        return "interpret"
    try:
        backend = jax.default_backend()
    except Exception:
        backend = "cpu"
    return "pallas" if backend in ("tpu", "axon") else "einsum"


def _flash_blocks():
    """Kernel tile-size overrides for on-chip sweeps (trace-time env, like
    BIGDL_TPU_FUSED_BLOCK_*): BIGDL_TPU_FLASH_BLOCK_Q / _K."""
    return {"block_q": int(os.environ.get("BIGDL_TPU_FLASH_BLOCK_Q", 512)),
            "block_k": int(os.environ.get("BIGDL_TPU_FLASH_BLOCK_K", 512))}


def _dispatch(name, kernel_fn, fallback_fn):
    """The ONE dispatch policy (off / interpret / pallas-with-logged-
    fallback / einsum) shared by every flash entry point.
    ``kernel_fn(interpret)`` runs the Pallas kernel; ``fallback_fn()``
    the einsum path."""
    mode = flash_mode()
    if os.environ.get("BIGDL_TPU_FLASH") == "off":
        return fallback_fn()          # explicit opt-out: no warning
    if mode == "interpret":
        return kernel_fn(True)
    try:
        backend = jax.default_backend()
    except Exception:
        backend = "cpu"
    if mode == "pallas":
        try:
            return kernel_fn(False)
        except Exception as e:
            _warn_once((name, "kernel", backend),
                       "Pallas %s kernel failed on backend %r (%s); "
                       "falling back to the einsum path", name, backend, e)
            return fallback_fn()
    _warn_once((name, "backend", backend),
               "%s: non-TPU backend %r uses the einsum path (set "
               "BIGDL_TPU_FLASH=interpret to run the Pallas kernel in "
               "interpreter mode)", name, backend)
    return fallback_fn()


def flash_attention(q, k, v, causal: bool = False):
    """q, k, v: (B, H, T, D)."""

    def kernel(interpret):
        # import inside the branch: a jax build without pallas must not
        # break the einsum path for non-TPU callers
        from ..kernels.flash_attention import flash_attention_fused
        return flash_attention_fused(q, k, v, causal=causal,
                                     interpret=interpret, **_flash_blocks())

    return _dispatch("flash attention", kernel,
                     lambda: _einsum_fallback(q, k, v, causal))


def _einsum_chunk_fallback(q, k, v, q_offset, kv_len):
    from ..nn.attention import dot_product_attention
    k, v = k[:, :, :kv_len], v[:, :, :kv_len]
    s = q.shape[-2]
    mask = jnp.where(
        jnp.arange(kv_len)[None, :] <= q_offset + jnp.arange(s)[:, None],
        0.0, -1e9)[None, None]
    return dot_product_attention(q, k, v, mask)


def flash_chunk_attention(q, k, v, q_offset: int, kv_len: int = None):
    """Rectangular-causal chunk attention over the first ``kv_len``
    positions of a KV cache (Transformer.prefill_chunked):
    q (B, H, S, D) at global positions q_offset... Same dispatch policy
    as :func:`flash_attention`; the einsum fallback materialises the
    (S, kv_len) mask/logits the kernel exists to avoid."""
    if kv_len is None:
        kv_len = k.shape[2]

    def kernel(interpret):
        from ..kernels.flash_attention import flash_chunk_attention as fck
        return fck(q, k, v, q_offset, kv_len=kv_len, interpret=interpret,
                   **_flash_blocks())

    return _dispatch("chunk attention", kernel,
                     lambda: _einsum_chunk_fallback(q, k, v, q_offset,
                                                    kv_len))
