"""Flash attention dispatch: custom Pallas kernel on TPU, einsum elsewhere.

The kernel itself lives in ``bigdl_tpu.kernels.flash_attention`` (hand-written
Pallas forward + backward, O(T) memory). This module is only the dispatcher:

* TPU-class backends ("tpu", and the axon PJRT plugin's "axon") run the
  compiled kernel;
* ``BIGDL_TPU_FLASH=interpret`` forces the same kernel through the Pallas
  interpreter (how the CPU test suite exercises the kernel code);
* ``BIGDL_TPU_FLASH=off`` or any non-TPU backend falls back to the reference
  einsum path in ``nn.attention`` — and the fallback is LOGGED, never silent,
  so a TPU run that degrades to O(T^2) attention is visible.
"""
from __future__ import annotations

import logging
import os

import jax
import jax.numpy as jnp

logger = logging.getLogger("bigdl_tpu")
_warned = set()


def _warn_once(key, msg, *args):
    if key not in _warned:
        _warned.add(key)
        logger.warning(msg, *args)


def _einsum_fallback(q, k, v, causal):
    import numpy as np
    from ..nn.attention import dot_product_attention
    mask = None
    if causal:
        t = q.shape[-2]
        mask = jnp.where(np.tril(np.ones((t, t), np.bool_))[None, None],
                         0.0, -1e9)
    return dot_product_attention(q, k, v, mask)


def flash_mode() -> str:
    """Resolved dispatch mode: 'pallas' | 'interpret' | 'einsum'.

    The ONE policy decision shared by every flash consumer (this
    dispatcher and parallel/ring_flash.py): BIGDL_TPU_FLASH=off forces
    einsum, =interpret runs the Pallas kernels in the interpreter, and
    otherwise TPU-class backends get the compiled kernels."""
    mode = os.environ.get("BIGDL_TPU_FLASH", "auto")
    if mode == "off":
        return "einsum"
    if mode == "interpret":
        return "interpret"
    try:
        backend = jax.default_backend()
    except Exception:
        backend = "cpu"
    return "pallas" if backend in ("tpu", "axon") else "einsum"


def _flash_blocks():
    """Kernel tile-size overrides for on-chip sweeps (trace-time env, like
    BIGDL_TPU_FUSED_BLOCK_*): BIGDL_TPU_FLASH_BLOCK_Q / _K."""
    return {"block_q": int(os.environ.get("BIGDL_TPU_FLASH_BLOCK_Q", 512)),
            "block_k": int(os.environ.get("BIGDL_TPU_FLASH_BLOCK_K", 512))}


def flash_attention(q, k, v, causal: bool = False):
    """q, k, v: (B, H, T, D)."""
    mode = flash_mode()
    if os.environ.get("BIGDL_TPU_FLASH") == "off":
        return _einsum_fallback(q, k, v, causal)  # explicit: no warning
    if mode == "interpret":
        from ..kernels.flash_attention import flash_attention_fused
        return flash_attention_fused(q, k, v, causal=causal, interpret=True,
                                     **_flash_blocks())

    try:
        backend = jax.default_backend()
    except Exception:
        backend = "cpu"
    if mode == "pallas":
        try:
            # import inside the branch: a jax build without pallas must not
            # break the einsum path for non-TPU callers
            from ..kernels.flash_attention import flash_attention_fused
            return flash_attention_fused(q, k, v, causal=causal,
                                         **_flash_blocks())
        except Exception as e:
            _warn_once(("kernel", backend),
                       "Pallas flash-attention kernel failed on backend %r "
                       "(%s); falling back to O(T^2) einsum attention",
                       backend, e)
            return _einsum_fallback(q, k, v, causal)
    _warn_once(("backend", backend),
               "flash attention: non-TPU backend %r uses the einsum path "
               "(set BIGDL_TPU_FLASH=interpret to run the Pallas kernel "
               "in interpreter mode)", backend)
    return _einsum_fallback(q, k, v, causal)
