"""Fused flash attention for TPU.

Uses the Pallas TPU flash-attention kernel (tiled over sequence blocks in
VMEM, O(T) memory) when running on a TPU backend; the public einsum path in
``nn.attention`` is the fallback everywhere else (CPU tests, debugging).
See /opt/skills/guides/pallas_guide.md for the kernel playbook.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def flash_attention(q, k, v, causal: bool = False):
    """q, k, v: (B, H, T, D)."""
    try:
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            flash_attention as _fa, BlockSizes)
        t = q.shape[-2]
        blk = min(512, t)
        sizes = BlockSizes.get_default()
        return _fa(q, k, v, causal=causal, block_sizes=sizes)
    except Exception:
        from ..nn.attention import dot_product_attention
        import numpy as np
        mask = None
        if causal:
            tt = q.shape[-2]
            mask = jnp.where(np.tril(np.ones((tt, tt), np.bool_))[None, None],
                             0.0, -1e9)
        return dot_product_attention(q, k, v, mask)
