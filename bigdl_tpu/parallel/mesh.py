"""Device-mesh management.

This package replaces the reference's distributed substrate (Spark executors +
block-manager parameter service, ``parameters/AllReduceParameter.scala`` and
``utils/Engine.scala`` cluster config) with jax.sharding over an explicit
``Mesh``. Axes:

* ``data`` — data parallelism (the reference's only mode)
* ``model`` — tensor parallelism (new capability, rides ICI)
* ``seq``  — sequence/context parallelism (ring attention)

Multi-host: call ``jax.distributed.initialize()`` before ``make_mesh`` and the
same code spans hosts — collectives ride ICI within a pod slice and DCN
across, scheduled by XLA, which is the TPU-native analog of the reference's
NCCL/MPI-free Spark shuffle aggregation.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

_default_mesh: Optional[Mesh] = None


def make_mesh(shape: Sequence[int], axes: Sequence[str],
              devices=None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    n = int(np.prod(shape))
    if n > len(devices):
        raise ValueError(f"mesh needs {n} devices, have {len(devices)}")
    arr = np.array(devices[:n]).reshape(tuple(shape))
    return Mesh(arr, tuple(axes))


def data_parallel_mesh(n: Optional[int] = None) -> Mesh:
    devs = jax.devices()
    n = n or len(devs)
    return make_mesh((n,), ("data",), devs)


def set_default_mesh(mesh: Mesh):
    global _default_mesh
    _default_mesh = mesh
    return mesh


def get_default_mesh() -> Mesh:
    global _default_mesh
    if _default_mesh is None:
        _default_mesh = data_parallel_mesh()
    return _default_mesh


def axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape[axis]
