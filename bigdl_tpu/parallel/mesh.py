"""Device-mesh management.

This package replaces the reference's distributed substrate (Spark executors +
block-manager parameter service, ``parameters/AllReduceParameter.scala`` and
``utils/Engine.scala`` cluster config) with jax.sharding over an explicit
``Mesh``. Axes:

* ``data`` — data parallelism (the reference's only mode)
* ``model`` — tensor parallelism (new capability, rides ICI)
* ``seq``  — sequence/context parallelism (ring attention)

Multi-host: call ``jax.distributed.initialize()`` before ``make_mesh`` and the
same code spans hosts — collectives ride ICI within a pod slice and DCN
across, scheduled by XLA, which is the TPU-native analog of the reference's
NCCL/MPI-free Spark shuffle aggregation.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

_default_mesh: Optional[Mesh] = None


def make_mesh(shape: Sequence[int], axes: Sequence[str],
              devices=None) -> Mesh:
    """Build a mesh whose device layout follows the physical ICI topology.

    When the requested shape covers every visible device,
    ``mesh_utils.create_device_mesh`` arranges them so neighboring mesh
    coordinates are ICI neighbors (ring collectives then ride ICI links
    instead of hopping the fabric arbitrarily). Falls back to a plain
    reshape for device subsets or host-only backends.
    """
    explicit = devices is not None
    devices = list(devices if explicit else jax.devices())
    n = int(np.prod(shape))
    if n > len(devices):
        raise ValueError(f"mesh needs {n} devices, have {len(devices)}")
    # topology-aware layout only when WE chose the devices — an explicit
    # caller-supplied ordering must be honored verbatim
    if n == len(devices) and not explicit:
        try:
            from jax.experimental import mesh_utils
            arr = mesh_utils.create_device_mesh(tuple(shape),
                                                devices=devices)
            return Mesh(arr, tuple(axes))
        except Exception:  # non-TPU topologies
            pass
    arr = np.array(devices[:n]).reshape(tuple(shape))
    return Mesh(arr, tuple(axes))


def make_hybrid_mesh(ici_shape: Sequence[int], dcn_shape: Sequence[int],
                     axes: Sequence[str]) -> Mesh:
    """Multi-slice/multi-host mesh over DCN × ICI.

    ``ici_shape``, ``dcn_shape`` and ``axes`` must have the same length;
    axis i has total size ``ici_shape[i] * dcn_shape[i]``, with the DCN
    factor spanning slices/hosts and the ICI factor staying inside one
    slice. E.g. 2 hosts × 8 chips, dp-over-DCN + tp-over-ICI::

        make_hybrid_mesh(ici_shape=(1, 8), dcn_shape=(2, 1),
                         axes=("data", "model"))   # mesh (2, 8)

    Put data parallelism on the DCN factor and tensor/sequence parallelism
    on the ICI factor — gradient all-reduce tolerates DCN latency;
    per-layer collectives do not (scaling-book recipe). Wraps
    ``mesh_utils.create_hybrid_device_mesh``.
    """
    if not (len(ici_shape) == len(dcn_shape) == len(axes)):
        raise ValueError("ici_shape, dcn_shape and axes must align "
                         f"(got {ici_shape}, {dcn_shape}, {axes})")
    from jax.experimental import mesh_utils
    devices = jax.devices()
    # The DCN granule is whatever the topology actually has dcn_total of:
    # multi-slice TPU pods group by slice_index; multi-process hosts
    # (including CPU rendezvous, where every device reports slice 0)
    # group by process.
    dcn_total = int(np.prod(dcn_shape))
    has_slice = hasattr(devices[0], "slice_index")
    n_slices = len({d.slice_index for d in devices}) if has_slice else 0
    granule_by_process = (not has_slice) or (n_slices != dcn_total)
    arr = mesh_utils.create_hybrid_device_mesh(
        tuple(ici_shape), tuple(dcn_shape), devices=devices,
        process_is_granule=granule_by_process)
    return Mesh(arr, tuple(axes))


def data_parallel_mesh(n: Optional[int] = None) -> Mesh:
    devs = jax.devices()
    n = n or len(devs)
    return make_mesh((n,), ("data",), devs)


def set_default_mesh(mesh: Mesh):
    global _default_mesh
    _default_mesh = mesh
    return mesh


def get_default_mesh() -> Mesh:
    global _default_mesh
    if _default_mesh is None:
        _default_mesh = data_parallel_mesh()
    return _default_mesh


def axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape[axis]
