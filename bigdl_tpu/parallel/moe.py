"""Mixture-of-Experts with expert parallelism over an ``expert`` mesh axis.

TPU-first addition beyond the reference (BigDL 0.x has no MoE). Switch-style
top-1 routing with the Mesh-TensorFlow dispatch/combine formulation
(PAPERS.md: Mesh-TensorFlow, arXiv:1811.02084): routing builds dense
(tokens, experts, capacity) dispatch/combine tensors so the data movement is
two einsums plus ``all_to_all`` over ICI — no dynamic shapes, MXU-friendly.

Layout: the ``expert`` axis doubles as the token (data) axis — each device
holds its local token slice AND exactly one expert (E = axis size).
``all_to_all`` exchanges expert minibatches: device d sends the tokens it
routed to expert e to e's owner and receives every device's tokens for its
own expert.
"""
from __future__ import annotations

import math
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

from ..utils.compat import axis_size


def expert_capacity(tokens: int, n_experts: int, factor: float) -> int:
    """Per-expert queue length: ceil(factor * tokens / n_experts), min 1."""
    return max(1, math.ceil(factor * tokens / n_experts))


def top1_routing(logits, capacity: int):
    """Switch routing: (tokens, E) logits → dispatch (t, E, C) bool,
    combine (t, E, C) float, aux load-balance loss."""
    t, E = logits.shape
    probs = jax.nn.softmax(logits, axis=-1)
    gate = jnp.max(probs, axis=-1)              # (t,)
    expert = jnp.argmax(probs, axis=-1)         # (t,)
    onehot = jax.nn.one_hot(expert, E, dtype=logits.dtype)  # (t, E)
    # position of each token within its expert's queue
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1.0          # (t, E)
    pos_of_token = jnp.sum(pos * onehot, axis=-1)            # (t,)
    keep = pos_of_token < capacity
    pos_clip = jnp.clip(pos_of_token, 0, capacity - 1).astype(jnp.int32)
    pos_onehot = jax.nn.one_hot(pos_clip, capacity,
                                dtype=logits.dtype)          # (t, C)
    dispatch = (onehot * keep[:, None])[:, :, None] * \
        pos_onehot[:, None, :]                               # (t, E, C)
    combine = dispatch * gate[:, None, None]
    # load-balance auxiliary loss (Switch Transformer eq. 4)
    frac_tokens = jnp.mean(onehot, axis=0)
    frac_probs = jnp.mean(probs, axis=0)
    aux = E * jnp.sum(frac_tokens * frac_probs)
    return dispatch, combine, aux


def moe_ffn(expert_fn: Callable, axis: str = "expert",
            capacity_factor: float = 1.25):
    """Build the per-device expert-parallel MoE apply.

    ``expert_fn(expert_params, x) -> y`` is one expert's FFN over a (n, d)
    batch. Returns ``run(router_w, expert_params, x)`` for use inside
    ``shard_map`` over ``axis``:

    * ``router_w``: (d, E) gating weights — replicated (``P()``).
    * ``expert_params``: this device's expert params (leading expert axis
      sharded over ``axis``; the size-1 local slice is squeezed here —
      exactly one expert per device).
    * ``x``: (t_local, d) local token slice (sharded over ``axis``).
    * returns ((t_local, d) outputs, aux_loss) — aux averaged over the mesh.
    """

    def run(router_w, expert_params, x):
        E = axis_size(axis)
        tloc, d = x.shape
        def _squeeze(a):
            if a.ndim and a.shape[0] != 1:
                raise ValueError(
                    "moe_ffn supports exactly one expert per device: "
                    f"local expert-param slice has leading dim {a.shape[0]} "
                    "(shard the stacked expert axis over the mesh axis)")
            return a[0] if a.ndim else a
        expert_params = jax.tree_util.tree_map(_squeeze, expert_params)
        capacity = expert_capacity(tloc, E, capacity_factor)

        logits = x @ router_w                                # (t, E)
        dispatch, combine, aux = top1_routing(logits, capacity)

        expert_in = jnp.einsum("td,tec->ecd", x, dispatch)   # (E, C, d)
        # exchange: slice e of my queues → expert e's owner; I receive every
        # device's queue for MY expert, stacked on the source axis
        recv = lax.all_to_all(expert_in, axis, split_axis=0,
                              concat_axis=0, tiled=True)     # (E, C, d)
        out = expert_fn(expert_params,
                        recv.reshape(E * capacity, d))       # (E*C, d)
        back = lax.all_to_all(out.reshape(E, capacity, -1), axis,
                              split_axis=0, concat_axis=0,
                              tiled=True)                    # (E, C, d)
        y = jnp.einsum("tec,ecd->td", combine, back)
        return y, lax.pmean(aux, axis)

    return run
