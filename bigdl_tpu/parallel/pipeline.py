"""Pipeline parallelism (GPipe schedule) over a ``pipe`` mesh axis.

TPU-first addition beyond the reference (BigDL 0.x has no pipeline
parallelism; its scale axis is Spark data parallelism only). The design is
the SPMD collective-pipeline formulation: every device holds ONE stage's
parameters (a homogeneous stack, e.g. transformer blocks), activations hop
stage→stage over ICI via ``ppermute`` inside a ``lax.scan`` over schedule
ticks, and microbatches fill the pipe GPipe-style (bubble =
(S-1)/(S-1+M)). Autodiff through ``scan``+``ppermute`` gives the backward
schedule for free — the transpose of a forward hop is the reverse hop, so
``jax.grad`` of a pipelined loss is itself a pipelined program.

Use inside ``shard_map``: stage params enter with their leading stage axis
sharded over ``pipe`` (spec ``P('pipe')``), the microbatched input
replicated (``P()``).
"""
from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

from ..utils.compat import axis_size, pvary


def gpipe(stage_fn: Callable, axis: str = "pipe"):
    """Build the per-device pipelined apply.

    ``stage_fn(stage_params, x) -> y`` must be shape-preserving (homogeneous
    stages — the transformer-block case). Returns ``run(params, x_stack)``
    for use inside ``shard_map`` over ``axis``:

    * ``params``: this device's stage parameters (leading stage axis of the
      stacked tree already stripped to size 1 by the shard_map spec; leaves
      are squeezed here).
    * ``x_stack``: (n_micro, micro_batch, ...) — replicated.
    * returns (n_micro, micro_batch, ...) — the last stage's outputs,
      broadcast to every device (masked psum), so downstream loss code is
      ordinary SPMD.
    """

    def run(params, x_stack):
        n_stages = axis_size(axis)
        idx = lax.axis_index(axis)
        n_micro = x_stack.shape[0]
        ticks = n_micro + n_stages - 1
        params = jax.tree_util.tree_map(
            lambda a: a[0] if a.ndim and a.shape[0] == 1 else a, params)

        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        # the carry varies per device from tick 1 on; mark the initial
        # zeros as axis-varying so the scan carry type is stable
        def _vary(a):
            return pvary(a, axis)
        zeros = _vary(jnp.zeros_like(x_stack[0]))
        outs0 = _vary(jnp.zeros_like(x_stack))

        def tick(carry, t):
            recv, outs = carry
            mb = jnp.clip(t, 0, n_micro - 1)
            # _vary: x_stack is pipe-invariant (replicated over the stage
            # axis) while recv is pipe-varying — under strict-VMA typing
            # (and composed meshes, e.g. dp x pipe) where() operands must
            # carry the same varying set
            inp = jnp.where(idx == 0, _vary(x_stack[mb]), recv)
            out = stage_fn(params, inp)
            # the last stage finishes microbatch m at tick t = m + S - 1
            m = t - (n_stages - 1)
            mclip = jnp.clip(m, 0, n_micro - 1)
            valid = jnp.logical_and(idx == n_stages - 1, m >= 0)
            outs = outs.at[mclip].set(
                jnp.where(valid, out, outs[mclip]))
            recv_next = lax.ppermute(out, axis, perm)
            return (recv_next, outs), None

        (_, outs), _ = lax.scan(tick, (zeros, outs0), jnp.arange(ticks))
        # broadcast the last stage's outputs to the whole mesh
        is_last = (idx == n_stages - 1).astype(x_stack.dtype)
        return lax.psum(outs * is_last, axis)

    return run


def stack_stage_params(per_stage_params):
    """Stack a list of per-stage param trees along a new leading axis
    (the axis ``shard_map`` shards over ``pipe``)."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs, 0),
                                  *per_stage_params)


def unstack_stage_params(stacked, n_stages: int):
    """Inverse of :func:`stack_stage_params`."""
    return [jax.tree_util.tree_map(lambda a, i=i: a[i], stacked)
            for i in range(n_stages)]
