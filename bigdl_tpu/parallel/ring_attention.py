"""Ring attention — sequence/context parallelism over a mesh ``seq`` axis.

The reference caps sequence length by single-node memory (its Transformer
materialises the full T×T attention matrix on one host). Here sequences are
sharded over the mesh: each device holds a T/n block of Q, K, V; K/V blocks
rotate around the ring via ``ppermute`` (ICI neighbor exchange, overlapped by
XLA with the local attention block matmuls) while a numerically-stable online
softmax accumulates the output. Memory per device is O(T/n), enabling contexts
n× longer — the long-context capability called for by the build goal.

Use inside ``shard_map`` with q/k/v sharded on the sequence dim, e.g.::

    f = shard_map(partial(ring_attention, axis='seq', causal=True),
                  mesh=mesh,
                  in_specs=(P(None, None, 'seq', None),) * 3,
                  out_specs=P(None, None, 'seq', None))
"""
from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from ..utils.compat import axis_size


def ring_attention(q, k, v, axis: str = "seq", causal: bool = False):
    """q, k, v: (B, H, Tblock, D) local blocks. Returns local output block."""
    n = axis_size(axis)
    idx = lax.axis_index(axis)
    tb = q.shape[-2]
    d = q.shape[-1]
    scale = 1.0 / math.sqrt(d)
    q_pos = idx * tb + jnp.arange(tb)  # global positions of my queries

    def one_block(carry, step):
        k_blk, v_blk, m, l, o = carry
        src = (idx - step) % n  # whose block I currently hold
        k_pos = src * tb + jnp.arange(tb)
        logits = jnp.einsum("bhqd,bhkd->bhqk", q, k_blk) * scale
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]
            logits = jnp.where(mask[None, None], logits, -jnp.inf)
        blk_max = jnp.max(logits, axis=-1)
        new_m = jnp.maximum(m, blk_max)
        # guard -inf rows (fully masked block): exp(-inf - -inf) → use where
        safe_m = jnp.where(jnp.isfinite(new_m), new_m, 0.0)
        p = jnp.exp(logits - safe_m[..., None])
        p = jnp.where(jnp.isfinite(logits), p, 0.0)
        correction = jnp.where(jnp.isfinite(m), jnp.exp(m - safe_m), 0.0)
        new_l = l * correction + jnp.sum(p, axis=-1)
        new_o = o * correction[..., None] + \
            jnp.einsum("bhqk,bhkd->bhqd", p, v_blk)
        # rotate K/V to the next rank (receive from previous)
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_next = lax.ppermute(k_blk, axis, perm)
        v_next = lax.ppermute(v_blk, axis, perm)
        return (k_next, v_next, new_m, new_l, new_o), None

    b, h = q.shape[0], q.shape[1]
    m0 = jnp.full((b, h, tb), -jnp.inf, q.dtype)
    l0 = jnp.zeros((b, h, tb), q.dtype)
    o0 = jnp.zeros_like(q)
    (k_f, v_f, m, l, o), _ = lax.scan(one_block, (k, v, m0, l0, o0),
                                      jnp.arange(n))
    return o / jnp.maximum(l[..., None], 1e-20)


def make_ring_attention(mesh, axis: str = "seq", causal: bool = False):
    """Build a shard_mapped ring attention over (B, H, T, D) global arrays."""
    from ..utils.compat import shard_map
    from jax.sharding import PartitionSpec as P
    spec = P(None, None, axis, None)
    return shard_map(partial(ring_attention, axis=axis, causal=causal),
                     mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_vma=False)
