"""Ring attention with flash-kernel local blocks and a hand-derived
ring backward.

The production long-context path: combines the two memory techniques —
sequence sharding over the mesh (``ring_attention.py``) and the Pallas
flash kernel within each block (``kernels/flash_attention.py``). Each
device holds T/n of Q, K, V; K/V blocks rotate via ``ppermute`` while the
per-block (output, logsumexp) pairs merge with the numerically-stable
log-sum-exp combination.

Backward is NOT autodiff-through-scan (which would save every block's
probabilities): it is the flash-attention-2 recomputation written as a
second ring pass — dK/dV accumulators *travel with* their K/V blocks
around the ring and arrive home after n hops, while dQ accumulates
locally (f32 accumulators, cast once on return). Residuals are only
(q, k, v, o, lse). On TPU both passes run the Pallas kernels, so memory
is O(T/n) per device in forward AND backward; the einsum path (CPU, or
``BIGDL_TPU_FLASH=off``) materialises one (T/n)² block at a time.

Dispatch honors the same ``BIGDL_TPU_FLASH`` policy as
``parallel/flash.py``: ``off`` forces einsum, ``interpret`` runs the
Pallas kernels in the interpreter (CPU tests exercise the kernel path),
and any kernel failure falls back to einsum with a logged warning —
never silently.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax

from ..utils.compat import axis_size, pvary

# ONE shared dispatch policy + warn-once registry (parallel/flash.py) and
# the kernels' own masking constant — no second copy to drift
from .flash import _warn_once, flash_mode as _block_mode
from ..kernels.flash_attention import NEG_INF


# ---------------------------------------------------------------------------
# per-block forward / backward (pluggable kernel)
# ---------------------------------------------------------------------------


def _block_attn_einsum(q, kb, vb, scale, causal_diag):
    s = jnp.einsum("bhqd,bhkd->bhqk", q, kb).astype(jnp.float32) * scale
    if causal_diag:
        t, tk = q.shape[-2], kb.shape[-2]
        mask = jnp.arange(t)[:, None] >= jnp.arange(tk)[None, :]
        s = jnp.where(mask[None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("bhqk,bhkd->bhqd",
                   (p / jnp.maximum(l, 1e-30)).astype(q.dtype), vb)
    lse = (m + jnp.log(jnp.maximum(l, 1e-30)))[..., 0]
    return o, lse


def _block_attn(q, kb, vb, scale, diag: bool, causal: bool, axes=None):
    """(o, lse) for one K/V block. ``diag`` — block holds the same global
    positions as q (triangular mask applies). ``axes``: mesh axes the
    blocks vary over (a bare string means one axis)."""
    axes = _as_axes(axes)
    use_causal = causal and diag
    mode = _block_mode()
    if mode in ("pallas", "interpret"):
        try:
            from ..kernels.flash_attention import _flash_fwd
            return _flash_fwd(q, kb, vb, use_causal, scale, 512, 512,
                              mode == "interpret",
                              vma=set(axes) if axes else None)
        except Exception as e:  # pragma: no cover - depends on backend
            _warn_once("ring_fwd", "ring-flash forward kernel failed (%s); "
                       "falling back to einsum blocks", e)
    return _block_attn_einsum(q, kb, vb, scale, use_causal)


def _block_bwd_einsum(q, kb, vb, lse, delta, do, scale, causal_diag):
    s = jnp.einsum("bhqd,bhkd->bhqk", q, kb).astype(jnp.float32) * scale
    if causal_diag:
        t, tk = q.shape[-2], kb.shape[-2]
        mask = jnp.arange(t)[:, None] >= jnp.arange(tk)[None, :]
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jnp.exp(s - lse[..., None])
    dv = jnp.einsum("bhqk,bhqd->bhkd", p, do.astype(jnp.float32))
    dp = jnp.einsum("bhqd,bhkd->bhqk", do.astype(jnp.float32),
                    vb.astype(jnp.float32))
    ds = p * (dp - delta[..., None]) * scale
    dq = jnp.einsum("bhqk,bhkd->bhqd", ds, kb.astype(jnp.float32))
    dk = jnp.einsum("bhqk,bhqd->bhkd", ds, q.astype(jnp.float32))
    return dq, dk, dv


def _block_bwd(q, kb, vb, o, lse, delta, do, scale, diag: bool,
               causal: bool, axes=None):
    """One block's (dq, dk, dv) contributions, f32, from GLOBAL (o, lse)
    and precomputed GLOBAL delta = rowsum(dO*O) (hoisted out of the ring
    scan — it is hop-invariant)."""
    axes = _as_axes(axes)
    use_causal = causal and diag
    mode = _block_mode()
    if mode in ("pallas", "interpret"):
        try:
            from ..kernels.flash_attention import _flash_bwd
            # out_dtype=f32: per-hop contributions must not round at the
            # input dtype before the ring accumulators sum them
            return _flash_bwd(use_causal, scale, 512, 512,
                              mode == "interpret", (q, kb, vb, o, lse), do,
                              delta=delta, out_dtype=jnp.float32,
                              vma=set(axes) if axes else None)
        except Exception as e:  # pragma: no cover - depends on backend
            _warn_once("ring_bwd", "ring-flash backward kernel failed "
                       "(%s); falling back to einsum blocks", e)
    return _block_bwd_einsum(q, kb, vb, lse, delta, do, scale, use_causal)


# ---------------------------------------------------------------------------
# ring forward / backward
# ---------------------------------------------------------------------------


def _as_axes(axes):
    """Normalize an axis spec: bare string -> 1-tuple; None/tuple pass."""
    return (axes,) if isinstance(axes, str) else axes


def _vary(x, axes):
    """Mark a fresh constant as varying over ``axes`` (strict-VMA
    shard_map requires cond branches / scan carries to agree)."""
    return pvary(x, axes)


def _vma_axes(x, ring_axis):
    """The FULL set of mesh axes ``x`` varies over inside this shard_map.
    Under a composed mesh (e.g. dp x sp) the blocks vary over more than
    the ring axis, and every fresh constant / kernel output must carry
    the same set or strict-VMA cond/scan typing rejects the program."""
    try:
        vma = jax.typeof(x).vma
        if vma:
            return tuple(sorted(vma))
    except (AttributeError, TypeError):  # older jax: no typeof/.vma
        pass
    return (ring_axis,) if ring_axis else ()


def _merge(o, lse, o_i, lse_i):
    new_lse = jnp.logaddexp(lse, lse_i)
    w = jnp.exp(lse - new_lse)[..., None].astype(o.dtype)
    w_i = jnp.exp(lse_i - new_lse)[..., None].astype(o.dtype)
    return o * w + o_i * w_i, new_lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def ring_flash_attention(q, k, v, axis: str = "seq",
                         causal: bool = False):
    """q, k, v: (B, H, Tblock, D) local blocks inside ``shard_map``."""
    o, lse = _ring_fwd(q, k, v, axis, causal)
    return o


def _ring_fwd(q, k, v, axis, causal):
    n = axis_size(axis)
    idx = lax.axis_index(axis)
    vaxes = _vma_axes(q, axis)
    scale = 1.0 / math.sqrt(q.shape[-1])
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, s):
        k_blk, v_blk, o, lse = carry
        src = (idx - s) % n  # whose block I hold this step
        if causal:
            b, h, tb, d = q.shape
            zeros = (jnp.zeros_like(q),
                     _vary(jnp.full((b, h, tb), NEG_INF, jnp.float32),
                           vaxes))
            # later blocks fully invisible: skip the compute entirely;
            # diagonal needs the triangular mask; earlier fully visible
            o_i, lse_i = lax.cond(
                src > idx,
                lambda: zeros,
                lambda: lax.cond(
                    src == idx,
                    lambda: _block_attn(q, k_blk, v_blk, scale, True,
                                        True, vaxes),
                    lambda: _block_attn(q, k_blk, v_blk, scale, False,
                                        True, vaxes)))
        else:
            o_i, lse_i = _block_attn(q, k_blk, v_blk, scale, False, False,
                                     vaxes)
        o, lse = _merge(o, lse, o_i, lse_i.astype(lse.dtype))
        k_next = lax.ppermute(k_blk, axis, perm)
        v_next = lax.ppermute(v_blk, axis, perm)
        return (k_next, v_next, o, lse), None

    b, h, tb, _ = q.shape
    o0 = jnp.zeros_like(q)
    lse0 = _vary(jnp.full((b, h, tb), NEG_INF, jnp.float32), vaxes)
    (k_f, v_f, o, lse), _ = lax.scan(step, (k, v, o0, lse0),
                                     jnp.arange(n))
    return o, lse


def _ring_vjp_fwd(q, k, v, axis, causal):
    o, lse = _ring_fwd(q, k, v, axis, causal)
    return o, (q, k, v, o, lse)


def _ring_vjp_bwd(axis, causal, res, do):
    """Second ring pass: dK/dV ride along with their K/V blocks; dQ stays.

    Flash-attention-2 recomputation from global (o, lse) — each block's
    contribution is independent given them, so on TPU the per-block work
    is the Pallas backward kernels themselves."""
    q, k, v, o, lse = res
    n = axis_size(axis)
    idx = lax.axis_index(axis)
    vaxes = _vma_axes(q, axis)
    scale = 1.0 / math.sqrt(q.shape[-1])
    perm = [(i, (i + 1) % n) for i in range(n)]
    # hop-invariant: compute the global rowsum(dO*O) once, not per hop
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)

    def step(carry, s):
        k_blk, v_blk, dk_blk, dv_blk, dq = carry
        src = (idx - s) % n
        zeros = (jnp.zeros_like(dq), jnp.zeros_like(dk_blk),
                 jnp.zeros_like(dv_blk))
        if causal:
            dq_i, dk_i, dv_i = lax.cond(
                src > idx,
                lambda: zeros,
                lambda: lax.cond(
                    src == idx,
                    lambda: _block_bwd(q, k_blk, v_blk, o, lse, delta, do,
                                       scale, True, True, vaxes),
                    lambda: _block_bwd(q, k_blk, v_blk, o, lse, delta, do,
                                       scale, False, True, vaxes)))
        else:
            dq_i, dk_i, dv_i = _block_bwd(q, k_blk, v_blk, o, lse, delta,
                                          do, scale, False, False, vaxes)
        dq = dq + dq_i
        dk_blk = dk_blk + dk_i
        dv_blk = dv_blk + dv_i
        k_next = lax.ppermute(k_blk, axis, perm)
        v_next = lax.ppermute(v_blk, axis, perm)
        dk_next = lax.ppermute(dk_blk, axis, perm)
        dv_next = lax.ppermute(dv_blk, axis, perm)
        return (k_next, v_next, dk_next, dv_next, dq), None

    init = (k, v, _vary(jnp.zeros(k.shape, jnp.float32), vaxes),
            _vary(jnp.zeros(v.shape, jnp.float32), vaxes),
            _vary(jnp.zeros(q.shape, jnp.float32), vaxes))
    (k_f, v_f, dk, dv, dq), _ = lax.scan(step, init, jnp.arange(n))
    # after n hops every dK/dV block is back on its owner; cast once
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


ring_flash_attention.defvjp(_ring_vjp_fwd, _ring_vjp_bwd)


def make_ring_flash_attention(mesh, axis: str = "seq",
                              causal: bool = False):
    """shard_mapped ring-flash attention over (B, H, T, D) global arrays."""
    from ..utils.compat import shard_map
    from jax.sharding import PartitionSpec as P
    spec = P(None, None, axis, None)
    return shard_map(
        functools.partial(ring_flash_attention, axis=axis, causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
