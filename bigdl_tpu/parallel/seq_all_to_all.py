"""All-to-all (Ulysses-style) sequence parallelism.

The second of the two sequence-parallel schemes the build goal calls for
("ring attention or all-to-all sequence/context parallelism"):

- **Ring** (``parallel/ring_attention.py`` / ``ring_flash.py``): K/V blocks
  rotate via ``ppermute``; communication is n-1 neighbor exchanges riding
  ICI, overlapped with the block matmuls. Memory O(T/n); works for any
  head count.
- **All-to-all** (this module): ONE head-scatter/seq-gather ``all_to_all``
  converts sequence sharding (B, H, T/n, D) into head sharding
  (B, H/n, T, D); attention then runs DENSE locally — which means the
  fused Pallas flash kernel applies unchanged — and one inverse
  ``all_to_all`` restores sequence sharding. Communication is 2
  all-to-alls of the activations regardless of n (vs the ring's n-1
  hops), at the cost of requiring ``num_heads % n == 0`` and O(T)
  local attention memory per head-shard (flash keeps that O(T) in
  activations, not O(T^2)).

Reference baseline: the reference Transformer materialises full T×T
attention on one host (``nn/Transformer.scala``) — no sequence
parallelism exists there; both schemes here are TPU-first capabilities.

Use inside ``shard_map`` with activations sharded on the sequence dim::

    f = shard_map(partial(a2a_attention, axis="seq", causal=True),
                  mesh=mesh,
                  in_specs=(P(None, None, "seq", None),) * 3,
                  out_specs=P(None, None, "seq", None))
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..utils.compat import axis_size


def a2a_attention(q, k, v, axis: str = "seq", causal: bool = False,
                  use_flash: bool = True):
    """Ulysses-style sequence-parallel attention.

    q, k, v: (B, H, T/n, D) local sequence blocks (full head count).
    Returns the local (B, H, T/n, D) output block. Requires H % n == 0.

    All-to-all #1 scatters heads / gathers sequence → (B, H/n, T, D);
    dense (flash) attention runs over the full sequence for the local
    head subset; all-to-all #2 inverts the exchange. Sequence blocks
    concatenate in axis-index order, so global token positions are
    correct and causal masking needs no position bookkeeping.
    """
    n = axis_size(axis)
    h = q.shape[1]
    if h % n:
        raise ValueError(
            f"a2a (Ulysses) sequence parallelism needs num_heads ({h}) "
            f"divisible by the '{axis}' axis size ({n}); use ring "
            "attention otherwise")

    def scatter_heads(x):
        return lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                              tiled=True)

    qh, kh, vh = scatter_heads(q), scatter_heads(k), scatter_heads(v)
    if use_flash:
        from .flash import flash_attention
        o = flash_attention(qh, kh, vh, causal=causal)
    else:
        from ..nn.attention import causal_mask, dot_product_attention
        mask = causal_mask(qh.shape[-2]) if causal else None
        o = dot_product_attention(qh, kh, vh, mask)
    return lax.all_to_all(o, axis, split_axis=2, concat_axis=1, tiled=True)
