"""Sequence-sharded KV-cache decode — long-context DISTRIBUTED serving.

A 100k-token conversation's KV cache can exceed one chip's HBM even
with GQA and quantization. Here the cache is sharded over a mesh axis
along TIME (device d owns global positions d*Tl .. (d+1)*Tl - 1); each
decode step runs one partial attention per device over its shard and
combines the per-device online-softmax statistics with three tiny
collectives (pmax of the running max, psum of the rescaled weights and
weighted values) — the same math that merges key blocks inside the
flash kernel, applied across devices. Per step each device touches only
its 1/n of the cache: HBM traffic AND cache memory both scale down with
the axis.

Beyond the reference: its inference path (``PredictionService``/local
Predictor) is data-parallel over complete models; the reference never
shards a single sequence's state. The training-side analog of this
module is ring attention (``parallel/ring_attention.py``); at decode
there is one query token, so no ppermute ring is needed — statistics
merging is cheaper than rotating K/V.

Correctness oracle: ``tests/test_distributed.py`` drives a multi-step
decode against the single-device cached path — token-identical.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from ..utils.compat import axis_size, shard_map
from jax.sharding import Mesh, PartitionSpec as P


def _partial_decode_attention(q, k_shard, v_shard, pos, axis):
    """Per-device body (runs inside shard_map): q (B, H, 1, D)
    replicated; k/v shards (B, kvH, Tl, D) holding this device's global
    positions d*Tl..; returns the globally combined (B, H, 1, D)."""
    d_ix = jax.lax.axis_index(axis)
    tl = k_shard.shape[2]
    base = d_ix * tl
    dh = q.shape[-1]
    groups = q.shape[1] // k_shard.shape[1]
    b, h, _, dd = q.shape
    # the grouped form covers MHA too: groups == 1 makes the reshape a
    # no-op and the einsum the plain (B, H, 1, Tl) score
    qg = q.reshape(b, h // groups, groups, dd)
    s = jnp.einsum("bkgd,bktd->bkgt", qg,
                   k_shard) / math.sqrt(dh)            # (B,kvH,G,Tl)
    keep = (base + jnp.arange(tl)) <= pos
    s = jnp.where(keep[None, None, None], s, -1e30)
    m_loc = jnp.max(s, axis=-1)                        # (B,kvH,G)
    m = jax.lax.pmax(m_loc, axis)
    p = jnp.exp(s - m[..., None])
    l = jax.lax.psum(jnp.sum(p, axis=-1), axis)        # (B,kvH,G)
    o = jnp.einsum("bkgt,bktd->bkgd", p.astype(v_shard.dtype),
                   v_shard)
    o = jax.lax.psum(o, axis)
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return out.reshape(b, h, 1, dh).astype(q.dtype)


def _shard_write(cache, x_t, pos, axis):
    """Write x_t (B, kvH, 1, D) at GLOBAL position ``pos`` into the
    device's time shard — a no-op on every device but the owner."""
    d_ix = jax.lax.axis_index(axis)
    tl = cache.shape[2]
    local = pos - d_ix * tl
    owns = jnp.logical_and(local >= 0, local < tl)
    upd = jax.lax.dynamic_update_slice(
        cache, x_t.astype(cache.dtype),
        (0, 0, jnp.clip(local, 0, tl - 1), 0))
    return jnp.where(owns, upd, cache)


def make_seq_sharded_decoder(mesh: Mesh, axis: str = "seq"):
    """Build a decode step over a time-sharded KV cache.

    The returned ``decode(q, k_t, v_t, k_cache, v_cache, pos)`` writes
    this step's K/V (B, kvH, 1, D) at global ``pos`` into the owning
    device's shard, attends q (B, nH, 1, D) over every valid position,
    and returns (out, k_cache, v_cache). Cache arrays are
    (B, kvH, Tmax, D) global, sharded P(None, None, axis, None) — Tmax
    must divide by the axis size. GQA welcome (compact shards).

    Capacity: ``pos`` MUST be < Tmax — like every fixed-size KV cache
    here, a step past capacity is not representable; with a traced
    ``pos`` it cannot raise, and the write would be silently dropped
    (no device owns the position), so size Tmax for the full
    generation up front. ``pos`` is a traced scalar: jit ONE step for
    the whole loop, and donate the cache buffers —
    ``jax.jit(decode, donate_argnums=(3, 4))`` — or each step pays a
    full extra cache copy for the functional update."""

    def body(q, k_t, v_t, k_cache, v_cache, pos):
        k_cache = _shard_write(k_cache, k_t, pos, axis)
        v_cache = _shard_write(v_cache, v_t, pos, axis)
        out = _partial_decode_attention(q, k_cache, v_cache, pos, axis)
        return out, k_cache, v_cache

    spec_c = P(None, None, axis, None)
    return shard_map(
        body, mesh=mesh,
        in_specs=(P(), P(), P(), spec_c, spec_c, P()),
        out_specs=(P(), spec_c, spec_c),
        check_vma=False)
