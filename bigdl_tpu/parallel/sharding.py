"""Sharding helpers: batch/param placement and tensor-parallel rules.

The reference has no tensor parallelism (its only mode is data parallel over
Spark partitions); TP here is a new TPU-native capability expressed entirely
through PartitionSpecs — XLA inserts the collectives.
"""
from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def replicated(mesh: Mesh):
    return NamedSharding(mesh, P())


def data_sharding(mesh: Mesh, axis: str = "data"):
    """Shard the leading (batch) dim."""
    return NamedSharding(mesh, P(axis))


def shard_batch(batch, mesh: Mesh, axis: str = "data"):
    """Device-put a host batch with the leading dim split over ``axis``."""
    sh = data_sharding(mesh, axis)

    def put(x):
        if x is None:
            return None
        return jax.device_put(np.asarray(x), sh)
    return jax.tree_util.tree_map(put, batch)


def shard_params(params, mesh: Mesh):
    """Replicate params across the mesh."""
    sh = replicated(mesh)
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sh), params)


def tp_linear_rules(axis: str = "model"):
    """PartitionSpecs for a column→row parallel Linear pair (Megatron-style):
    first Linear's (out, in) weight column-sharded, second row-sharded;
    activations stay sharded on the hidden dim between them, one psum at the
    end — XLA derives this from the specs."""
    return {
        "column": {"weight": P(axis, None), "bias": P(axis)},
        "row": {"weight": P(None, axis), "bias": P()},
    }


def constraint(x, mesh: Mesh, spec: P):
    """jax.lax.with_sharding_constraint wrapper."""
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
