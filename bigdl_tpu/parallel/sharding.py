"""Sharding helpers: batch/param placement and tensor-parallel rules.

The reference has no tensor parallelism (its only mode is data parallel over
Spark partitions); TP here is a new TPU-native capability expressed entirely
through PartitionSpecs — XLA inserts the collectives.
"""
from __future__ import annotations

import logging

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def replicated(mesh: Mesh):
    return NamedSharding(mesh, P())


def data_sharding(mesh: Mesh, axis: str = "data"):
    """Shard the leading (batch) dim."""
    return NamedSharding(mesh, P(axis))


def is_multi_process(mesh: Mesh) -> bool:
    return len({d.process_index for d in mesh.devices.flat}) > 1


def put_global(x, mesh: Mesh, spec) -> jax.Array:
    """Place a host array THAT EVERY PROCESS HOLDS IN FULL (params,
    optimizer state — same seed everywhere) onto the mesh with ``spec``.

    Single-controller: plain device_put. Multi-controller: device_put
    cannot address remote devices, so each process materialises only its
    addressable shards via make_array_from_callback slicing its full copy
    (the multi-host analog of the reference broadcasting the model to every
    Spark executor, DistriOptimizer.scala init)."""
    sh = NamedSharding(mesh, spec)
    if is_multi_process(mesh):
        xa = np.asarray(x)
        return jax.make_array_from_callback(xa.shape, sh,
                                            lambda idx: xa[idx])
    # single-controller: hand device-resident arrays straight to
    # device_put (on-device reshard, no host round trip)
    return jax.device_put(x, sh)


def gather_to_host(tree, mesh: Mesh):
    """Fetch a (possibly cross-process sharded) tree to host numpy.

    Multi-controller: leaves sharded over remote devices are not
    addressable, so first jit-reshard everything to replicated (a
    collective — every process must call this at the same point, which
    holds for symmetric triggers like checkpoints), then fetch."""
    import jax.tree_util as jtu
    if is_multi_process(mesh):
        rep = NamedSharding(mesh, P())
        tree = jax.jit(lambda t: t, out_shardings=jtu.tree_map(
            lambda _: rep, tree))(tree)
    return jtu.tree_map(lambda a: np.asarray(a), tree)


def shard_batch(batch, mesh: Mesh, axis: str = "data"):
    """Device-put a host batch with the leading dim split over ``axis``.

    Multi-controller: each process's batch is its LOCAL share (each Spark
    partition reads its own split in the reference); the global batch is
    the concatenation over processes."""
    sh = data_sharding(mesh, axis)
    multi = is_multi_process(mesh)

    def put(x):
        if x is None:
            return None
        x = np.asarray(x)
        if multi:
            return jax.make_array_from_process_local_data(sh, x)
        return jax.device_put(x, sh)
    return jax.tree_util.tree_map(put, batch)


def shard_stacked_batch(batch, mesh: Mesh, axis: str = "data"):
    """Place a superstep-stacked ``[K, batch, ...]`` host batch: the
    microbatch (scan) dim replicated, the per-step batch dim split over
    ``axis`` — each of the K fused steps then runs with exactly the
    layout ``shard_batch`` gives a single step. Multi-controller: the
    local stack concatenates over processes along dim 1, matching the
    per-step local-split contract of ``shard_batch``."""
    multi = is_multi_process(mesh)

    def put(x):
        if x is None:
            return None
        x = np.asarray(x)
        sh = NamedSharding(mesh, P(None, axis) if x.ndim >= 2 else P())
        if multi:
            return jax.make_array_from_process_local_data(sh, x)
        return jax.device_put(x, sh)
    return jax.tree_util.tree_map(put, batch)


def shard_params(params, mesh: Mesh):
    """Replicate params across the mesh (multi-controller safe)."""
    return jax.tree_util.tree_map(
        lambda x: put_global(x, mesh, P()), params)


def surviving_devices(mesh: Mesh, lost_processes=()):
    """Devices of ``mesh`` NOT owned by the lost processes — the raw
    material an elastic restart re-derives the mesh from. Order is
    preserved (mesh iteration order), so the reshaped mesh keeps the
    survivors' relative layout."""
    lost = set(lost_processes)
    return [d for d in mesh.devices.flat if d.process_index not in lost]


def mesh_after_loss(mesh: Mesh, lost_processes=(), devices=None,
                    axis: str = "data") -> Mesh:
    """Re-derive a mesh after host loss: same axis names, the ``axis``
    dimension shrunk to what the surviving devices support, every other
    axis kept at its original size (tensor/sequence-parallel groups must
    stay intact — only the data-parallel degree is elastic). Explicit
    ``devices`` (e.g. a simulated-membership subset in the CPU fault
    drill) override the ``lost_processes`` filter.

    Model/seq groups stay WHOLE: a new mesh row is only ever one of the
    ORIGINAL mesh's rows that survived intact — regrouping leftover
    devices from different broken rows would be numerically fine after
    resharding but silently turn every model-parallel collective into a
    cross-host (DCN instead of ICI) hop. Survivors stranded in a broken
    row are DROPPED and the drop is logged loudly so an operator sees
    the capacity loss; no intact row surviving raises (a partial group
    cannot run the program at all)."""
    if devices is None:
        devices = surviving_devices(mesh, lost_processes)
    devices = list(devices)
    if not devices:
        raise ValueError("no surviving devices to build a mesh from")
    axes = tuple(mesh.axis_names)
    if axis not in axes:
        raise ValueError(f"mesh has no {axis!r} axis (axes: {axes})")
    ax_idx = axes.index(axis)
    other = 1
    for a in axes:
        if a != axis:
            other *= mesh.shape[a]
    if other == 1:
        # pure data-parallel: every survivor is a whole row
        shape = tuple(len(devices) if a == axis else 1 for a in axes)
        return Mesh(np.array(devices).reshape(shape), axes)
    surv = set(devices)
    rows = np.moveaxis(mesh.devices, ax_idx, 0).reshape(
        mesh.shape[axis], other)
    whole = [row for row in rows if all(d in surv for d in row)]
    new_axis = len(whole)
    if new_axis < 1:
        raise ValueError(
            f"{len(devices)} surviving devices leave no whole "
            f"{axis!r} row of {other} devices intact (mesh axes {axes})")
    if new_axis * other < len(devices):
        logging.getLogger(__name__).warning(
            "mesh_after_loss: dropping %d surviving devices stranded in "
            "broken %r rows of %d (keeping %d of %d)",
            len(devices) - new_axis * other, axis, other,
            new_axis * other, len(devices))
    arr = np.moveaxis(
        np.array([d for row in whole for d in row]).reshape(
            (new_axis,) + tuple(s for a, s in zip(axes, mesh.devices.shape)
                                if a != axis)),
        0, ax_idx)
    return Mesh(arr, axes)


def transformer_tp_specs(params, axis: str = "model"):
    """PartitionSpec tree for Transformer/TransformerLM params —
    Megatron-style tensor parallelism: attention q/k/v column-sharded,
    output projection row-sharded; FFN w1 (and SwiGLU's w3 gate)
    column-, w2 row-sharded; everything else (embedding, norms, biases
    except b1) replicated. Works for training (the ``__graft_entry__``
    dryrun jits the full train step over these) AND inference:
    ``jax.jit(model.generate)`` over params placed with these specs
    decodes tensor-parallel, XLA inserting the per-layer psum — the
    multi-chip serving path (tested on the 8-device mesh in
    tests/test_distributed.py). Head-count caveat: the column shards
    must not split a head — num_heads (and num_kv_heads, and
    filter_size) should be divisible by the axis size."""

    def spec_for(path, leaf):
        keys = [str(getattr(p, "key", getattr(p, "idx", p))) for p in path]
        joined = "/".join(keys)
        if leaf.ndim == 2:
            if any(k in joined for k in ("wq", "wk", "wv")):
                return P(None, axis)
            if "wo" in joined:
                return P(axis, None)
            if "w1" in joined or "w3" in joined:   # w3: SwiGLU gate
                return P(None, axis)
            if "w2" in joined:
                return P(axis, None)
        if "b1" in joined and leaf.ndim == 1:
            return P(axis)
        return P()

    return jax.tree_util.tree_map_with_path(spec_for, params)


def fsdp_specs(params, mesh: Mesh, axis: str = "data",
               min_elems: int = 16384):
    """ZeRO-3 / FSDP-style parameter sharding specs: every leaf with at
    least ``min_elems`` elements is sharded along its largest
    ``axis``-divisible dimension; small leaves (biases, norm scales)
    stay replicated. Placed with these specs, parameters (and, under
    ``jit``, the optimizer state that mirrors them) live at 1/N memory
    per device; XLA all-gathers each layer's shards just-in-time at its
    use site and re-shards gradients with reduce-scatter — the ZeRO-3
    communication schedule derived from placement alone, no wrapper
    machinery. Compose with a data-sharded batch for standard
    FSDP training (tests/test_distributed.py proves step-for-step
    equality with replicated DP). Beyond the reference: its parameter
    server shards optimizer state only (ZeRO-1 analog,
    ``AllReduceParameter.scala``); the r3 ZeRO-1 path remains in
    ``optim.DistriOptimizer(zero1=True)``."""
    n = mesh.shape[axis]

    def spec(leaf):
        if not hasattr(leaf, "shape") or leaf.size < min_elems:
            return P()
        dims = sorted(range(leaf.ndim), key=lambda i: -leaf.shape[i])
        for i in dims:
            if leaf.shape[i] % n == 0:
                parts = [None] * leaf.ndim
                parts[i] = axis
                return P(*parts)
        return P()

    return jax.tree_util.tree_map(spec, params)


#: mesh axes a serving batch shards over when present — Levanter's
#: ``P(("replica", "data"))`` idiom: one physical mesh carries both the
#: replica-parallel degree (whole engine replicas) and the data-parallel
#: degree (rows within a replica's dispatch), and the request batch
#: splits its leading dim across BOTH.
SERVING_BATCH_AXES = ("replica", "data")


def serving_batch_spec(mesh: Mesh, axes=SERVING_BATCH_AXES) -> P:
    """PartitionSpec for a serving micro-batch's leading dim on ``mesh``:
    sharded jointly over whichever of ``axes`` the mesh actually has
    (``P(("replica", "data"))`` on a replica×data mesh, ``P("data")`` on
    a data-only mesh), replicated when the mesh has neither (a pure
    tensor-parallel mesh serves the whole batch on every shard — the
    parallelism is inside the layers)."""
    present = tuple(a for a in axes if a in mesh.axis_names)
    return P(present) if present else P()


def batch_shard_count(mesh: Mesh, spec: P) -> int:
    """How many ways ``spec`` splits the leading batch dim on ``mesh`` —
    the serving engine's bucket floor: every padded bucket must be a
    multiple of this so the shards divide evenly."""
    if not spec or spec[0] is None:
        return 1
    first = spec[0]
    axes = first if isinstance(first, tuple) else (first,)
    n = 1
    for a in axes:
        n *= mesh.shape[a]
    return n


def serving_param_specs(params, mesh: Mesh, placement,
                        model_axis: str = "model",
                        data_axis: str = "data"):
    """Resolve a serving-engine param placement into a PartitionSpec
    tree: ``"tp"`` → :func:`transformer_tp_specs` (Megatron-style —
    models that don't fit one chip serve over the ``model`` axis),
    ``"fsdp"`` → :func:`fsdp_specs` over ``data_axis`` (big leaves at
    1/N memory, all-gathered just-in-time), ``"replicated"``/None →
    every leaf replicated, a callable → ``placement(params)``, anything
    else is taken as an explicit spec tree."""
    if placement is None or placement == "replicated":
        return jax.tree_util.tree_map(lambda _: P(), params)
    if callable(placement):
        return placement(params)
    if placement == "tp":
        return transformer_tp_specs(params, axis=model_axis)
    if placement == "fsdp":
        return fsdp_specs(params, mesh, axis=data_axis)
    return placement


def place_with_specs(tree, mesh: Mesh, specs):
    """Device-put a params/state pytree onto ``mesh`` leaf-by-leaf with
    the matching PartitionSpec tree (multi-controller safe via
    :func:`put_global`) — the sharded-load half of a serving hot swap:
    the registry runs this on the PUBLISHING thread, so traffic keeps
    flowing on the active version while the new one lands sharded."""
    if tree is None:
        return None
    return jax.tree_util.tree_map(
        lambda x, s: put_global(x, mesh, s), tree, specs)


def tp_linear_rules(axis: str = "model"):
    """PartitionSpecs for a column→row parallel Linear pair (Megatron-style):
    first Linear's (out, in) weight column-sharded, second row-sharded;
    activations stay sharded on the hidden dim between them, one psum at the
    end — XLA derives this from the specs."""
    return {
        "column": {"weight": P(axis, None), "bias": P(axis)},
        "row": {"weight": P(None, axis), "bias": P()},
    }


def constraint(x, mesh: Mesh, spec: P):
    """jax.lax.with_sharding_constraint wrapper."""
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
