from .quantize import (quantize, QuantizedLinear, QuantizedSpatialConvolution,
                       quantize_weight)
from .calibration import (calibrate, fold_batchnorm, quantizable_paths,
                          Observer, MinMaxObserver, MovingAverageObserver,
                          PercentileObserver)
