from .quantize import (quantize, QuantizedLinear, QuantizedSpatialConvolution,
                       quantize_weight)
from .calibration import (calibrate, fold_batchnorm, quantizable_paths,
                          Observer, MinMaxObserver, MovingAverageObserver,
                          PercentileObserver)
from .lm import (QuantizedWeight, QuantizedWeightInt4, quantize_lm_params,
                 quantize_weight_int8, quantize_weight_int4,
                 lm_quantized_bytes)
