from .quantize import (quantize, QuantizedLinear, QuantizedSpatialConvolution,
                       quantize_weight)
from .calibration import (calibrate, fold_batchnorm, quantizable_paths,
                          Observer, MinMaxObserver, MovingAverageObserver,
                          PercentileObserver)
from .lm import (QuantizedWeight, quantize_lm_params,
                 quantize_weight_int8, lm_quantized_bytes)
