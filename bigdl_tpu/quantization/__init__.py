from .quantize import (quantize, QuantizedLinear, QuantizedSpatialConvolution,
                       quantize_weight)
