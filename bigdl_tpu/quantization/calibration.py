"""Static int8 calibration (SURVEY §2.9 r2 item).

Parity target: the reference's DL-Boost int8 flow (``nn/quantized/``) carries
per-layer activation thresholds baked at quantize time; here a calibration
pass runs representative batches through the *float* model with observers
attached to every quantizable layer, records activation ranges, and
``quantize(model, calibration=...)`` then uses the static scales instead of
the dynamic per-batch max — removing the runtime max-reduce and making the
quantized graph fully static for XLA.

Observers:
- ``MinMaxObserver`` — running max of |x| (the reference's default).
- ``MovingAverageObserver`` — EMA of per-batch max |x| (robust to one-off
  spikes; torch.quantization-style).
- ``PercentileObserver`` — max of a per-batch percentile of |x| (clips
  outliers; mkldnn-calibration-style).

Conv+BN fusion: ``fold_batchnorm`` folds inference-mode BatchNormalization
into a preceding Linear/SpatialConvolution inside Sequential containers (the
analog of the reference's fusion table for quantization-friendly graphs).
"""
from __future__ import annotations

from typing import Callable, Dict, Iterable, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..nn.module import Module, Container
from ..nn.linear import Linear
from ..nn.conv import SpatialConvolution
from ..nn.norm import BatchNormalization
from ..nn.containers import Sequential


class Observer:
    """Tracks the absolute activation range of one layer's input."""

    def update(self, x) -> None:
        raise NotImplementedError

    @property
    def absmax(self) -> float:
        raise NotImplementedError

    @property
    def scale(self) -> float:
        return max(float(self.absmax), 1e-8) / 127.0


class MinMaxObserver(Observer):
    def __init__(self):
        self._max = 0.0

    def update(self, x):
        self._max = max(self._max, float(jnp.max(jnp.abs(x))))

    @property
    def absmax(self):
        return self._max


class MovingAverageObserver(Observer):
    def __init__(self, momentum: float = 0.9):
        self.momentum = momentum
        self._avg = None

    def update(self, x):
        batch_max = float(jnp.max(jnp.abs(x)))
        self._avg = batch_max if self._avg is None else \
            self.momentum * self._avg + (1 - self.momentum) * batch_max

    @property
    def absmax(self):
        return self._avg or 0.0


class PercentileObserver(Observer):
    def __init__(self, percentile: float = 99.99):
        self.percentile = percentile
        self._max = 0.0

    def update(self, x):
        p = float(np.percentile(np.abs(np.asarray(x)), self.percentile))
        self._max = max(self._max, p)

    @property
    def absmax(self):
        return self._max


def _walk(module: Module, path: str = ""):
    """Yield (path, module) with the same keying used by
    quantize._quantize_rec (shared child_path) so scales line up."""
    from .quantize import child_path
    yield path, module
    if isinstance(module, Container):
        for i, child in enumerate(module.modules):
            yield from _walk(child, child_path(path, i))


def quantizable_paths(model: Module) -> List[Tuple[str, Module]]:
    """Layers quantize() will convert — same isinstance tests as
    quantize._quantize_rec (covers SpatialShare/DilatedConvolution and
    SpatialSeparableConvolution too)."""
    from .quantize import QuantizedLinear
    from ..nn.conv import SpatialSeparableConvolution
    from ..nn.sparse import SparseLinear
    return [(p, m) for p, m in _walk(model)
            if (isinstance(m, Linear) and not isinstance(
                m, (QuantizedLinear, SparseLinear)))
            or isinstance(m, (SpatialConvolution,
                              SpatialSeparableConvolution))]


def calibrate(model: Module, batches: Iterable,
              observer_factory: Callable[[], Observer] = MinMaxObserver,
              ) -> Dict[str, float]:
    """Run ``batches`` through the float model in eval mode, observing the
    input range of every quantizable layer. → {layer_path: activation_scale}.
    """
    model.ensure_initialized()
    was_training = model.train_mode
    model.evaluate()
    observers: Dict[str, Observer] = {}
    hooked: List[Module] = []
    try:
        for path, mod in quantizable_paths(model):
            obs = observers[path] = observer_factory()

            def wrapped(params, state, x, training, rng,
                        _orig=mod._apply, _obs=obs):
                _obs.update(x)
                return _orig(params, state, x, training, rng)

            mod._apply = wrapped  # instance attr shadows the class method
            hooked.append(mod)
        for x in batches:
            model.forward(x)
    finally:
        for mod in hooked:
            # the same instance may sit at several paths (shared layers)
            mod.__dict__.pop("_apply", None)
        if was_training:
            model.training()
    return {p: o.scale for p, o in observers.items()}


def fold_batchnorm(model: Module) -> Module:
    """Fold eval-mode BN into the preceding Linear/SpatialConvolution inside
    Sequential containers (in place). The folded BN becomes an Identity-like
    no-op by zeroing its normalization: we instead drop it from the chain."""
    from ..nn.elementwise import Identity

    def fold_pair(layer: Module, bn: BatchNormalization,
                  lp, bp, bn_state) -> None:
        gamma = np.asarray(bp.get("weight", np.ones(bn.n_output)))
        beta = np.asarray(bp.get("bias", np.zeros(bn.n_output)))
        mean = np.asarray(bn_state["running_mean"])
        var = np.asarray(bn_state["running_var"])
        factor = gamma / np.sqrt(var + bn.eps)
        w = np.asarray(lp["weight"])
        shape = (-1,) + (1,) * (w.ndim - 1)
        lp["weight"] = jnp.asarray(w * factor.reshape(shape))
        bias = np.asarray(lp["bias"]) if "bias" in lp else np.zeros_like(mean)
        lp["bias"] = jnp.asarray((bias - mean) * factor + beta)

    def rec(module: Module, params, state):
        if isinstance(module, Sequential):
            mods = module.modules
            for i in range(len(mods) - 1):
                layer, bn = mods[i], mods[i + 1]
                if isinstance(layer, (Linear, SpatialConvolution)) and \
                        isinstance(bn, BatchNormalization) and bn.affine:
                    if "bias" not in params[str(i)]:
                        params[str(i)]["bias"] = jnp.zeros(
                            np.asarray(params[str(i)]["weight"]).shape[0])
                        layer.with_bias = True
                    fold_pair(layer, bn, params[str(i)], params[str(i + 1)],
                              state[str(i + 1)])
                    mods[i + 1] = Identity()
                    params[str(i + 1)] = {}
                    state[str(i + 1)] = {}
        if isinstance(module, Container):
            for i, child in enumerate(module.modules):
                rec(child, params[str(i)], state.get(str(i), {}))

    model.ensure_initialized()
    rec(model, model.params, model.state)
    model.grad_params = jax.tree_util.tree_map(jnp.zeros_like, model.params)
    return model
