"""Weight-only int8 quantization for the transformer LM inference path.

Autoregressive decode is WEIGHT-bandwidth-bound: every generated token
re-reads all block weights from HBM while activations are a single token
row. Storing block matmul weights as int8 with per-output-channel scales
halves (vs bf16) or quarters (vs f32) that traffic; the dequantize —
``(x @ q_int8.astype(x.dtype)) * scale`` — fuses into the matmul under
XLA, so the int8 tensor is what travels.

Mechanism: :class:`QuantizedWeight` is a registered pytree whose
``__rmatmul__`` performs the fused dequant-matmul. Because every matmul
site in the transformer stack is spelled ``x @ params[...]``, quantized
params drop into the UNCHANGED forward/prefill/decode code — no model
edits, no parallel implementation to keep in sync. The tied embedding
stays un-quantized (it is consumed by ``jnp.take`` and transposed for
the output projection).

Beyond the reference: its int8 path (``bigdl.utils.Quantization``,
nn/quantized/) covers Linear/Conv inference; BigDL 0.x has no
transformer decode to quantize. PTQ for Linear/Conv lives in
``quantization/quantize.py``; this module is the LM-specific weight-only
variant.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


class QuantizedWeight:
    """Per-output-channel symmetric int8 weight: ``w ≈ q * s``.

    Supports the one operation the transformer stack needs
    (``x @ w`` via ``__rmatmul__``); anything else should fail loudly
    rather than silently densify.
    """

    def __init__(self, q, s):
        self.q = q            # (K, N) int8
        self.s = s            # (N,) f32 scale

    @property
    def shape(self):
        return self.q.shape

    @property
    def dtype(self):  # the EFFECTIVE dtype seen by consumers
        return self.s.dtype

    def __rmatmul__(self, x):
        # dequant fused into the matmul epilogue by XLA: int8 is what
        # travels from HBM
        return (x @ self.q.astype(x.dtype)) * self.s.astype(x.dtype)

    def dequantize(self):
        return self.q.astype(self.s.dtype) * self.s

    def __repr__(self):
        return f"QuantizedWeight{tuple(self.q.shape)}"


jax.tree_util.register_pytree_node(
    QuantizedWeight,
    lambda w: ((w.q, w.s), None),
    lambda _, ch: QuantizedWeight(*ch))


_DEFAULT_KEYS = frozenset({"wq", "wk", "wv", "wo", "w1", "w2"})


def quantize_weight_int8(w):
    """(K, N) weight → :class:`QuantizedWeight` with per-OUT-channel
    scales. One quantization implementation exists in this package —
    quantize.py's ``quantize_weight`` (axis = the KEPT out-channel axis,
    which for a (K, N) matmul weight is 1); this wraps it in the pytree
    carrier."""
    from .quantize import quantize_weight
    q, s = quantize_weight(jnp.asarray(w), axis=1)
    return QuantizedWeight(q, s.reshape(-1))


def quantize_lm_params(params, keys=_DEFAULT_KEYS):
    """Replace the 2-D block matmul weights named in ``keys`` with
    :class:`QuantizedWeight`. Everything else (embedding, layernorms,
    biases) keeps its dtype. The result drops into ``model.apply`` /
    ``generate`` / ``translate`` unchanged — but do NOT run it through
    dtype-cast tree_maps (they would cast the int8 payload)."""

    def walk(node):
        if isinstance(node, dict):
            return {k: (quantize_weight_int8(v)
                        if k in keys and hasattr(v, "ndim") and v.ndim == 2
                        else walk(v))
                    for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            return type(node)(walk(v) for v in node)
        return node

    return walk(params)


def lm_quantized_bytes(params) -> dict:
    """Weight-byte accounting: {'quantized': n, 'dense': n} — the HBM
    traffic story the decode path cares about."""
    qb = db = 0
    for leaf in jax.tree_util.tree_leaves(
            params, is_leaf=lambda x: isinstance(x, QuantizedWeight)):
        if isinstance(leaf, QuantizedWeight):
            qb += leaf.q.nbytes + leaf.s.nbytes
        elif hasattr(leaf, "nbytes"):
            db += leaf.nbytes
    return {"quantized": int(qb), "dense": int(db)}
