"""Weight-only int8/int4 quantization for the transformer LM inference path.

Autoregressive decode is WEIGHT-bandwidth-bound: every generated token
re-reads all block weights from HBM while activations are a single token
row. Storing block matmul weights as int8 with per-output-channel scales
halves (vs bf16) or quarters (vs f32) that traffic; the dequantize —
``(x @ q_int8.astype(x.dtype)) * scale`` — fuses into the matmul under
XLA, so the int8 tensor is what travels.

Mechanism: :class:`QuantizedWeight` is a registered pytree whose
``__rmatmul__`` performs the fused dequant-matmul. Because every matmul
site in the transformer stack is spelled ``x @ params[...]``, quantized
params drop into the UNCHANGED forward/prefill/decode code — no model
edits, no parallel implementation to keep in sync. The tied embedding
stays un-quantized (it is consumed by ``jnp.take`` and transposed for
the output projection).

Beyond the reference: its int8 path (``bigdl.utils.Quantization``,
nn/quantized/) covers Linear/Conv inference; BigDL 0.x has no
transformer decode to quantize. PTQ for Linear/Conv lives in
``quantization/quantize.py``; this module is the LM-specific weight-only
variant.

Serving-tier integration (docs/SERVING.md "Quantized replicas"):
because the quantized params are a drop-in pytree,
``ModelRegistry.publish(quantize_lm_params(params), ...)`` already
serves int8 through the whole stack (continuous batching, paged KV,
prefix cache, router). The remaining ROADMAP direction-4 work is the
declared publish transform and the quantized-vs-f32 replica A/B behind
the Router — not new kernels.
"""
from __future__ import annotations

import logging
import math

import jax
import jax.numpy as jnp


class QuantizedWeight:
    """Per-output-channel symmetric int8 weight: ``w ≈ q * s``.

    Supports the one operation the transformer stack needs
    (``x @ w`` via ``__rmatmul__``); anything else should fail loudly
    rather than silently densify.
    """

    def __init__(self, q, s):
        self.q = q            # (K, N) int8
        self.s = s            # (N,) f32 scale

    @property
    def shape(self):
        return self.q.shape

    @property
    def dtype(self):  # the EFFECTIVE dtype seen by consumers
        return self.s.dtype

    def __rmatmul__(self, x):
        # dequant fused into the matmul epilogue by XLA: int8 is what
        # travels from HBM
        return (x @ self.q.astype(x.dtype)) * self.s.astype(x.dtype)

    def dequantize(self):
        return self.q.astype(self.s.dtype) * self.s

    def __repr__(self):
        return f"QuantizedWeight{tuple(self.q.shape)}"


jax.tree_util.register_pytree_node(
    QuantizedWeight,
    lambda w: ((w.q, w.s), None),
    lambda _, ch: QuantizedWeight(*ch))


class QuantizedWeightInt4:
    """Group-wise symmetric int4 weight: ``w[k, n] ≈ q[k, n] * s[k//g, n]``.

    int4 per-output-channel alone is too coarse for transformer weights;
    the standard recipe is a scale per GROUP of ``g`` contraction rows
    (default 128). The matmul is computed as per-group partial
    contractions — ``sum_g (x_g @ q_g) * s_g`` — so the int4 tensor is
    what streams from HBM (XLA stores s4 packed, two values per byte on
    TPU: half the traffic of the int8 path, quarter of bf16).
    """

    GROUP = 128

    def __init__(self, q, s, group=GROUP):
        self.q = q            # (K, N) int4
        self.s = s            # (K // group, N) f32 scale
        self.group = int(group)

    @property
    def shape(self):
        return self.q.shape

    @property
    def dtype(self):  # the EFFECTIVE dtype seen by consumers
        return self.s.dtype

    def __rmatmul__(self, x):
        K, N = self.q.shape
        G = K // self.group
        xg = x.reshape(x.shape[:-1] + (G, self.group))
        qg = self.q.reshape(G, self.group, N).astype(x.dtype)
        partial = jnp.einsum("...gk,gkn->...gn", xg, qg)
        return jnp.einsum("...gn,gn->...n", partial, self.s.astype(x.dtype))

    def dequantize(self):
        K, N = self.q.shape
        qf = self.q.astype(self.s.dtype).reshape(
            self.s.shape[0], self.group, N)
        return (qf * self.s[:, None, :]).reshape(K, N)

    def __repr__(self):
        return f"QuantizedWeightInt4{tuple(self.q.shape)}g{self.group}"


jax.tree_util.register_pytree_node(
    QuantizedWeightInt4,
    lambda w: ((w.q, w.s), w.group),
    lambda group, ch: QuantizedWeightInt4(*ch, group=group))


_DEFAULT_KEYS = frozenset({"wq", "wk", "wv", "wo", "w1", "w2"})


def quantize_weight_int8(w):
    """(K, N) weight → :class:`QuantizedWeight` with per-OUT-channel
    scales. One quantization implementation exists in this package —
    quantize.py's ``quantize_weight`` (axis = the KEPT out-channel axis,
    which for a (K, N) matmul weight is 1); this wraps it in the pytree
    carrier."""
    from .quantize import quantize_weight
    q, s = quantize_weight(jnp.asarray(w), axis=1)
    return QuantizedWeight(q, s.reshape(-1))


def quantize_weight_int4(w, group=QuantizedWeightInt4.GROUP):
    """(K, N) weight → :class:`QuantizedWeightInt4` with a symmetric
    max-abs scale per (group-of-K-rows, out-channel) block. K must be a
    multiple of ``group`` (true for every transformer block matmul at
    the default 128)."""
    w = jnp.asarray(w, jnp.float32)
    K, N = w.shape
    if K % group:
        raise ValueError(
            f"int4 group quantization needs K % group == 0, got K={K} "
            f"group={group}")
    wg = w.reshape(K // group, group, N)
    s = jnp.max(jnp.abs(wg), axis=1) / 7.0        # symmetric [-7, 7]
    s = jnp.where(s == 0, 1.0, s)
    q = jnp.clip(jnp.round(wg / s[:, None, :]), -8, 7)
    q = q.reshape(K, N).astype(jnp.int4)
    return QuantizedWeightInt4(q, s, group=group)


def quantize_lm_params(params, keys=_DEFAULT_KEYS, bits=8,
                       group=QuantizedWeightInt4.GROUP):
    """Replace the 2-D block matmul weights named in ``keys`` with
    :class:`QuantizedWeight` (``bits=8``, per-out-channel scales) or
    :class:`QuantizedWeightInt4` (``bits=4``, group-wise scales).
    Everything else (embedding, layernorms, biases) keeps its dtype. The
    result drops into ``model.apply`` / ``generate`` / ``translate``
    unchanged — but do NOT run it through dtype-cast tree_maps (they
    would cast the integer payload)."""
    if bits == 8:
        quantize = quantize_weight_int8
    elif bits == 4:
        def quantize(w):
            # auto-fit the group to this weight's K (gcd keeps it a
            # divisor; small models just get finer-grained scales)
            g = group if w.shape[0] % group == 0 \
                else math.gcd(w.shape[0], group)
            if g < 4:
                # f32 scale per group: 4/g + 0.5 bytes/element — at
                # g<4 the "quantized" stream exceeds bf16's 2 B/elem
                logging.getLogger("bigdl_tpu").warning(
                    "int4 group degraded to %d for K=%d (gcd with %d): "
                    "scale overhead makes this LARGER than bf16 — pass "
                    "a group that divides K", g, w.shape[0], group)
            return quantize_weight_int4(w, group=g)
    else:
        raise ValueError(f"bits must be 8 or 4, got {bits}")

    def walk(node):
        if isinstance(node, dict):
            return {k: (quantize(v)
                        if k in keys and hasattr(v, "ndim") and v.ndim == 2
                        else walk(v))
                    for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            return type(node)(walk(v) for v in node)
        return node

    return walk(params)


def lm_quantized_bytes(params) -> dict:
    """Weight-byte accounting: {'quantized': n, 'dense': n} — the HBM
    traffic story the decode path cares about. int4 payloads are counted
    at their packed HBM size (two values per byte), which is how XLA
    stores s4 on TPU regardless of what ``nbytes`` reports host-side."""
    qcls = (QuantizedWeight, QuantizedWeightInt4)
    qb = db = 0
    for leaf in jax.tree_util.tree_leaves(
            params, is_leaf=lambda x: isinstance(x, qcls)):
        if isinstance(leaf, QuantizedWeightInt4):
            qb += (leaf.q.size + 1) // 2 + leaf.s.nbytes
        elif isinstance(leaf, QuantizedWeight):
            qb += leaf.q.nbytes + leaf.s.nbytes
        elif hasattr(leaf, "nbytes"):
            db += leaf.nbytes
    return {"quantized": int(qb), "dense": int(db)}
