"""Int8 post-training quantization.

Parity: reference ``nn/quantized/`` (QuantizedLinear,
QuantizedSpatialConvolution, quantize.Quantizer — Intel DL-Boost int8
inference) and ``bigdl.utils.quantization`` entry
``Module.quantize()``.

TPU-native design: weights are quantized per-output-channel to int8
(symmetric, scale = max|w|/127); activations are quantized dynamically
per-tensor inside the compiled graph (one max-reduce, fused by XLA). The
int8×int8→int32 contraction runs on the MXU via
``lax.dot_general(..., preferred_element_type=int32)`` — the TPU analog of
DL-Boost VNNI. The reference's static calibration tables are an r2 item
(SURVEY §2.9).
"""
from __future__ import annotations

import copy

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..nn.module import Module, Container
from ..nn.linear import Linear
from ..nn.conv import SpatialConvolution
from ..nn.graph_container import Graph


def quantize_weight(w, axis=0):
    """Symmetric per-channel int8 quantization along ``axis`` (out-channels).
    Returns (int8 weights, f32 scales)."""
    w = jnp.asarray(w)
    red = tuple(i for i in range(w.ndim) if i != axis)
    absmax = jnp.max(jnp.abs(w), axis=red, keepdims=True)
    scale = jnp.maximum(absmax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def _dynamic_quantize(x):
    absmax = jnp.max(jnp.abs(x))
    scale = jnp.maximum(absmax, 1e-8) / 127.0
    return _static_quantize(x, scale), scale


def _static_quantize(x, scale):
    return jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)


def child_path(path: str, i: int) -> str:
    """Layer-path keying shared by quantize and calibration ('0/2/...')."""
    return f"{path}/{i}" if path else str(i)


class QuantizedLinear(Module):
    """nn/quantized/Linear.scala — int8 weights, int32 accumulate."""

    def __init__(self, input_size, output_size, with_bias=True, name=None):
        super().__init__(name=name)
        self.input_size, self.output_size = input_size, output_size
        self.with_bias = with_bias
        self._src_params = None  # float params captured at quantize() time

    @staticmethod
    def from_float(layer: Linear, params, act_scale=None):
        q = QuantizedLinear(layer.input_size, layer.output_size,
                            layer.with_bias, name=layer.name + "_int8")
        q._src_params = params
        q._act_scale = act_scale
        return q

    def _init_params(self, rng):
        w = self._src_params["weight"]
        qw, scale = quantize_weight(w, axis=0)
        p = {"qweight": qw, "scale": scale.reshape(-1)}
        if getattr(self, "_act_scale", None) is not None:
            p["act_scale"] = jnp.float32(self._act_scale)
        if self.with_bias:
            p["bias"] = jnp.asarray(self._src_params["bias"])
        return p

    def _apply(self, params, state, x, training, rng):
        if "act_scale" in params:  # static calibrated scale — no max-reduce
            xs = params["act_scale"]
            xq = _static_quantize(x, xs)
        else:
            xq, xs = _dynamic_quantize(x)
        acc = lax.dot_general(xq, params["qweight"],
                              (((x.ndim - 1,), (1,)), ((), ())),
                              preferred_element_type=jnp.int32)
        y = acc.astype(jnp.float32) * (xs * params["scale"])
        if self.with_bias:
            y = y + params["bias"]
        return y


class QuantizedSpatialConvolution(Module):
    """nn/quantized/SpatialConvolution.scala — int8 conv, NCHW."""

    def __init__(self, conv: SpatialConvolution, name=None):
        super().__init__(name=name or conv.name + "_int8")
        self.cfg = conv
        self._src_params = None

    @staticmethod
    def from_float(conv: SpatialConvolution, params, act_scale=None):
        q = QuantizedSpatialConvolution(conv)
        q._src_params = params
        q._act_scale = act_scale
        return q

    def _init_params(self, rng):
        w = self._src_params["weight"]  # (out, in/g, kh, kw)
        qw, scale = quantize_weight(w, axis=0)
        p = {"qweight": qw, "scale": scale.reshape(-1)}
        if getattr(self, "_act_scale", None) is not None:
            p["act_scale"] = jnp.float32(self._act_scale)
        if self.cfg.with_bias:
            p["bias"] = jnp.asarray(self._src_params["bias"])
        return p

    def _apply(self, params, state, x, training, rng):
        from ..nn.conv import _pad_pair, _resolve_padding
        c = self.cfg
        squeeze = False
        if x.ndim == 3:
            x, squeeze = x[None], True
        if "act_scale" in params:  # static calibrated scale — no max-reduce
            xs = params["act_scale"]
            xq = _static_quantize(x, xs)
        else:
            xq, xs = _dynamic_quantize(x)
        pads = (_pad_pair(c.pad_h, c.kernel_h, c.stride_h),
                _pad_pair(c.pad_w, c.kernel_w, c.stride_w))
        acc = lax.conv_general_dilated(
            xq, params["qweight"], (c.stride_h, c.stride_w),
            _resolve_padding(pads),
            rhs_dilation=(c.dilation_h, c.dilation_w),
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
            feature_group_count=c.n_group,
            preferred_element_type=jnp.int32)
        y = acc.astype(jnp.float32) * \
            (xs * params["scale"])[None, :, None, None]
        if c.with_bias:
            y = y + params["bias"][None, :, None, None]
        return y[0] if squeeze else y


class QuantizedSpatialSeparableConvolution(Module):
    """Int8 depthwise + pointwise conv (parity: reference
    ``nn/quantized/SpatialDilatedConvolution.scala`` breadth — the separable
    factorization quantizes both stages; the intermediate is requantized
    dynamically between them)."""

    def __init__(self, sep, name=None):
        super().__init__(name=name or sep.name + "_int8")
        self.cfg = sep
        self._src_params = None

    @staticmethod
    def from_float(sep, params, act_scale=None):
        q = QuantizedSpatialSeparableConvolution(sep)
        q._src_params = params
        q._act_scale = act_scale
        return q

    def _init_params(self, rng):
        qd, dscale = quantize_weight(self._src_params["depth_weight"], axis=0)
        qp, pscale = quantize_weight(self._src_params["point_weight"], axis=0)
        p = {"qdepth": qd, "dscale": dscale.reshape(-1),
             "qpoint": qp, "pscale": pscale.reshape(-1)}
        if getattr(self, "_act_scale", None) is not None:
            p["act_scale"] = jnp.float32(self._act_scale)
        if self.cfg.has_bias:
            p["bias"] = jnp.asarray(self._src_params["bias"])
        return p

    def _apply(self, params, state, x, training, rng):
        from ..nn.conv import _pad_pair, _resolve_padding
        c = self.cfg
        squeeze = False
        if x.ndim == 3:
            x, squeeze = x[None], True
        if "act_scale" in params:
            xs = params["act_scale"]
            xq = _static_quantize(x, xs)
        else:
            xq, xs = _dynamic_quantize(x)
        pads = (_pad_pair(c.ph, c.kh, c.sh), _pad_pair(c.pw, c.kw, c.sw))
        acc = lax.conv_general_dilated(
            xq, params["qdepth"], (c.sh, c.sw), _resolve_padding(pads),
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
            feature_group_count=c.n_input_channel,
            preferred_element_type=jnp.int32)
        mid = acc.astype(jnp.float32) * \
            (xs * params["dscale"])[None, :, None, None]
        mq, ms = _dynamic_quantize(mid)
        acc2 = lax.conv_general_dilated(
            mq, params["qpoint"], (1, 1), "VALID",
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
            preferred_element_type=jnp.int32)
        y = acc2.astype(jnp.float32) * \
            (ms * params["pscale"])[None, :, None, None]
        if c.has_bias:
            y = y + params["bias"][None, :, None, None]
        return y[0] if squeeze else y


def _quantize_rec(module: Module, params, calibration, path="", used=None):
    """Return (new_module, new_params) with eligible layers replaced.
    ``calibration`` maps layer paths (child_path keying, shared with
    calibration.quantizable_paths) to static activation scales; None →
    dynamic quantization. ``used`` collects matched calibration keys."""
    act = (calibration or {}).get(path)
    if act is not None and used is not None:
        used.add(path)
    from ..nn.sparse import SparseLinear
    if isinstance(module, Linear) and not isinstance(
            module, (QuantizedLinear, SparseLinear)):
        # SparseLinear stays float: its value is the COO input path, which
        # the dense int8 contraction cannot take
        q = QuantizedLinear.from_float(module, params, act)
        return q, q._init_params(None)
    if isinstance(module, SpatialConvolution):
        q = QuantizedSpatialConvolution.from_float(module, params, act)
        return q, q._init_params(None)
    from ..nn.conv import SpatialSeparableConvolution
    if isinstance(module, SpatialSeparableConvolution):
        q = QuantizedSpatialSeparableConvolution.from_float(module, params,
                                                            act)
        return q, q._init_params(None)
    if isinstance(module, Container):
        new_params = dict(params)
        replacements = {}
        for i, child in enumerate(module.modules):
            nm, np_ = _quantize_rec(child, params[str(i)], calibration,
                                    child_path(path, i), used)
            # containers are rewritten in place (nm is child) but still
            # return fresh params for quantized descendants — always take
            # the returned subtree, not only when the object was swapped
            new_params[str(i)] = np_
            if nm is not child:
                replacements[i] = nm
        for i, nm in replacements.items():
            old = module.modules[i]
            module.modules[i] = nm
            if isinstance(module, Graph):
                for node in module.topo:
                    if node.module is old:
                        node.module = nm
        return module, new_params
    return module, params


def quantize(model: Module, calibration=None) -> Module:
    """Module.quantize() parity: returns an int8-inference copy of the model
    (weights quantized per-channel; activations quantized dynamically, or
    statically when a ``calibration`` dict from
    ``quantization.calibrate(model, batches)`` is given)."""
    model.ensure_initialized()
    m = copy.deepcopy(model)
    used = set()
    new_m, new_params = _quantize_rec(m, m.params, calibration, used=used)
    if calibration and set(calibration) - used:
        import logging
        logging.getLogger(__name__).warning(
            "calibration keys not matched to any quantizable layer "
            "(falling back to dynamic quantization elsewhere): %s",
            sorted(set(calibration) - used))
    new_m.params = new_params
    new_m.grad_params = jax.tree_util.tree_map(jnp.zeros_like, new_params)
    new_m.evaluate()
    return new_m
