"""Online serving: dynamic micro-batching over the compiled forward.

Training got prefetch, superstep fusion and a persistent compile cache;
this package is the inference-side counterpart for the "heavy traffic
from millions of users" regime — many small concurrent requests that
must be coalesced into device-efficient batches under a latency
deadline, instead of the per-request dispatch an RPC-per-inference
design pays (the overhead 1805.08430 "RPC Considered Harmful" measures).

* ``engine`` — :class:`ServingEngine`: bounded request queue →
  batcher thread → padded shape-bucket dispatch of the ONE compiled
  forward shared with ``optim.Predictor`` → per-request futures.
  Flushes on ``max_batch`` OR ``max_wait_ms``; typed ``QueueFull``
  backpressure; per-request deadlines; drain-then-shutdown.
* ``batching`` — request/future types, typed rejections, per-request-
  isolated batch assembly, bucket math re-exported from
  ``optim.predictor``.
* ``registry`` — :class:`ModelRegistry`: versioned params with
  background load + atomic activate; the engine snapshots the active
  version once per batch, so hot swap never mixes versions inside a
  response.
* ``kv_cache`` — :class:`PagedKVCache`: fixed-size HBM blocks +
  per-request block tables (vLLM's paged layout) with per-block
  REFERENCE COUNTS and copy-on-write forks, the block ledger exported
  as ``serve/kv_*`` gauges.
* ``prefix_cache`` — :class:`PrefixCache`: content-addressed index
  over the block ledger (rolling chain digests of (tokens, model
  version) at block granularity) — shared prompt prefixes are stored
  once and their prefill skipped at admission; LRU eviction over
  unreferenced entries, prefix-affinity probes for the router
  (docs/SERVING.md "Prefix cache").
* ``decode_scheduler`` — :class:`DecodeScheduler`: continuous batching
  for autoregressive LM decode — requests join/leave the running batch
  at decode-step boundaries over ONE compiled paged step; chunked
  prefill admission, per-request version pinning for hot swap,
  per-request temperature/top-p sampling under seeded key streams,
  optional BATCHED speculative decoding — every greedy row drafts and
  verifies per round with per-row acceptance (docs/SERVING.md
  "Speculative decoding (batched)").
* ``router`` — :class:`Router`: N engine replicas behind SLO-aware
  dispatch — priority-class weighted-fair queues, deadline-aware
  placement (tight deadlines to the least-loaded replica,
  deadline-doomed requests fail fast at admission), per-replica stall
  drain + failover + rejoin, fleet-wide hot swap. Both engines also
  take ``mesh=`` + ``placement=`` (TP / FSDP PartitionSpecs from
  ``parallel.sharding``) so a single replica can span a mesh — the
  model-parallel axis — while the router scales the replica axis.

Metrics (`docs/OBSERVABILITY.md`): ``serve/queue_depth``,
``serve/batch_occupancy``, ``serve/latency_ms``, ``serve/rejected``,
``serve/timeouts``, ``serve/batches``, ``serve/requests``; one
``serve/batch`` span per dispatch. Tuning guide: `docs/SERVING.md`.
"""
from .batching import (QueueFull, DeadlineExceeded, EngineStopped,
                       ServeFuture, Request, assemble)
from .registry import ModelRegistry, ModelVersion
from .engine import ServingEngine, serving_threads_alive, THREAD_NAME
from .kv_cache import (HostKVPool, HostPoolOOM, KVCacheOOM, KVSwapManager,
                       PagedKVCache, blocks_for_tokens,
                       kv_swap_threads_alive)
from .prefix_cache import PrefixCache, chain_keys
from .decode_scheduler import (DecodeScheduler, LMRequest,
                               decode_scheduler_threads_alive,
                               prefill_schedule)
from .router import PriorityClass, Router, router_threads_alive
# cross-process fleet tier (ISSUE 15): replica agents in other
# processes behind the SAME Router — membership/health over beaten
# files, tensors over a framed local-socket transport, disaggregated
# prefill/decode pools with content-key-verified KV handoff
# (docs/SERVING.md "Fleet serving", `make fleet-smoke`)
from .transport import (TransportClient, TransportClosed,
                        TransportServer, transport_threads_alive)
from .fleet import (DisaggregatedFleet, FleetMonitor, KVHandoffError,
                    RemoteReplica, ReplicaAgent, discover,
                    fleet_threads_alive, read_member, wait_for_members,
                    warm_replica)
# elastic control plane (ISSUE 19): an SLO-scoring reconcile loop that
# scales the fleet (spawn/drain under budgets with hysteresis +
# cooldown), promotes decode replicas to prefill duty under backlog,
# and prefix-warms joiners — membership changes ride the router's
# drain/failover machinery, so scaling never loses a request
# (docs/SERVING.md "Fleet operations")
from .controller import (FleetController, ScalePolicy,
                         controller_threads_alive)
# the transient-failure classification AND the retry budget are SHARED
# with the trainer (parallel/failure.FaultPolicy): the engine's batch
# retry, the scheduler's bitwise step replay and the router's
# KV-preserving failover all branch on classify_failure — and the
# parallel/chaos.py fault-injection plane drills every one of those
# seams (docs/RESILIENCE.md "Serving faults", `make chaos-smoke`)
from ..parallel.failure import TransientDeviceError  # noqa: F401
