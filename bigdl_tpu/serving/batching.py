"""Micro-batch assembly: requests, futures, typed rejections, padding.

The shape-bucket math itself (``bucket_for`` / ``shape_buckets`` /
``pad_leading``) lives in ``optim/predictor.py`` next to the ONE
compiled forward both consumers share — this module re-exports it and
adds the request-side machinery: the future a client waits on, the
typed exceptions admission control raises, and the per-request-isolated
batch assembly (one malformed input fails ITS future, never the batch
around it, never the batcher).
"""
from __future__ import annotations

import time
from concurrent.futures import Future
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..optim.predictor import (bucket_for, pad_leading,  # noqa: F401
                               shape_buckets, leading_dim)


class QueueFull(RuntimeError):
    """Admission control: the bounded request queue is at capacity.
    Typed so load balancers / clients can branch on it (shed, retry
    with backoff) without string-matching."""


class DeadlineExceeded(TimeoutError):
    """The request's deadline passed before its batch dispatched."""


class EngineStopped(RuntimeError):
    """submit() after shutdown began (drain accepts no new work)."""


class ServeFuture(Future):
    """`concurrent.futures.Future` plus serving provenance: the model
    ``version`` that answered (stamped at scatter time — a hot-swap
    test's witness that a batch is never split across versions), the
    request id ``rid`` minted at submit, and ``trace`` — the
    per-request stage decomposition ``{rid, queue_wait_ms,
    assemble_ms, dispatch_ms, bucket, version}`` attached when its
    batch dispatches, so a slow response is attributable to queueing
    vs assembly vs the device without correlating logs."""

    def __init__(self):
        super().__init__()
        self.version: Optional[str] = None
        self.rid: Optional[int] = None
        self.trace: Optional[dict] = None


class Request:
    """One queued inference request: the raw input, the future the
    client holds, its timing (enqueue time for the latency histogram,
    absolute monotonic deadline or None), and the request id the
    engine minted at ``submit()`` — ``t_enqueue_ns`` is the
    ``perf_counter_ns`` stamp the queue-wait stage span starts from."""

    __slots__ = ("x", "future", "t_enqueue", "t_enqueue_ns", "deadline",
                 "rid")

    def __init__(self, x, deadline_s: Optional[float] = None,
                 rid: Optional[int] = None):
        self.x = x
        self.future = ServeFuture()
        self.rid = rid
        self.future.rid = rid
        self.t_enqueue = time.monotonic()
        self.t_enqueue_ns = time.perf_counter_ns()
        self.deadline = (self.t_enqueue + deadline_s
                         if deadline_s is not None else None)

    def expired(self, now: Optional[float] = None) -> bool:
        return (self.deadline is not None
                and (now or time.monotonic()) > self.deadline)


def assemble(requests: Sequence[Request],
             template_shape: Optional[Tuple[int, ...]] = None,
             dtype=np.float32) -> Tuple[Optional[np.ndarray], List[Request]]:
    """Stack per-request sample arrays into one ``[n, ...]`` host batch.

    Error isolation happens HERE: each request's input is converted and
    shape-checked independently — a failure sets that request's future
    (so the client sees the real exception) and drops it from the batch;
    the survivors still dispatch. Returns ``(batch, live_requests)``
    with ``batch is None`` when nothing survived.

    ``template_shape`` (the engine's configured ``input_shape``) is the
    authority when given; otherwise the first convertible request sets
    the template — later mismatches fail their own future.
    """
    xs: List[np.ndarray] = []
    live: List[Request] = []
    shape = template_shape
    for r in requests:
        try:
            a = np.asarray(r.x, dtype=dtype)
            if shape is None:
                shape = a.shape
            if a.shape != shape:
                raise ValueError(
                    f"request sample shape {a.shape} != expected {shape} "
                    "(submit ONE unbatched sample per request)")
        except BaseException as e:  # noqa: BLE001 — routed to the future
            if not r.future.cancelled():
                r.future.set_exception(e)
            continue
        xs.append(a)
        live.append(r)
    if not live:
        return None, []
    return np.stack(xs, axis=0), live
