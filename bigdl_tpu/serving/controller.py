"""Elastic fleet control plane (ISSUE 19).

The fleet tier (PR 15/16) serves across processes with membership fixed
at launch. This module adds the piece the TensorFlow system paper
treats as table stakes — a dynamic-cluster layer — as a RECONCILE LOOP
over the signals the repo already emits: member files carry each
agent's ``serving`` section (queue depth, inflight, active version) via
``FileHeartbeat``, and the Router counts submissions/misses per class.
The controller never invents a data path; it only changes WHO is in
the existing ones:

* **SLO-scored scaling** — each tick scores fleet load (router class
  queues + member-file queue depths, per healthy replica) and the
  deadline-miss rate of the tick window against a :class:`ScalePolicy`.
  Sustained pressure (hysteresis: ``up_ticks`` consecutive ticks)
  spawns a replica through the caller's ``spawn`` hook; sustained
  slack retires one. Both respect min/max budgets and a cooldown so a
  noisy minute cannot flap the fleet. Retirement is a DRAIN, never a
  kill: ``Router.remove_replica`` fails the victim's in-flight work
  over to survivors (set-once futures, zero lost), then the agent
  drains its own queue and exits 0.

* **Prefill promotion** — when the prefill pool's backlog crosses
  ``prefill_backlog_high`` while the decode pool has a replica to
  spare, one decode replica is PROMOTED: removed from router rotation
  (its in-flight decodes fail over), version-checked against the pool
  (a skewed replica would refuse every handoff — promotion waits
  instead), role-flipped via the ``set_role`` op (the member file
  advertises the new duty immediately), and added to the
  :class:`~.fleet.DisaggregatedFleet` prefill pool. Backlog relief
  demotes it back the same way. This closes the PR-15-named gap: TTFT
  insulation now has somewhere to get capacity FROM.

* **Prefix warming on join** — a spawned replica adopts the hottest
  prefix chains from a live peer via :func:`~.fleet.warm_replica`
  before taking traffic, so scale-up serves warm (the scale-up TTFT
  gate in ``PERF_BASELINE.json``).

* **Adoption** — ``start()`` reconciles the membership DIRECTORY
  against the router: live members the router doesn't know (a prior
  controller spawned them, then died) are adopted, not respawned. A
  controller is therefore stateless-restartable: the directory is the
  state.

Chaos sites: ``fleet/controller_tick`` (a transient skips one tick; a
permanent kills the controller thread — the fleet KEEPS SERVING,
because the router/monitor own the data path) and ``fleet/spawn`` (a
spawn failure is counted and retried after cooldown; the fleet never
half-registers a replica). Metrics ride ``serve/fleet_*``
(docs/OBSERVABILITY.md); runbook in docs/SERVING.md "Fleet
operations".
"""
from __future__ import annotations

import itertools
import logging
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, Optional, Set

from .. import observability as obs
from ..observability import health as _health
from ..parallel import chaos as _chaos
from ..parallel.failure import TRANSIENT, classify_failure
from .fleet import (DisaggregatedFleet, FleetMonitor, RemoteReplica,
                    discover, read_member, warm_replica)

_LOG = logging.getLogger("bigdl_tpu.serving.controller")

CONTROLLER_THREAD = "bigdl_tpu-fleet-controller"


@dataclass
class ScalePolicy:
    """The controller's SLO thresholds and scaling discipline.

    Load score = (router class-queue depth + member-file queue depths)
    per healthy replica; miss rate = deadline misses / submissions in
    the tick window. Hysteresis (``up_ticks``/``down_ticks``
    consecutive ticks over/under threshold) plus ``cooldown_s`` after
    ANY membership change keep a bursty minute from flapping the
    fleet — scaling is meant to track sustained pressure, the queues
    absorb the rest."""
    min_replicas: int = 1
    max_replicas: int = 4
    #: per-healthy-replica backlog above which the fleet is overloaded
    queue_high: float = 8.0
    #: ...and below which it is over-provisioned
    queue_low: float = 1.0
    #: deadline-miss fraction per tick window that counts as overload
    miss_rate_high: float = 0.05
    up_ticks: int = 2
    down_ticks: int = 4
    cooldown_s: float = 5.0
    #: prefill-pool backlog (queue depth + pending across specialists)
    #: that triggers a decode→prefill promotion / its relief demotes
    prefill_backlog_high: int = 8
    prefill_backlog_low: int = 1
    #: only these router classes feed the load score (None = all)
    watch_classes: Optional[Set[str]] = None
    #: max prompts warmed into a joining replica (0 disables warming)
    warm_limit: int = 8
    #: after a drain-retire, the victim's name is held out of adoption
    #: until its member doc goes terminal or this many seconds pass —
    #: the shutdown ack races the agent's final beat, and a tick in
    #: that window must not re-register the retiring replica
    retire_grace_s: float = 60.0


class FleetController:
    """The reconcile loop: observe → score → (maybe) change membership.

    Parameters
    ----------
    router : the :class:`~.router.Router` whose ``add_replica`` /
        ``remove_replica`` / ``stats`` are the scale levers + signal.
    monitor : the :class:`~.fleet.FleetMonitor` watching the same
        replicas (``watch``/``unwatch`` keep it in step).
    fleet_dir : the membership directory — the controller's durable
        state. A respawned controller adopts whatever lives here.
    spawn : ``spawn(name) -> RemoteReplica`` — launch ONE new agent
        (subprocess, thread, whatever the deployment uses) and return
        a connected handle. The controller wraps the call with the
        ``fleet/spawn`` chaos seam and the ``serve/fleet_spawn_ms``
        histogram; a raise is a counted, retried-after-cooldown
        failure, never a half-registered replica.
    disagg : optional :class:`~.fleet.DisaggregatedFleet` — enables
        prefill promotion/demotion and pool-aware adoption.
    warm_prompts : the prompts a joining replica pre-warms (a sequence,
        or a zero-arg callable returning one — e.g. "current hottest
        chains"). Warming degrades per-prompt; it never blocks a join.
    every_s : tick cadence of the background thread. ``tick()`` is
        public so tests drive reconciliation deterministically.
    """

    def __init__(self, router, monitor: FleetMonitor, *, fleet_dir: str,
                 spawn: Callable[[str], RemoteReplica],
                 policy: Optional[ScalePolicy] = None,
                 disagg: Optional[DisaggregatedFleet] = None,
                 warm_prompts=None,
                 every_s: float = 0.5,
                 name: str = "controller",
                 spawn_prefix: str = "auto"):
        self.router = router
        self.monitor = monitor
        self.fleet_dir = fleet_dir
        self.spawn = spawn
        self.policy = policy or ScalePolicy()
        self.disagg = disagg
        self.warm_prompts = warm_prompts
        self.every_s = float(every_s)
        self.name = name
        self.spawn_prefix = spawn_prefix
        self._members: Dict[str, RemoteReplica] = {
            r.name: r for r in monitor.replicas}
        if disagg is not None:
            for p in disagg.prefill:
                self._members.setdefault(p.name, p)
        self._promoted: Set[str] = set()
        # name → monotonic stamp of a drain-retire still in flight:
        # adopt() skips these until the member doc goes terminal (or
        # the grace period lapses)
        self._retired: Dict[str, float] = {}
        self._spawn_ids = itertools.count()
        self._up_streak = 0
        self._down_streak = 0
        self._last_change = float("-inf")
        self._last_submitted = 0
        self._last_misses = 0
        self._stats = {"ticks": 0, "tick_faults": 0, "scale_ups": 0,
                       "scale_downs": 0, "spawn_failed": 0,
                       "promotions": 0, "demotions": 0, "adopted": 0,
                       "warm_prompts": 0, "version_skew_blocked": 0}
        self._stats_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.dead = False

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "FleetController":
        self.adopt()
        self._thread = threading.Thread(
            target=self._loop, name=f"{CONTROLLER_THREAD}[{self.name}]",
            daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(10.0)

    def stats(self) -> Dict:
        with self._stats_lock:
            out = dict(self._stats)
        out["replicas"] = len(self._members)
        out["promoted"] = sorted(self._promoted)
        out["dead"] = self.dead
        return out

    # -- adoption --------------------------------------------------------

    def adopt(self) -> int:
        """Reconcile the membership DIRECTORY against the router: any
        live member (not dead, not cleanly final) the controller does
        not already track gets a fresh :class:`RemoteReplica` and joins
        the router/monitor (prefill-role members join the disagg
        prefill pool instead). This is what makes a controller restart
        an ADOPTION, not a respawn storm — the directory is the
        controller's only durable state. A name this controller just
        drain-retired is held out until its member doc goes terminal
        (or ``retire_grace_s`` lapses): the agent's shutdown ack races
        its final beat, and a tick landing in that window must not
        re-register the retiring replica. Returns members adopted."""
        n = 0
        now = time.monotonic()
        for doc in discover(self.fleet_dir):
            name = doc["name"]
            if doc.get("dead") or doc.get("final"):
                self._retired.pop(name, None)   # retirement completed
                continue
            if name in self._members:
                continue
            if name in self._retired:
                if now - self._retired[name] < self.policy.retire_grace_s:
                    continue
                self._retired.pop(name, None)
            rep = RemoteReplica(doc, fleet_dir=self.fleet_dir)
            try:
                rep.start()
            except OSError:
                # registered but unreachable (still booting, or its
                # host died without a terminal beat) — next tick
                continue
            try:
                self._register(rep)
            except ValueError:
                rep.close()
                continue
            self._members[name] = rep
            n += 1
            self._bump("adopted")
            _LOG.info("controller %s adopted member %s (%s) at %s:%d",
                      self.name, name, rep.role, rep.host, rep.port)
            if obs.enabled():
                obs.instant("serve/fleet_adopt", agent=name,
                            role=rep.role)
        return n

    def _register(self, rep: RemoteReplica):
        if rep.role == "prefill" and self.disagg is not None:
            self.disagg.add_prefill(rep)
            self.monitor.watch(rep)
            return
        self.router.add_replica(rep)
        self.monitor.watch(rep)
        if self.disagg is not None and rep.role == "decode":
            self.disagg.add_decode(rep)

    # -- the reconcile loop ----------------------------------------------

    def _loop(self):
        while not self._stop.is_set():
            try:
                _chaos.maybe_fire("fleet/controller_tick", tag=self.name)
                self.tick()
            except BaseException as e:  # noqa: BLE001 — classify
                if classify_failure(e) == TRANSIENT:
                    # one lost tick: membership is unchanged, the data
                    # path never noticed
                    self._bump("tick_faults")
                    if obs.enabled():
                        obs.counter("serve/fleet_controller_faults").inc()
                else:
                    # controller DEATH. Deliberately not fatal to the
                    # fleet: the router/monitor own the data path, so
                    # serving continues with membership frozen; a
                    # respawned controller adopts the directory.
                    self.dead = True
                    _health.emit("fleet_controller_death",
                                 controller=self.name, error=repr(e))
                    _LOG.error("fleet controller %s died: %s",
                               self.name, e)
                    return
            self._stop.wait(self.every_s)

    def tick(self):
        """ONE reconciliation: score the fleet, take at most one
        membership action. Public so tests and drills can drive the
        controller deterministically without the thread cadence."""
        self._bump("ticks")
        pol = self.policy
        load, miss_rate, n_healthy = self._score()
        if obs.enabled():
            obs.gauge("serve/fleet_size").set(len(self._members))
            obs.gauge("serve/fleet_load").set(load)
        over = load > pol.queue_high or miss_rate > pol.miss_rate_high
        under = (load < pol.queue_low
                 and miss_rate <= pol.miss_rate_high / 2)
        self._up_streak = self._up_streak + 1 if over else 0
        self._down_streak = self._down_streak + 1 if under else 0
        self.adopt()
        if self.disagg is not None:
            self._reconcile_prefill()
        if time.monotonic() - self._last_change < pol.cooldown_s:
            return
        size = self._router_size()
        if self._up_streak >= pol.up_ticks and size < pol.max_replicas:
            self._scale_up()
        elif self._down_streak >= pol.down_ticks \
                and size > pol.min_replicas and n_healthy > 1:
            self._scale_down()

    # -- signals ---------------------------------------------------------

    def _score(self):
        rs = self.router.stats()
        reps = rs.get("replicas", {})
        n_healthy = sum(1 for v in reps.values() if v.get("healthy"))
        qd = rs.get("queue_depth", {})
        if self.policy.watch_classes is not None:
            qd = {k: v for k, v in qd.items()
                  if k in self.policy.watch_classes}
        backlog = sum(qd.values())
        for name in reps:
            s = self._serving(name)
            backlog += int(s.get("queue_depth") or 0)
        load = backlog / max(1, n_healthy)
        submitted = rs.get("submitted", 0)
        misses = rs.get("deadline_misses", 0)
        ds = submitted - self._last_submitted
        dm = misses - self._last_misses
        self._last_submitted, self._last_misses = submitted, misses
        miss_rate = (dm / ds) if ds > 0 else 0.0
        return load, miss_rate, n_healthy

    def _serving(self, name: str) -> Dict:
        rep = self._members.get(name)
        doc = rep.member() if rep is not None else None
        return (doc or {}).get("serving", {}) or {}

    def _router_size(self) -> int:
        return len(self.router.stats().get("replicas", {}))

    # -- scale -----------------------------------------------------------

    def _next_spawn_name(self) -> str:
        """The next ``<prefix>N`` no member already claims. The id
        counter restarts at 0 with every controller incarnation, so a
        successor that ADOPTED a predecessor's ``auto0`` must not hand
        that name to its own first spawn — the new agent would clobber
        the live replica's member file and the healthy original would
        be falsely retired off the new agent's beats. Skip ids with a
        tracked member, a retirement in flight, or any existing member
        file (live, final, or orphaned: the name is taken either way)."""
        while True:
            name = f"{self.spawn_prefix}{next(self._spawn_ids)}"
            if (name not in self._members
                    and name not in self._retired
                    and read_member(self.fleet_dir, name) is None):
                return name

    def _scale_up(self):
        name = self._next_spawn_name()
        t0 = time.monotonic()
        try:
            _chaos.maybe_fire("fleet/spawn", tag=name)
            rep = self.spawn(name)
        except BaseException as e:  # noqa: BLE001 — spawn must not kill
            # the controller: a failed spawn changed NOTHING (no router
            # entry, no monitor entry) — count it, honor the cooldown,
            # try again. An orphan member file, if the process half-
            # started, is adopted by a later tick.
            self._bump("spawn_failed")
            if obs.enabled():
                obs.counter("serve/fleet_spawn_failed").inc()
            _LOG.warning("fleet spawn %s failed (%s: %s) — retrying "
                         "after cooldown", name, type(e).__name__, e)
            self._last_change = time.monotonic()
            return
        if obs.enabled():
            obs.histogram("serve/fleet_spawn_ms", unit="ms").observe(
                (time.monotonic() - t0) * 1000.0)
        self._warm(rep)
        try:
            self._register(rep)
        except ValueError as e:
            _LOG.warning("spawned replica %s rejected by router (%s) — "
                         "draining it", name, e)
            rep.shutdown(drain=True)
            self._bump("spawn_failed")
            self._last_change = time.monotonic()
            return
        self._members[rep.name] = rep
        self._bump("scale_ups")
        self._up_streak = 0
        self._last_change = time.monotonic()
        _health.emit("fleet_scale_up", agent=rep.name,
                     size=len(self._members))
        if obs.enabled():
            obs.counter("serve/fleet_scale_ups").inc()
            obs.instant("serve/fleet_scale_up", agent=rep.name,
                        spawn_ms=round((time.monotonic() - t0) * 1e3, 1))
        _LOG.info("fleet scaled UP: %s joined (%d members)",
                  rep.name, len(self._members))

    def _scale_down(self):
        victim = self._pick_victim()
        if victim is None:
            return
        try:
            eng = self.router.remove_replica(victim)
        except ValueError:
            return   # raced to the last replica / tag coverage — skip
        self.monitor.unwatch(victim)
        if self.disagg is not None:
            self.disagg.remove_decode(victim)
        self._members.pop(victim, None)
        # hold the name out of adoption until its member doc goes
        # terminal — the agent acks shutdown BEFORE its final beat
        self._retired[victim] = time.monotonic()
        self._bump("scale_downs")
        self._down_streak = 0
        self._last_change = time.monotonic()
        _health.emit("fleet_scale_down", agent=victim,
                     size=len(self._members))
        if obs.enabled():
            obs.counter("serve/fleet_scale_downs").inc()
            obs.instant("serve/fleet_scale_down", agent=victim)
        _LOG.info("fleet scaled DOWN: %s retiring (%d members)",
                  victim, len(self._members))
        # the router already failed the victim's in-flight work over to
        # survivors; now the AGENT drains its own queue and exits 0 —
        # retire is always a drain, never a kill
        try:
            eng.shutdown(drain=True)
        except Exception:  # noqa: BLE001 — it is out of rotation either way
            pass

    def _pick_victim(self) -> Optional[str]:
        """The healthy router replica with the least in-flight work —
        never a promoted specialist (demotion owns those), preferring
        controller-spawned replicas so a static seed fleet survives
        scale-down."""
        rs = self.router.stats().get("replicas", {})
        cand = [(v.get("inflight", 0),
                 0 if n.startswith(self.spawn_prefix) else 1, n)
                for n, v in rs.items()
                if v.get("healthy") and n not in self._promoted]
        if not cand:
            return None
        cand.sort(key=lambda t: (t[1], t[0], t[2]))
        return cand[0][2]

    # -- prefill promotion ----------------------------------------------

    def _reconcile_prefill(self):
        pol = self.policy
        backlog = 0
        for p in self.disagg.prefill:
            s = self._serving(p.name)
            backlog += (int(s.get("queue_depth") or 0)
                        + int(s.get("pending") or 0))
        if obs.enabled():
            obs.gauge("serve/fleet_prefill_backlog").set(backlog)
        if backlog > pol.prefill_backlog_high:
            self._promote()
        elif backlog <= pol.prefill_backlog_low and self._promoted:
            self._demote()

    def _promote(self):
        """Decode → prefill: dedicate one decode replica to the backed-
        up prefill pool. Router removal first (its in-flight decodes
        fail over — zero lost), then the version check (a skewed
        promotee would refuse every handoff: wait instead, counted),
        then the role flip + pool move."""
        rs = self.router.stats().get("replicas", {})
        cand = [(v.get("inflight", 0), n) for n, v in rs.items()
                if v.get("healthy") and n not in self._promoted]
        if len(cand) < 2:
            return   # never strip the decode pool bare
        name = min(cand)[1]
        rep = self._members.get(name)
        if rep is None:
            return
        def _live_version(r):
            # the FRESH member doc, never the handle cache: an adopted
            # or idle handle's cached version is seeded at construction
            # and only refreshed by its own submit acks, so it can stay
            # None/stale forever and block promotion on phantom skew
            doc = r.member()
            if doc is None:
                return r.active_version()
            return (doc.get("serving") or {}).get("active_version")

        pool_vs = {v for v in (_live_version(p)
                               for p in self.disagg.prefill)
                   if v is not None}
        if pool_vs and _live_version(rep) not in pool_vs:
            self._bump("version_skew_blocked")
            if obs.enabled():
                obs.counter("serve/fleet_promotion_skew_blocked").inc()
            return
        try:
            self.router.remove_replica(name)
        except ValueError:
            return
        try:
            rep.set_role("prefill")
        except Exception as e:  # noqa: BLE001 — undo, stay consistent
            _LOG.warning("promotion of %s failed at role flip (%s) — "
                         "rejoining decode", name, e)
            self.router.add_replica(rep)
            return
        self.disagg.remove_decode(name)
        self.disagg.add_prefill(rep)
        self._promoted.add(name)
        self._bump("promotions")
        self._last_change = time.monotonic()
        _health.emit("fleet_promotion", agent=name, to_role="prefill")
        if obs.enabled():
            obs.counter("serve/fleet_promotions").inc()
            obs.instant("serve/fleet_promotion", agent=name)
        _LOG.info("promoted %s to prefill duty", name)

    def _demote(self):
        name = sorted(self._promoted)[0]
        rep = self.disagg.remove_prefill(name)
        if rep is None:
            self._promoted.discard(name)
            return
        try:
            rep.set_role("decode")
        except Exception as e:  # noqa: BLE001 — keep it prefill then
            self.disagg.add_prefill(rep)
            _LOG.warning("demotion of %s failed at role flip: %s",
                         name, e)
            return
        try:
            self.router.add_replica(rep)
        except ValueError:
            pass   # already present (raced adoption)
        self.disagg.add_decode(rep)
        self._promoted.discard(name)
        self._bump("demotions")
        self._last_change = time.monotonic()
        _health.emit("fleet_demotion", agent=name, to_role="decode")
        if obs.enabled():
            obs.counter("serve/fleet_demotions").inc()
            obs.instant("serve/fleet_demotion", agent=name)
        _LOG.info("demoted %s back to decode duty", name)

    # -- warming ---------------------------------------------------------

    def _warm(self, rep: RemoteReplica):
        """Pre-warm a joining replica's prefix cache from a live peer
        (the PR-16 ``warm_replica`` hop) so scale-up serves warm.
        Strictly best-effort: a failed warm is a cold join, not a
        failed join."""
        if self.policy.warm_limit <= 0 or self.warm_prompts is None:
            return
        prompts = (self.warm_prompts() if callable(self.warm_prompts)
                   else self.warm_prompts)
        prompts = list(prompts)[:self.policy.warm_limit]
        if not prompts:
            return
        source = next(
            (m for m in self._members.values()
             if m.name != rep.name and not m._client.closed), None)
        if source is None:
            return
        try:
            out = warm_replica(source, rep, prompts,
                               timeout_s=self.every_s * 120)
            self._bump("warm_prompts", out.get("warmed", 0))
        except Exception as e:  # noqa: BLE001 — warming is optional
            _LOG.warning("prefix warming for %s failed: %s",
                         rep.name, e)

    def _bump(self, key: str, n: int = 1):
        with self._stats_lock:
            self._stats[key] += n


def controller_threads_alive() -> int:
    """Live controller loops (tests assert 0 after stop)."""
    return sum(1 for t in threading.enumerate() if t.is_alive()
               and t.name.startswith(CONTROLLER_THREAD))
