"""Continuous batching: iteration-level LM decode scheduling.

The :class:`~.engine.ServingEngine` batches ONE-SHOT forwards — a
request enters a micro-batch, the batch dispatches, every row resolves.
Autoregressive decode breaks that shape: a request is not one forward
but hundreds, and whole-request batching (cut a batch, run EVERY
member's full generation, return together) makes short requests wait
for the longest member while freed rows decode as padding. Continuous
batching (Orca's iteration-level scheduling; the vLLM serving loop)
reschedules at DECODE-STEP boundaries instead: requests join the
running batch the step after they arrive, leave the step they finish,
and the ONE compiled decode step stays hot the whole time — scheduling
work onto fixed compiled shapes rather than reshaping per request, the
same discipline the training side's superstep/bucket work rides.

Shape discipline (why recompiles never happen mid-traffic):

* the KV cache is PAGED (``kv_cache.PagedKVCache``) — fixed-size blocks
  + per-request block tables, so heterogeneous sequence lengths share
  one pooled allocation and the compiled step's cache operand never
  changes shape;
* active rows pad to POWER-OF-TWO buckets (``optim.predictor.
  bucket_for`` — the serving engine's discipline) with a floor of 2:
  XLA CPU lowers 1-row matmuls to a gemv kernel that differs from the
  >=2-row gemm in the last ulp, and a bucket floor of 2 keeps every
  step of every request in ONE gemm M-class — that is what makes a
  request's tokens bitwise-identical whether it decodes alone or with
  the batch reshuffling around it (the correctness gate in
  tests/test_serving_lm.py);
* prompts prefill in fixed CHUNKS (pow-2-bucketed tail) through the
  same paged path, so a long prompt costs O(chunk * Tp) attention
  scratch and a bounded set of compiled shapes;
* shared prompt PREFIXES are served from the content-addressed prefix
  cache (``prefix_cache.PrefixCache`` over the refcounted block
  ledger): admission adopts the longest cached block-aligned prefix
  and skips its prefill chunks — the thousand-identical-system-prompts
  workload pays ONE prefill and stores the pages once, with
  copy-on-write forks guarding the shared pages (docs/SERVING.md
  "Prefix cache").

Hot swap: a request PINS the model version active at its admission and
keeps it to completion — swap() takes effect for later admissions, and
each dispatch serves exactly one version group, so no dispatch (and no
request continuation) ever mixes versions. Speculative decoding
(nn/speculative.py's draft-propose / chunk-verify pattern) rides the
same paged step BATCHED across the whole version group (ISSUE 14):
every greedy row drafts ``spec_k`` tokens in ``spec_k+1`` batched
paged draft steps, ONE chunked verify (``S = spec_k+1`` per row — a
shape ``decode_paged`` and the Pallas kernel already serve) scores
them all, and per-row acceptance lengths (``nn.speculative.
batched_acceptance``, computed in-program) advance each row
independently — rollback is the host-side per-row position counter
(rejected positions hold garbage the position-masked attention never
reads and the next round's writes overwrite; target and draft pools
stay in lockstep). Rows that cannot speculate — sampled rows, whose
acceptance rule is argmax-match — ride the SAME verify dispatch
masked to one real token, so a mixed batch still costs one program.

Per-request telemetry rides the PR-5 rid machinery: ``serve/prefill``
and ``serve/decode_step`` spans carry rids, and every future leaves
with a trace dict ({rid, queue_wait_ms, prefill_ms, ttft_ms, tpot_ms,
decode_steps, tokens, version}) plus the ``serve/ttft_ms`` /
``serve/tpot_ms`` histograms and the tokens/s lines the LM bench
(bench_serving.py --lm) reports. See docs/SERVING.md "Continuous
batching".
"""
from __future__ import annotations

import itertools
import queue
import threading
import time
from collections import deque
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import observability as obs
from ..observability import cluster as _cluster
from ..observability import flight as _flight
from ..observability import health as _health
from ..optim.predictor import bucket_for
from ..parallel import chaos as _chaos
from ..parallel.failure import (FaultPolicy, TransientDeviceError,
                                classify_failure, TRANSIENT)
from .batching import (DeadlineExceeded, EngineStopped, QueueFull,
                       ServeFuture)
from .kv_cache import (SPILL_PENDING, KVCacheOOM, KVSwapManager,
                       PagedKVCache, blocks_for_tokens)
from .prefix_cache import PrefixCache
from .registry import ModelRegistry

THREAD_NAME = "bigdl_tpu-serving-decode-scheduler"

_STAT_KEYS = ("submitted", "completed", "rejected", "timeouts",
              "decode_steps", "prefill_chunks", "tokens", "swaps",
              "spec_rounds", "spec_accepted", "spec_row_rounds",
              "spec_fallbacks", "defrags",
              "prefix_hits", "prefix_misses", "prefix_reused_tokens",
              "prefix_cow_forks", "step_replays", "kv_corruptions",
              "preemptions", "resumes", "resume_recomputes")


def _pow2_bucket(n: int, cap: int, floor: int = 2) -> int:
    """Smallest power of two >= n, floored (gemm M-class — see module
    docstring) and capped."""
    b = floor
    while b < n:
        b <<= 1
    return min(b, cap)


def prefill_schedule(prompt_len: int, chunk: int):
    """The chunked-prefill plan for a prompt: [(start, real, padded)].
    Full chunks run at ``chunk``; the tail pads to a power-of-two
    bucket (floor 2), so the compiled prefill shapes are bounded to
    {2, 4, ..., chunk}. Shared with the solo-decode oracle in
    tests/test_serving_lm.py so both sides chunk identically."""
    out = []
    s = 0
    while s < prompt_len:
        real = min(chunk, prompt_len - s)
        out.append((s, real, _pow2_bucket(real, chunk)))
        s += real
    return out


def prefill_padded_end(prompt_len: int, chunk: int) -> int:
    """Highest position (exclusive) the padded prefill writes — the
    capacity the block reservation must cover."""
    s, real, padded = prefill_schedule(prompt_len, chunk)[-1]
    return s + padded


class LMRequest:
    """One in-flight generation: prompt, budget, and decode state."""

    __slots__ = ("prompt", "max_new_tokens", "eos_id", "future", "rid",
                 "deadline", "t_enqueue", "t_enqueue_ns", "t_admit_ns",
                 "t_first_ns", "t_done_ns", "prefill_ms", "version",
                 "model_version", "slot", "pos", "generated", "steps",
                 "chunks", "pf_i", "temperature", "top_p", "seed",
                 "hit_tokens", "adopted_n", "draft_pos", "spec_rounds",
                 "spec_accepted", "priority", "swap_handle", "resume_seq")

    def __init__(self, prompt, max_new_tokens, eos_id, deadline_s, rid,
                 temperature: float = 0.0, top_p: float = 1.0,
                 seed: int = 0, priority: int = 0):
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.max_new_tokens = int(max_new_tokens)
        self.eos_id = eos_id
        self.temperature = float(temperature)
        self.top_p = float(top_p)
        self.seed = int(seed) & 0xFFFFFFFF
        self.future = ServeFuture()
        self.future.rid = rid
        self.rid = rid
        self.t_enqueue = time.monotonic()
        self.t_enqueue_ns = time.perf_counter_ns()
        self.t_admit_ns = None
        self.t_first_ns = None
        self.t_done_ns = None
        self.prefill_ms = 0.0
        self.deadline = (self.t_enqueue + deadline_s
                         if deadline_s is not None else None)
        self.version = None        # pinned at admission
        self.model_version = None  # the ModelVersion object (params ref)
        self.slot = None
        self.pos = 0               # next cache write position
        self.generated = []
        self.steps = 0             # decode dispatches this request rode
        self.chunks = None         # prefill_schedule, set at admission
        self.pf_i = 0              # next prefill chunk to run
        self.hit_tokens = 0        # prefix-cache hit length (tokens)
        self.adopted_n = 0         # shared blocks adopted at admission
        self.draft_pos = 0         # draft-cache write frontier (tokens);
        #                            < pos means the draft trails the
        #                            target and needs a catch-up prefill
        #                            before its next speculative round
        self.spec_rounds = 0       # speculative rounds this row rode
        self.spec_accepted = 0     # draft tokens the target accepted
        self.priority = int(priority)  # preemption class (higher wins)
        self.swap_handle = None    # HostKVHandle while preempted-to-host
        self.resume_seq = None     # host tokens to re-prefill when the
        #                            swap degraded to recompute

    def expired(self, now: Optional[float] = None) -> bool:
        return (self.deadline is not None
                and (now or time.monotonic()) > self.deadline)


class DecodeScheduler:
    """Iteration-level LM serving over one decoder-only model.

    Parameters
    ----------
    model : LM-mode ``nn.Transformer`` (``models.TransformerLM``).
    max_slots : fixed slot capacity of the running batch (>= 2); active
        rows pad to power-of-two buckets within it.
    block_size / max_seq_len : paged-KV geometry — ``max_seq_len``
        bounds prompt + generation per request (must be <= the model's
        ``max_len``); blocks hold ``block_size`` positions each.
    num_blocks : pooled block count (+1 reserved null block). Default
        sizes the pool so every slot can hold a full ``max_seq_len``
        sequence; shrink it to exercise admission backpressure.
    prefill_chunk : chunked-prefill piece size (pow-2, >= 2).
    draft_model : optional LM sharing the vocab — arms BATCHED greedy
        speculative decoding: at every step boundary, EVERY greedy row
        of a version group drafts ``spec_k`` tokens (batched paged
        draft steps) and one chunked verify dispatch scores the whole
        group, advancing each row by its own acceptance length
        (docs/SERVING.md "Speculative decoding (batched)"). Sampled
        rows ride the same verify masked to one real token;
        sampled-MAJORITY groups and boundaries with a prompt
        mid-prefill fall back to the plain step
        (``serve/spec_fallbacks``).
    admission : ``"continuous"`` (iteration-level — the point of this
        class) or ``"static"`` (whole-request batching: a batch admits
        only when the previous one fully drained — the bench baseline).
    eos_id : default end-of-sequence id (per-request override at
        ``submit``).
    prefix_cache : content-addressed KV block sharing
        (``prefix_cache.PrefixCache``, on by default). Admission looks
        up the longest cached block-aligned prefix of each prompt,
        ADOPTS those blocks (refcount +1, zero copies) and skips their
        prefill chunks entirely — on a hit, TTFT collapses to the tail
        chunk + the first decode step. Completed prefills register
        their full prompt blocks for future hits; under block pressure
        admission reclaims unreferenced entries LRU-first. Reuse is
        keyed on (tokens, model version), so a hot swap never crosses
        versions. Hits align to ``max(prefill_chunk, block_size)`` —
        the warm suffix then re-runs EXACTLY the cold schedule's
        remaining chunks (same shapes, same inputs), which is what
        keeps warm tokens bitwise-identical to a cold solo decode. A
        fully-cached aligned prompt re-runs only its LAST chunk for the
        first-token logits; that chunk's writes into shared pages take
        copy-on-write forks (reserved at admission — no mid-flight
        OOM).
    sampling_seed : base of the per-request sampling key stream
        (``engine.next_rng_keys``-style: one deterministic stream, one
        seed per request derived from it, so a request's samples are
        reproducible and independent of who shares its batch). Requests
        default to greedy; ``submit(..., temperature=, top_p=, seed=)``
        opts into sampling per request.
    mesh / placement : model-parallel serving — a
        ``jax.sharding.Mesh`` plus a param-placement policy (``"tp"`` /
        ``"fsdp"`` / spec tree / callable, see
        ``parallel.sharding.serving_param_specs``). Published versions
        device-put SHARDED onto the mesh, the paged KV pool lives on the
        mesh (kvH split over the ``model`` axis when it divides), and
        the same compiled paged step dispatches over it with
        XLA-inserted collectives. Speculative decoding is single-device
        only (``draft_model`` + ``mesh`` raises).
    name : replica name — per-replica watchdog beacon
        (``serving/decode_scheduler[<name>]``) for Router health
        integration.
    fault_policy : the Tier-2 retry budget for the compiled-step
        dispatch path (the serving analog of
        ``Optimizer.set_fault_policy``, one policy surface shared with
        :class:`~.engine.ServingEngine`'s batch retry). Every decode
        group / prefill chunk / speculative round snapshots the
        host-side step state (page handles + per-row counters) BEFORE
        dispatching; a failure classified TRANSIENT restores the
        snapshot, backs off, and replays the identical dispatch — the
        operands are immutable and the pages functional, so a replayed
        step is bitwise the step a fault-free run takes. PERMANENT
        failures (and an exhausted budget) kill the loop: a crash
        bundle with per-request triage lands, and every in-flight
        request fails typed :class:`EngineStopped` carrying its
        already-generated tokens on ``.partial`` — the splice point
        for the Router's KV-preserving failover. Default: one
        immediate retry (``FaultPolicy(max_restarts=1,
        backoff_base_s=0)``); pass ``FaultPolicy(max_restarts=0)`` to
        disable replay.
    audit_every : loop passes between KV-ledger audits
        (:meth:`audit`; 0 disables the cadence — shutdown still
        audits). A violation QUARANTINES the ledger instead of crashing
        the loop: a ``health/kv_corruption`` event + crash bundle land
        once, and admission stops creating NEW shared state (prefix
        lookups/registrations bypass) while in-flight traffic keeps
        draining.
    host_blocks : size of the host-RAM KV paging tier (ISSUE 18) in
        BLOCKS; 0 (default) disables it. When armed, prefix-cache
        evictions SPILL to host RAM instead of dropping (a later lookup
        refills — the second chance) and the scheduler gains swap-based
        preemption. All swaps are scheduled at step boundaries and
        staged asynchronously — the compiled step never blocks on a
        transfer (docs/SERVING.md "KV memory hierarchy").
    preempt : allow swap-based preemption (needs ``host_blocks``):
        when admission of a higher-``priority`` request hits block
        pressure, the lowest-priority decoding request's pages swap
        out, it re-enters the backlog, and re-admission refills and
        resumes BITWISE (the PR-13 snapshotted-handles argument; a
        failed stage degrades to recompute from host-resident tokens —
        never corrupt). ``False`` keeps spill/refill but never
        interrupts a running request.
    """

    def __init__(self, model, *, max_slots: int = 8, block_size: int = 16,
                 max_seq_len: int = 256, num_blocks: Optional[int] = None,
                 prefill_chunk: int = 32, draft_model=None, spec_k: int = 4,
                 max_queue: int = 256,
                 default_deadline_ms: Optional[float] = None,
                 eos_id: Optional[int] = None,
                 registry: Optional[ModelRegistry] = None,
                 admission: str = "continuous",
                 static_wait_ms: float = 4.0,
                 stall_deadline_s: Optional[float] = None,
                 sampling_seed: int = 0,
                 prefix_cache: bool = True,
                 prefix_cache_entries: Optional[int] = None,
                 mesh=None, placement=None,
                 name: Optional[str] = None,
                 tags=(),
                 fault_policy: Optional[FaultPolicy] = None,
                 audit_every: int = 256,
                 host_blocks: int = 0,
                 preempt: bool = True):
        if model.mode != "lm":
            raise ValueError("DecodeScheduler serves LM-mode models")
        if max_slots < 2:
            raise ValueError(f"max_slots must be >= 2 (the bucket floor "
                             f"— see module docstring), got {max_slots}")
        if prefill_chunk < 2 or (prefill_chunk & (prefill_chunk - 1)):
            raise ValueError(f"prefill_chunk must be a power of two >= 2, "
                             f"got {prefill_chunk}")
        if max_seq_len > model.max_len:
            raise ValueError(f"max_seq_len {max_seq_len} > model.max_len "
                             f"{model.max_len}")
        if admission not in ("continuous", "static"):
            raise ValueError(f"admission must be 'continuous' or 'static', "
                             f"got {admission!r}")
        if mesh is not None and draft_model is not None:
            raise ValueError("speculative decoding is single-device only — "
                             "drop draft_model or the mesh")
        model.ensure_initialized()
        self.model = model
        self.max_slots = int(max_slots)
        self.max_seq_len = int(max_seq_len)
        self.prefill_chunk = int(prefill_chunk)
        self.admission = admission
        self.default_deadline_ms = default_deadline_ms
        self.eos_id = eos_id
        self.sampling_seed = int(sampling_seed)
        self.spec_k = int(spec_k)
        self.name = name
        # capability labels the Router's class→replica affinity matches
        # against PriorityClass(replica_tags=...) — e.g. an
        # int8-published replica tags itself "int8" so bulk traffic can
        # pin to it while tight traffic rides the f32 fleet
        self.tags = tuple(tags)
        self.beacon_name = ("serving/decode_scheduler" if name is None
                            else f"serving/decode_scheduler[{name}]")
        self.mesh = mesh
        self._page_axis = None   # mesh axis the pages' kvH dim splits over
        page_sharding = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            from ..parallel import sharding as _sh
            if registry is not None and placement is not None:
                raise ValueError(
                    "placement= is applied by the registry the scheduler "
                    "builds — with an explicit registry= it would be "
                    "silently ignored; construct the registry with "
                    "mesh/param_specs yourself, or drop one argument")
            self._op_sharding = NamedSharding(mesh, P())
            kvh = model.blocks[0].attn._kvh()
            if "model" in mesh.axis_names and mesh.shape["model"] > 1 \
                    and kvh % mesh.shape["model"] == 0:
                # pooled K/V pages split over KV heads: the decode-path
                # HBM lever under tensor parallelism — each shard holds
                # kvH/tp heads of every block
                page_sharding = NamedSharding(mesh, P(None, "model"))
                self._page_axis = "model"
            else:
                page_sharding = self._op_sharding
            if registry is None:
                registry = ModelRegistry(
                    mesh=mesh,
                    param_specs=_sh.serving_param_specs(
                        model.params, mesh, placement))
        mbs = blocks_for_tokens(max_seq_len, block_size)
        if num_blocks is None:
            num_blocks = self.max_slots * mbs + 1
        self.kv = PagedKVCache(model, num_blocks=num_blocks,
                               block_size=block_size,
                               max_blocks_per_seq=mbs,
                               sharding=page_sharding)
        # host-RAM paging tier (ISSUE 18): one async staging pipeline
        # under the device pool, shared by the prefix cache's second
        # chance and swap-based preemption
        self.kv_swap = (KVSwapManager(self.kv, host_blocks, tag=name)
                        if host_blocks > 0 else None)
        self.preempt_enabled = bool(preempt) and self.kv_swap is not None
        # prefix reuse aligns to max(chunk, block): hits leave the cold
        # schedule's remaining chunks intact (same compiled shapes, same
        # inputs — the bitwise contract; both are powers of two, so the
        # smaller always divides the larger)
        self.hit_align = max(self.prefill_chunk, int(block_size))
        self.prefix = (PrefixCache(self.kv,
                                   max_entries=prefix_cache_entries,
                                   swap=self.kv_swap)
                       if prefix_cache else None)
        self.draft_model = draft_model
        self.draft_kv = None
        if draft_model is not None:
            if draft_model.vocab_size != model.vocab_size:
                raise ValueError("draft and target must share a vocabulary")
            draft_model.ensure_initialized()
            self.draft_kv = PagedKVCache(draft_model, num_blocks=num_blocks,
                                         block_size=block_size,
                                         max_blocks_per_seq=mbs,
                                         metric_prefix="serve/draft_kv")
        self.registry = registry or ModelRegistry()
        if self.registry.current() is None:
            self.registry.publish(model.params, model.state, version="v0",
                                  activate=True)
        self._greedy_args = {}  # bucket -> device-resident greedy triple
        self._step_jit = self._build_step(model, "serve/decode_step")
        self._draft_jit = (self._build_step(draft_model, "serve/draft_step")
                           if draft_model is not None else None)
        # per-row acceptance lengths computed IN-PROGRAM: one readback
        # per spec round carries (accept_len, emitted tokens) for the
        # whole batch (nn/speculative.py)
        from ..nn.speculative import batched_acceptance
        self._accept_jit = jax.jit(batched_acceptance)
        self.static_wait_ms = float(static_wait_ms)
        self.max_queue = int(max_queue)
        self._q: queue.Queue = queue.Queue(maxsize=self.max_queue)
        self._defrag_wanted = threading.Event()
        self._backlog: deque = deque()   # scheduler-local, arrival order
        self._prefilling: deque = deque()  # admitted, prompt mid-prefill
        self._active: list = []          # decoding LMRequests, slot order
        self._free_slots = list(range(self.max_slots - 1, -1, -1))
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        self._stop = threading.Event()
        self._pending = 0
        self._cond = threading.Condition()
        self._stats = dict.fromkeys(_STAT_KEYS, 0)
        self._stats_lock = threading.Lock()
        self._rids = itertools.count()
        self.stall_deadline_s = stall_deadline_s
        self._beacon = _health.NULL_BEACON
        self._snap_writer = _cluster.default_writer()
        # Tier-2 replay: default is the engine's historical one-shot
        # immediate retry, now expressed through the shared policy
        self.fault_policy = (fault_policy if fault_policy is not None
                             else FaultPolicy(max_restarts=1,
                                              backoff_base_s=0.0))
        self.audit_every = int(audit_every)
        self._audit_tick = 0
        self._quarantined = False

    def _build_step(self, model, name):
        """The ONE compiled paged decode step: next-token choices for
        every (row, chunk-position) plus the functionally-updated pages.
        Params are arguments, so every model version shares the
        executable; distinct (bucket, S) shapes compile once each.

        The trace runs under ``parallel.flash.paged_serving_context``
        carrying this scheduler's (mesh, kv-head shard axis), so the
        Pallas paged-attention kernel — when ``BIGDL_TPU_PAGED_ATTN``
        enables it — dispatches shard_map'd per kv-head group under TP
        placement and plain everywhere else. The draft model is
        single-device by construction (mesh+draft refused), so its step
        traces with no mesh.

        Token choice is per-row: greedy argmax when ``temps[b] <= 0``
        (bitwise the pre-sampling behavior — the correctness gate),
        temperature + top-p (nucleus) sampling otherwise. Sampling keys
        derive IN-PROGRAM from ``fold_in(PRNGKey(seeds[b]), position)``
        — a function of the request's seed and the absolute position
        only, so a sampled request draws the same tokens whether it
        decodes alone or mid-swarm (batch-mix independence, same
        contract the gemm M-class floor gives greedy). The whole
        sampling branch sits under ``lax.cond``: an all-greedy dispatch
        (the common case) never pays the sort."""

        def sample(logits, positions, seeds, temps, top_ps):
            B, S, V = logits.shape
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

            def sampled():
                base = jax.vmap(jax.random.PRNGKey)(
                    seeds.astype(jnp.uint32))
                pos = positions[:, None] + jnp.arange(S)[None, :]
                keys = jax.vmap(lambda k, ps: jax.vmap(
                    lambda p: jax.random.fold_in(k, p))(ps))(base, pos)
                t = jnp.maximum(temps, 1e-6)[:, None, None]
                scaled = logits / t
                order = jnp.argsort(-scaled, axis=-1)
                srt = jnp.take_along_axis(scaled, order, axis=-1)
                probs = jax.nn.softmax(srt, axis=-1)
                cum = jnp.cumsum(probs, axis=-1)
                # nucleus: keep while the mass BEFORE a token is < p
                # (the top-1 token always survives)
                keep = (cum - probs) < top_ps[:, None, None]
                masked = jnp.where(keep, srt, -jnp.inf)
                pick = jax.vmap(jax.random.categorical)(
                    keys.reshape(B * S, -1), masked.reshape(B * S, V))
                tok = jnp.take_along_axis(order.reshape(B * S, V),
                                          pick[:, None], axis=-1)[:, 0]
                tok = tok.reshape(B, S).astype(jnp.int32)
                # per-row: greedy rows of a mixed batch stay greedy
                return jnp.where(temps[:, None] > 0.0, tok, greedy)

            return jax.lax.cond(jnp.any(temps > 0.0), sampled,
                                lambda: greedy)

        mesh = self.mesh if model is self.model else None
        axis = self._page_axis if model is self.model else None
        from ..parallel import flash as _flash

        def step(params, pages, tokens, positions, tables, seeds, temps,
                 top_ps):
            with _flash.paged_serving_context(mesh=mesh, shard_axis=axis):
                logits, pages = model.decode_paged(
                    params, tokens, positions, pages, tables)
            return sample(logits, positions, seeds, temps, top_ps), pages

        return obs.perf.instrument_jit(jax.jit(step), name=name,
                                       kind="forward",
                                       key_argnums=(2, 3, 4))

    def _put(self, a):
        """Operand placement for one dispatch: replicated onto the mesh
        when serving model-parallel (params/pages carry the sharded
        placement; XLA inserts the collectives), plain transfer
        otherwise."""
        if self.mesh is not None:
            return jax.device_put(np.asarray(a), self._op_sharding)
        return jnp.asarray(a)

    def _sampling_args(self, rows, bucket):
        """(seeds, temps, top_ps) operands for one dispatch — padded
        slots are greedy (temp 0), so they never pay sampling work.
        The all-greedy triple (the default workload, and every padded
        warmup/draft/spec dispatch) is constant per bucket and cached
        device-resident, so the hot decode loop adds no per-step
        transfers until a request actually opts into sampling."""
        if all(r.temperature <= 0.0 for r in rows):
            cached = self._greedy_args.get(bucket)
            if cached is None:
                cached = (self._put(np.zeros((bucket,), np.uint32)),
                          self._put(np.zeros((bucket,), np.float32)),
                          self._put(np.ones((bucket,), np.float32)))
                self._greedy_args[bucket] = cached
            return cached
        seeds = np.zeros((bucket,), np.uint32)
        temps = np.zeros((bucket,), np.float32)
        top_ps = np.ones((bucket,), np.float32)
        for i, r in enumerate(rows):
            seeds[i] = r.seed
            temps[i] = r.temperature
            top_ps[i] = r.top_p
        return self._put(seeds), self._put(temps), self._put(top_ps)

    # -- lifecycle -------------------------------------------------------

    def start(self, warmup: bool = True):
        if self._thread is not None and self._thread.is_alive():
            return self
        if self._closed:
            raise EngineStopped("scheduler was shut down; build a new one")
        if warmup:
            self.warmup()
        self._beacon = _health.beacon(self.beacon_name,
                                      deadline_s=self.stall_deadline_s)
        self._thread = threading.Thread(target=self._run, name=THREAD_NAME,
                                        daemon=True)
        self._thread.start()
        return self

    def warmup(self):
        """Precompile EVERY shape the scheduler can dispatch — decode
        buckets {2, 4, ..., max_slots}, prefill chunk shapes
        {2, 4, ..., prefill_chunk}, and the speculative draft/verify
        shapes — by driving the compiled step against the null block
        table (writes land in the reserved garbage block). With the
        persistent compile cache on, a restarted server warms from disk;
        either way no live request ever pays an XLA compile."""
        def shapes_upto(cap, lo=2):
            out, b = [], lo
            while b < cap:
                out.append(b)
                b <<= 1
            out.append(cap)
            return out

        def drive(jit_fn, pages_of, B, S):
            cache = pages_of
            table = np.zeros((B, cache.max_blocks_per_seq), np.int32)
            with obs.span("serve/warmup_decode", shape=(B, S)):
                choices, pages = jit_fn(
                    self.registry.current().params if cache is self.kv
                    else self.draft_model.params,
                    cache.pages(), self._put(np.zeros((B, S), np.int32)),
                    self._put(np.zeros((B,), np.int32)), self._put(table),
                    *self._sampling_args((), B))
                cache.set_pages(pages)
                # sync-ok: warmup precompile — runs before serving starts
                jax.block_until_ready(choices)

        for b in shapes_upto(self.max_slots):
            drive(self._step_jit, self.kv, b, 1)
        for s in shapes_upto(self.prefill_chunk):
            drive(self._step_jit, self.kv, 1, s)
        if self.draft_model is not None:
            # batched speculation touches every (bucket, S) pair: the
            # draft steps and the S=spec_k+1 verify run at EVERY decode
            # bucket (the whole version group rides one round), and the
            # draft's (1, s) prefill/catch-up shapes mirror the
            # target's chunk schedule
            for b in shapes_upto(self.max_slots):
                drive(self._draft_jit, self.draft_kv, b, 1)
                drive(self._step_jit, self.kv, b, self.spec_k + 1)
                # the in-program acceptance schedule compiles per
                # bucket too — live traffic must add zero compiles
                # (operands ride _put like every live dispatch, so the
                # warmed placement matches; today mesh+draft is refused
                # and _put is a plain transfer, but the invariant must
                # survive a future mesh-served spec path)
                jax.block_until_ready(self._accept_jit(  # sync-ok: warmup
                    self._put(np.zeros((b, self.spec_k), np.int32)),
                    self._put(np.zeros((b, self.spec_k + 1), np.int32)),
                    self._put(np.zeros((b,), bool))))
            for s in shapes_upto(self.prefill_chunk):
                drive(self._draft_jit, self.draft_kv, 1, s)
        if self.kv_swap is not None:
            # the stager's bucketed gathers compile too — paying one on
            # the staging thread under live traffic stalls every spill
            # behind it (the second-chance window closes PENDING)
            self.kv_swap.warmup()
        return self

    def drain(self, timeout: Optional[float] = None) -> bool:
        with self._cond:
            return self._cond.wait_for(lambda: self._pending == 0, timeout)

    def shutdown(self, drain: bool = True, timeout: float = 60.0):
        """Graceful by default: stop admitting, serve everything already
        queued/active to completion, join. ``drain=False`` abandons all
        in-flight work with typed :class:`EngineStopped` failures. Either
        way every KV block returns to the free list before this returns
        (``serve/kv_blocks_in_use`` drains to zero — the leak gate)."""
        with self._cond:
            self._closed = True
        if not drain:
            self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
            if t.is_alive():
                import logging
                # the drain is overrunning its budget: hard-stop the
                # loop and give it one short grace to exit at the next
                # step boundary — the cleanup below mutates scheduler-
                # owned state and MUST NOT race a live loop
                logging.getLogger(__name__).warning(
                    "decode scheduler did not join within %.0fs — "
                    "hard-stopping", timeout)
                self._stop.set()
                t.join(10.0)
                if t.is_alive():
                    # wedged inside a dispatch: leave its state alone
                    # (freeing live requests' blocks under a running
                    # loop would let a later admission alias their
                    # pages); the stall watchdog owns this failure mode
                    logging.getLogger(__name__).error(
                        "decode scheduler wedged — skipping state "
                        "cleanup; clients fail via the stall watchdog")
                    self._beacon.close()
                    return
        self._beacon.close()
        # hard stop (or a dead scheduler): fail whatever is left, free
        # its blocks — a client must never hang and a block never leak
        self._abandon_inflight("scheduler shut down before completion")
        # the shutdown audit: the ledger must be consistent at the end
        # of every run (violations quarantine + bundle, never raise)
        self._audit("shutdown")
        # every owner is gone — drop the prefix cache's pins so the
        # shared pages return too (the kv_blocks_in_use -> 0 leak gate
        # holds on every shutdown path, sharing included)
        if self.prefix is not None:
            self.prefix.clear()
        # ... and the host tier drains with it: _release settled every
        # preempted handle, prefix.clear() every spilled one, so the
        # stager has nothing live left — stop it (the wedged path above
        # returns early and leaves the daemon thread; the stall
        # watchdog owns that failure mode)
        if self.kv_swap is not None:
            self.kv_swap.shutdown()

    def __enter__(self):
        return self.start()

    def __exit__(self, exc_type, exc, tb):
        self.shutdown(drain=exc_type is None)
        return False

    # -- client surface --------------------------------------------------

    def submit(self, prompt_ids, max_new_tokens: int,
               deadline_ms: Optional[float] = None,
               eos_id="default", temperature: float = 0.0,
               top_p: float = 1.0,
               seed: Optional[int] = None,
               priority: int = 0) -> ServeFuture:
        """Enqueue ONE generation request: ``prompt_ids`` (1-D int) →
        future resolving to the GENERATED ids (np.int32, prompt
        excluded). Raises :class:`QueueFull` / typed rejection
        on over-budget requests; a deadline that expires mid-generation
        fails the future with :class:`DeadlineExceeded` whose
        ``partial`` attribute carries the tokens generated so far.

        ``temperature=0`` (default) decodes greedy — bitwise the
        pre-sampling behavior. ``temperature>0`` samples with top-p
        ``top_p`` under a per-request key stream: ``seed`` pins the
        stream explicitly (same seed ⇒ same tokens, regardless of
        batch mix); when None, the seed derives deterministically from
        the scheduler's ``sampling_seed`` and this request's rid.

        ``priority`` is the preemption class (default 0): with the host
        tier armed, admission of a higher-priority request under block
        pressure may swap a lower-priority DECODING request out to host
        RAM; the victim resumes bitwise when blocks free up. Equal
        priorities never preempt each other."""
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if temperature < 0:
            raise ValueError(f"temperature must be >= 0, got {temperature}")
        if not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("prompt must be non-empty")
        spec_over = (self.spec_k + 1) if self.draft_model is not None else 0
        worst = max(prefill_padded_end(prompt.size, self.prefill_chunk),
                    prompt.size + max_new_tokens + spec_over)
        if worst > self.max_seq_len:
            raise ValueError(
                f"prompt {prompt.size} + max_new {max_new_tokens} "
                f"(+ padding/speculation headroom) needs {worst} positions "
                f"> max_seq_len {self.max_seq_len}")
        ms = deadline_ms if deadline_ms is not None \
            else self.default_deadline_ms
        eid = self.eos_id if eos_id == "default" else eos_id
        rid = next(self._rids)
        if seed is None:
            # next_rng_keys-style stream: a splitmix-flavored fold of
            # (base, rid) — deterministic per request, decorrelated
            # across requests, zero device work
            seed = ((self.sampling_seed * 0x9E3779B9 + rid * 0x85EBCA6B
                     + 0xC2B2AE35) & 0xFFFFFFFF)
        req = LMRequest(prompt, max_new_tokens, eid,
                        ms / 1000.0 if ms is not None else None,
                        rid, temperature=temperature, top_p=top_p,
                        seed=seed, priority=priority)
        try:
            with self._cond:
                if self._closed:
                    raise EngineStopped("scheduler is shutting down")
                self._q.put_nowait(req)
                self._pending += 1
        except queue.Full:
            self._bump("rejected")
            if obs.enabled():
                obs.counter("serve/rejected").inc()
            raise QueueFull(
                f"request queue at capacity ({self.max_queue}) — shed or "
                "retry with backoff")
        req.future.add_done_callback(lambda f: self._on_done(f))
        self._bump("submitted")
        return req.future

    def generate(self, prompt_ids, max_new_tokens: int,
                 timeout: Optional[float] = None, **kw) -> np.ndarray:
        """Synchronous convenience: ``submit(...).result(timeout)``."""
        if self._thread is None:
            raise RuntimeError("scheduler not started — call start() or "
                               "use it as a context manager")
        return self.submit(prompt_ids, max_new_tokens, **kw).result(timeout)

    def swap(self, params, state=None, version: Optional[str] = None) -> str:
        """Hot swap: load + activate a new version. In-flight requests
        keep the version they pinned at admission to their last token
        (dispatches are cut per version group — no program ever sees two
        param sets); admissions after this call serve the new version.
        ``state=None`` inherits the active version's state (a
        params-only swap must not change the compiled step's pytree)."""
        if state is None:
            cur = self.registry.current()
            state = cur.state if cur is not None else self.model.state
        v = self.registry.publish(params, state, version=version,
                                  activate=False)
        self.registry.activate(v)
        self._bump("swaps")
        if obs.enabled():
            obs.instant("serve/swap", version=v)
        return v

    def defrag(self) -> int:
        """Request a block-pool defrag at the next step boundary (safe:
        the scheduler thread runs it between dispatches). Synchronous
        when called before start() or after shutdown."""
        if self._thread is None or not self._thread.is_alive():
            n = self.kv.defrag()
            if self.draft_kv is not None:
                n += self.draft_kv.defrag()
            if n:
                self._bump("defrags")
            return n
        self._defrag_wanted.set()
        return -1  # deferred; watch serve/kv_defrag_moves

    def stats(self) -> dict:
        with self._stats_lock:
            out = dict(self._stats)
        out["pending"] = self._pending
        out["queue_depth"] = self._q.qsize() + len(self._backlog)
        out["active"] = len(self._active)
        out["prefilling"] = len(self._prefilling)
        out["active_version"] = self.registry.active_version
        out["quarantined"] = self._quarantined
        out["kv"] = self.kv.stats()
        out["prefix"] = (self.prefix.stats() if self.prefix is not None
                         else None)
        out["host"] = (self.kv_swap.stats() if self.kv_swap is not None
                       else None)
        return out

    def cached_prefix_tokens(self, prompt_ids) -> int:
        """Router-affinity probe: how many leading tokens of this
        prompt admission would actually REUSE from this replica's
        prefix cache under the active version — the raw resident chain
        aligned down to ``hit_align``, so the router never steers a
        request toward a fragment admission will discard. Pure host
        work (a digest walk) — safe to call from router dispatch
        threads; 0 with the cache disabled (or the ledger
        quarantined — the router must not steer toward a cache
        admission will refuse to adopt from)."""
        if self.prefix is None or self._quarantined:
            return 0
        mv = self.registry.current()
        if mv is None:
            return 0
        t = self.prefix.peek(prompt_ids, mv.version)
        return t - t % self.hit_align

    # -- transient step replay (Tier-2, ISSUE 13) ------------------------

    def _snapshot_step_state(self, rows):
        """Host-side snapshot of everything ONE compiled step group can
        mutate, taken BEFORE the dispatch: the functional page handles
        of both pools (the compiled step returns NEW handles — holding
        the old ones IS the rollback) and the per-row decode counters.
        Pure reference/int copies — no device touch, no allocation
        proportional to model size."""
        return (self.kv.pages(),
                self.draft_kv.pages() if self.draft_kv is not None
                else None,
                [(r, r.pos, r.steps, len(r.generated), r.pf_i,
                  r.draft_pos) for r in rows])

    def _restore_step_state(self, snap):
        pages, dpages, rows = snap
        self.kv.set_pages(pages)
        if dpages is not None:
            self.draft_kv.set_pages(dpages)
        for r, pos, steps, ngen, pf_i, draft_pos in rows:
            r.pos, r.steps, r.pf_i = pos, steps, pf_i
            r.draft_pos = draft_pos
            del r.generated[ngen:]

    def _replay_group(self, stage, rows, fn):
        """Dispatch ``fn`` under the fault policy: a failure classified
        into the policy's retry classes restores the pre-dispatch
        snapshot, backs off (injectable sleep — fault drills run at
        full speed), and replays. The operand arrays are immutable and
        the snapshot restores the exact page handles, so a replayed
        group is BITWISE the group a fault-free run dispatches — the
        serving analog of the trainer's superstep replay. Failures
        outside the budget/classes propagate to :meth:`_die` (crash
        bundle + typed in-flight failures)."""
        pol = self.fault_policy
        snap = self._snapshot_step_state(rows)
        while True:
            try:
                out = fn()
            except BaseException as e:  # noqa: BLE001 — classify, maybe replay
                cls = classify_failure(e)
                self._restore_step_state(snap)
                if pol is None or self._stop.is_set() \
                        or not pol.should_retry(cls):
                    raise
                pol.record_failure()
                self._bump("step_replays")
                if obs.enabled():
                    obs.counter("serve/step_replays").inc()
                _health.emit("serve_step_replay", stage=stage,
                             failure_class=cls, attempt=pol.consecutive,
                             rids=[r.rid for r in rows],
                             error=f"{type(e).__name__}: {e}")
                delay = pol.backoff_s()
                if delay > 0:
                    pol.sleep(delay)
                continue
            if pol is not None:
                pol.record_success()
            return out

    # -- KV ledger auditor (ISSUE 13) ------------------------------------

    def audit(self) -> dict:
        """Run the ledger invariant checker over the target pool (with
        the prefix cache's exact pin map) and the draft pool. Pure host
        work at a quiesced point — the scheduler thread runs it on the
        ``audit_every`` cadence and at shutdown; callers may run it any
        time the loop is not mid-dispatch. Returns the merged
        :meth:`PagedKVCache.audit` report."""
        pins = (self.prefix.pinned_blocks() if self.prefix is not None
                else {})
        rep = self.kv.audit(prefix_pins=pins)
        if self.draft_kv is not None:
            drep = self.draft_kv.audit(prefix_pins={})
            rep = {"ok": rep["ok"] and drep["ok"],
                   "violations": rep["violations"]
                   + [f"draft: {v}" for v in drep["violations"]],
                   "blocks": rep["blocks"] + drep["blocks"],
                   "owners": rep["owners"] + drep["owners"]}
        return rep

    def _audit(self, where: str) -> dict:
        """Cadence/shutdown audit: a violation QUARANTINES instead of
        crashing — serving a corrupt ledger read-only beats killing
        every in-flight client, but creating NEW shared state in it
        (prefix adoption, registration) would spread the corruption, so
        that stops. One ``health/kv_corruption`` event + crash bundle
        land on the FIRST detection; later audits just count."""
        rep = self.audit()
        if rep["ok"]:
            return rep
        first = not self._quarantined
        self._quarantined = True
        if first:
            # one corruption episode = ONE count on both surfaces (the
            # stats key and the obs counter stay in lockstep, like
            # every other stat here); later cadence audits of the same
            # quarantined ledger change nothing
            self._bump("kv_corruptions")
            if obs.enabled():
                obs.counter("serve/kv_corruptions").inc()
            _health.emit("kv_corruption", component=self.beacon_name,
                         where=where, n_violations=len(rep["violations"]),
                         violations=rep["violations"][:8])
            if obs.enabled():
                _flight.dump_crash_bundle(error=None, context={
                    "component": "serving/decode_scheduler",
                    "event": "kv_corruption", "where": where,
                    "violations": rep["violations"][:32],
                    "requests": self._triage()})
        return rep

    # -- scheduler loop --------------------------------------------------

    def _run(self):
        try:
            self._loop()
        except BaseException as e:  # noqa: BLE001 — post-mortem, then die
            self._die(e)
            raise

    def _triage(self):
        """Per-request state for the crash bundle: who was in flight,
        how far along, and what it held — the table
        ``tools/flight_report.py`` renders as the post-mortem's
        in-flight section."""
        out = []

        def add(r, stage):
            out.append({"rid": r.rid, "stage": stage,
                        "prompt_len": int(r.prompt.size),
                        "tokens": len(r.generated),
                        "kv_blocks": self.kv.owned(r.rid),
                        "version": r.version})

        for r in self._active:
            add(r, "decode")
        for r in self._prefilling:
            add(r, "prefill")
        for r in self._backlog:
            add(r, "backlog")
        return out

    def _die(self, error):
        """Loop death (a PERMANENT dispatch fault, or an exhausted
        replay budget): land the crash bundle WITH per-request triage,
        then fail every in-flight request typed — active/prefilling
        requests carry the tokens they already generated on
        ``exc.partial``, which is what lets a Router failover re-seed a
        survivor with ``prompt + partial`` instead of losing the decode
        state — and return every block so the ledger drains."""
        if obs.enabled():
            _flight.dump_crash_bundle(error=error, context={
                "component": "serving/decode_scheduler",
                "failure_class": classify_failure(error),
                "requests": self._triage(),
                "stats": {k: v for k, v in self.stats().items()
                          if k not in ("kv", "prefix")}})
        with self._cond:
            self._closed = True
        self._abandon_inflight(
            f"decode scheduler died: {type(error).__name__}: {error}")
        if self.prefix is not None:
            self.prefix.clear()
        if self.kv_swap is not None:
            self.kv_swap.shutdown()
        self._beacon.close()

    def _abandon_inflight(self, msg: str):
        """Gather every request the scheduler still holds (active,
        prefilling, backlogged, queued), release their resources, and
        fail each typed :class:`EngineStopped` with the generated
        prefix attached on ``.partial`` — the Router's KV-preserving
        splice point. Both death paths (shutdown's hard-stop cleanup
        and :meth:`_die`) share this, so the partial-carrying contract
        cannot drift between them."""
        leftovers = list(self._active) + list(self._prefilling) \
            + list(self._backlog)
        self._active.clear()
        self._prefilling.clear()
        self._backlog.clear()
        while True:
            try:
                leftovers.append(self._q.get_nowait())
            except queue.Empty:
                break
        for r in leftovers:
            partial = np.asarray(r.generated, np.int32)
            self._release(r)
            if not r.future.done():
                exc = EngineStopped(msg)
                # these tokens are real — bitwise the uninterrupted
                # run's prefix — so a failover can resume from them
                exc.partial = partial
                try:
                    r.future.set_exception(exc)
                except Exception:
                    pass

    def _loop(self):
        """The iteration-level loop: every pass is one step boundary —
        drain arrivals, admit into free slots, advance ONE prefill
        chunk, ONE decode dispatch per active version group, evict
        finished/expired rows. Prefill is interleaved chunk-at-a-time
        so a joining long prompt never head-of-line-blocks the running
        batch for more than one chunk's forward. Nothing in here blocks
        on the device except the per-step token readbacks."""
        while not self._stop.is_set():
            self._beacon.pulse()
            if obs.enabled():
                self._snap_writer.maybe_write()
            self._drain_arrivals()
            self._admit()
            stepped = self._advance_prefill()
            stepped |= self._step_all()
            self._evict_expired()
            if self._defrag_wanted.is_set():
                self._defrag_wanted.clear()
                try:
                    n = self.kv.defrag()
                    if self.draft_kv is not None:
                        n += self.draft_kv.defrag()
                except Exception as e:  # noqa: BLE001 — transient = skip
                    # a TRANSIENT page-copy failure aborts the repack
                    # with the ledger untouched — skip the round (the
                    # next defrag() request retries) rather than kill
                    # every in-flight generation over an optimization
                    if classify_failure(e) != TRANSIENT:
                        raise
                    _health.emit("serve_defrag_skipped",
                                 error=f"{type(e).__name__}: {e}")
                else:
                    if n:
                        self._bump("defrags")
            if self.audit_every > 0:
                self._audit_tick += 1
                if self._audit_tick >= self.audit_every:
                    self._audit_tick = 0
                    self._audit("cadence")
            if self._closed and not self._active and not self._prefilling \
                    and not self._backlog and self._q.empty():
                break
            if not stepped:
                # idle (or static mode waiting out its fill window):
                # block briefly on the queue so arrival→admission
                # latency stays low without a spin
                try:
                    self._backlog.append(self._q.get(
                        timeout=0.002 if self._backlog else 0.02))
                    self._pull_pending()
                except queue.Empty:
                    pass

    def _pull_pending(self):
        while True:
            try:
                self._backlog.append(self._q.get_nowait())
            except queue.Empty:
                return

    def _drain_arrivals(self):
        self._pull_pending()

    def _admit(self):
        """Admit backlog head-of-line into free slots at this step
        boundary. A request is admitted only when its WORST-CASE block
        need is reservable, so no later step can OOM mid-flight; static
        mode additionally waits for the running batch to fully drain
        (whole-request batching — the bench baseline). FIFO order is
        kept even when a smaller later request would fit (no starvation
        of large requests)."""
        if self.admission == "static":
            if self._active or self._prefilling:
                return
            if self._backlog and len(self._backlog) < self.max_slots \
                    and not self._closed:
                # whole-request batching needs a fill window (the
                # ServingEngine's max_wait_ms analog): wait briefly for
                # the batch to fill rather than running a batch of one
                oldest = self._backlog[0].t_enqueue
                if (time.monotonic() - oldest) * 1000.0 < \
                        self.static_wait_ms:
                    return
        while self._backlog and self._free_slots:
            req = self._backlog[0]
            if req.future.cancelled():
                self._backlog.popleft()
                self._finish(req, cancel=True)
                continue
            if req.expired():
                self._backlog.popleft()
                self._expire(req)
                continue
            if req.swap_handle is not None or req.resume_seq is not None:
                # a preempted request resumes through refill-before-
                # resume, never ordinary admission (its decode state is
                # on the host tier, not in its prompt). Deferring keeps
                # it at the head — FIFO, so resumption cannot starve
                # behind a stream of fresh arrivals.
                if not self._resume_preempted(req):
                    break
                continue
            # spec_over is PER SLOT: under batched speculation every
            # active row (sampled ones included — they ride the verify
            # dispatch masked to one real token, whose padded lanes
            # still write k+1 positions) may overshoot by spec_k+1
            spec_over = (self.spec_k + 1) if self.draft_model is not None \
                else 0
            worst = max(
                prefill_padded_end(req.prompt.size, self.prefill_chunk),
                req.prompt.size + req.max_new_tokens + spec_over)
            mv = self.registry.current()
            cold = prefill_schedule(req.prompt.size, self.prefill_chunk)
            plan, adopted, fork_idxs = self._prefix_plan(req, mv.version,
                                                         cold)
            forked = []
            try:
                # worst-case PRIVATE need: total blocks minus the shared
                # prefix it adopts, plus the copy-on-write pages its
                # warm plan must fork
                need = (blocks_for_tokens(worst, self.kv.block_size)
                        - len(adopted) + len(fork_idxs))
                if adopted:
                    self.kv.adopt(req.rid, adopted)
                try:
                    if self.prefix is not None \
                            and not self.kv.can_allocate(need):
                        # block pressure: reclaim unreferenced prefix
                        # entries (LRU, leaf-first) before deferring —
                        # the blocks just adopted are pinned (refcount
                        # >= 2) and cannot be taken back out from under
                        # this request
                        self.prefix.evict(need - self.kv.blocks_free())
                    if not self.kv.can_allocate(need):
                        raise KVCacheOOM(
                            f"need {need} private blocks, "
                            f"{self.kv.blocks_free()} free")
                    self.kv.ensure_capacity(req.rid, worst)
                    if self.draft_kv is not None:
                        self.draft_kv.ensure_capacity(req.rid, worst)
                    if fork_idxs:
                        # copy-on-write EAGERLY, inside the same
                        # admission transaction that checked the free
                        # list: a later admission may consume every
                        # free block, and a fork deferred to prefill
                        # time would then OOM mid-flight (the invariant
                        # this whole block exists to uphold)
                        forked = self.kv.fork_blocks(req.rid, fork_idxs)
                except (KVCacheOOM, TransientDeviceError):
                    # undo the adoption and any partial growth — a
                    # deferred request must leave the ledger untouched
                    self.kv.free(req.rid)
                    raise
            except (KVCacheOOM, TransientDeviceError) as e:
                # backpressure: leave it queued — eviction will free
                # blocks and the next boundary retries. A TRANSIENT
                # fault in the admission transaction (an injected
                # cow-fork/evict failure) takes the same deferral:
                # the transaction unwound, the request just waits.
                # Under REAL block pressure a higher-priority arrival
                # may instead swap a lower-priority decoding request
                # out to the host tier and retry immediately (ISSUE
                # 18) — admission stops deferring when spilling a
                # victim frees enough blocks.
                if isinstance(e, KVCacheOOM) and self._try_preempt(req):
                    continue
                break
            self._backlog.popleft()
            req.slot = self._free_slots.pop()
            req.version = mv.version
            req.model_version = mv
            req.t_admit_ns = time.perf_counter_ns()
            req.chunks = plan
            req.pf_i = 0
            if self.prefix is not None:
                self._bump("prefix_hits" if req.hit_tokens
                           else "prefix_misses")
                # honest savings accounting: tokens the warm plan does
                # NOT prefill — in the rerun-last-chunk case the tail
                # chunk's tokens are re-computed, so they don't count
                reused = int(req.prompt.size) - sum(c[1] for c in plan)
                if reused:
                    self._bump("prefix_reused_tokens", reused)
                if forked:
                    self._bump("prefix_cow_forks", len(forked))
                if obs.enabled():
                    if req.hit_tokens:
                        obs.counter("serve/prefix_hits").inc()
                    else:
                        obs.counter("serve/prefix_misses").inc()
                    if reused:
                        obs.counter("serve/prefix_reused_tokens").inc(
                            reused)
                    if forked:
                        obs.counter("serve/prefix_cow_forks").inc(
                            len(forked))
            if not req.future.set_running_or_notify_cancel():
                self._finish(req, cancel=True)
                continue
            self._prefilling.append(req)

    def _prefix_plan(self, req, version, cold):
        """Prefill-skip admission: returns ``(chunks_to_run,
        adopted_blocks, cow_fork_idxs)``. A miss (or a disabled cache)
        runs the full cold schedule. A hit adopts the longest cached
        ``hit_align``-aligned prefix and keeps only the cold schedule's
        chunks at/after it — identical shapes over identical inputs, so
        warm tokens stay bitwise the cold solo decode's. A FULLY cached
        aligned prompt keeps just its last chunk (the first-token
        logits must still be computed); the adopted blocks that chunk
        overwrites are returned as ``cow_fork_idxs`` for the admission
        transaction to fork EAGERLY — deferring the fork to prefill
        time would let an interleaved admission drain the free list and
        OOM it mid-flight."""
        req.hit_tokens = 0
        req.adopted_n = 0
        if self.prefix is None or self._quarantined:
            # a quarantined ledger serves, but adopting shared pages
            # out of it would spread whatever the auditor caught
            return cold, [], []
        bs = self.kv.block_size
        chain = self.prefix.lookup(req.prompt, version)
        h = min(len(chain) * bs, int(req.prompt.size))
        h -= h % self.hit_align
        if h <= 0:
            return cold, [], []
        adopted = chain[:h // bs]
        plan = [c for c in cold if c[0] >= h] or [cold[-1]]
        fork_idxs = []
        s0, _, padded0 = plan[0]
        if s0 < h:
            # rerun-last-chunk case: adopted blocks the chunk overwrites
            fork_idxs = list(range(
                s0 // bs, min(len(adopted), -(-(s0 + padded0) // bs))))
        req.hit_tokens = h
        req.adopted_n = len(adopted)
        return plan, adopted, fork_idxs

    def _register_prefix(self, req):
        """Prefill done: register every FULL prompt block for future
        hits (content-addressed; the tail partial block — still
        receiving this request's decode writes — is never shared).
        Blocks already indexed (the adopted prefix, or a concurrent
        twin that registered first) are refreshed, not re-inserted, so
        a shared system prompt stays resident ONCE."""
        if self.prefix is None or self._quarantined:
            return
        nfull = int(req.prompt.size) // self.kv.block_size
        if not nfull:
            return
        try:
            self.prefix.insert(req.prompt, req.version,
                               self.kv.owner_blocks(req.rid)[:nfull])
        except Exception as e:  # noqa: BLE001 — transient = degrade
            # a TRANSIENT failure registering the prefix (injected
            # index fault) costs future hits, never correctness — skip
            if classify_failure(e) != TRANSIENT:
                raise
            _health.emit("prefix_insert_skipped", rid=req.rid,
                         error=f"{type(e).__name__}: {e}")

    # -- swap-based preemption (ISSUE 18) --------------------------------

    def _try_preempt(self, for_req) -> bool:
        """Admission hit block pressure: swap the cheapest
        lower-priority DECODING request out to the host tier so
        ``for_req`` can admit now instead of deferring. The victim's
        pages snapshot at this boundary (the stager fetches them
        asynchronously — immutable functional handles, so freeing the
        device blocks immediately is safe), it re-enters the backlog
        right behind the request it yielded to, and re-admission
        refills and resumes bitwise. Returns True when a victim was
        preempted (the caller retries admission in the same pass)."""
        if not self.preempt_enabled:
            return False
        cands = [r for r in self._active if r.priority < for_req.priority]
        if not cands:
            return False
        # lowest priority first; among equals the fewest owned blocks —
        # the cheapest swap that relieves the pressure
        victim = min(cands, key=lambda r: (r.priority,
                                           self.kv.owned(r.rid)))
        blocks = self.kv.owner_blocks(victim.rid)
        if not blocks:
            return False
        h = self.kv_swap.spill(blocks, tag="preempt")
        if h is None and self.prefix is not None \
                and self.prefix.drop_spilled(len(blocks)):
            # host pressure: a running request's decode state outranks
            # cold spilled prefix chains — drop the coldest and retry
            h = self.kv_swap.spill(blocks, tag="preempt")
        if h is None:
            return False
        victim.swap_handle = h
        # the snapshot keeps the bytes alive for the stager — the
        # device blocks return to the free list at THIS boundary
        self.kv.free(victim.rid)
        if self.draft_kv is not None:
            self.draft_kv.free(victim.rid)
        victim.draft_pos = 0
        self._active.remove(victim)
        self._free_slots.append(victim.slot)
        victim.slot = None
        # behind the head request it yielded to; model version stays
        # pinned — the resumed stream must finish on the params it
        # started with
        self._backlog.insert(1, victim)
        self._bump("preemptions")
        if obs.enabled():
            obs.counter("serve/preemptions").inc()
        _flight.record("serve/preempt", rid=victim.rid,
                       for_rid=for_req.rid, blocks=len(blocks))
        return True

    def _resume_preempted(self, req) -> bool:
        """Refill-before-resume for the backlog head: land the
        preempted request's host pages back in the device pool and
        return it to the running batch — its decode continues from the
        exact position it was interrupted at, bitwise (the refilled
        pages are digest-verified copies of the snapshotted handles —
        the PR-13 replay argument). A stage still in flight, a full
        device pool, or a full draft pool DEFERS (False — retry next
        boundary); a failed/corrupt stage DEGRADES to re-prefilling the
        host-resident tokens through the ordinary chunk schedule (the
        router-failover recompute precedent — per-position KV is
        bitwise stable across chunkings). Returns True when the request
        left the backlog (resumed or recomputing)."""
        spec_over = (self.spec_k + 1) if self.draft_model is not None \
            else 0
        keep = int(req.prompt.size) + req.max_new_tokens + spec_over
        h = req.swap_handle
        if h is not None:
            if h.state == SPILL_PENDING:
                return False   # stage in flight — next boundary
            need = h.n_blocks
            if not self.kv.can_allocate(need) and self.prefix is not None \
                    and not self._quarantined:
                self.prefix.evict(need - self.kv.blocks_free())
            if not self.kv.can_allocate(need):
                return False
            if self.draft_kv is not None and not self.draft_kv.can_allocate(
                    blocks_for_tokens(keep, self.kv.block_size)):
                return False
            try:
                ids = self.kv_swap.refill(req.rid, h)
            except KVCacheOOM:
                return False   # handle intact — roomier boundary retries
            if ids is not None:
                req.swap_handle = None
                # single-threaded admission: the can_allocate pre-check
                # above guarantees this growth cannot OOM
                if self.draft_kv is not None:
                    self.draft_kv.ensure_capacity(req.rid, keep)
                self._backlog.popleft()
                req.slot = self._free_slots.pop()
                self._active.append(req)
                self._bump("resumes")
                if obs.enabled():
                    obs.counter("serve/resumes").inc()
                return True
            # stage failed/corrupt (handle settled by the manager):
            # recompute from the host-resident tokens — the KV for
            # positions [0, pos) re-prefills chunk-by-chunk, then
            # decode continues exactly where it stopped
            req.swap_handle = None
            req.resume_seq = np.concatenate(
                [req.prompt,
                 np.asarray(req.generated, np.int32)])[:req.pos]
        seq = req.resume_seq
        worst = max(prefill_padded_end(seq.size, self.prefill_chunk),
                    keep)
        need = blocks_for_tokens(worst, self.kv.block_size)
        if not self.kv.can_allocate(need) and self.prefix is not None \
                and not self._quarantined:
            self.prefix.evict(need - self.kv.blocks_free())
        if not self.kv.can_allocate(need) or (
                self.draft_kv is not None
                and not self.draft_kv.can_allocate(need)):
            return False   # resume_seq persists — retry stays here
        self.kv.ensure_capacity(req.rid, worst)
        if self.draft_kv is not None:
            self.draft_kv.ensure_capacity(req.rid, worst)
        req.chunks = prefill_schedule(seq.size, self.prefill_chunk)
        req.pf_i = 0
        self._backlog.popleft()
        req.slot = self._free_slots.pop()
        self._prefilling.append(req)
        self._bump("resume_recomputes")
        if obs.enabled():
            obs.counter("serve/resume_recomputes").inc()
        _health.emit("kv_swap_recompute", rid=req.rid,
                     tokens=int(seq.size))
        return True

    def _advance_prefill(self) -> bool:
        """ONE prefill chunk for the head admitted-but-prefilling
        request (FIFO), interleaved with the running batch's decode
        steps — a joining 100k-token prompt stalls active generations
        by at most one chunk's forward per step boundary, not its whole
        prefill. The LAST chunk's final real row is the first generated
        token (TTFT stamps there). Returns True when it did work."""
        if not self._prefilling:
            return False
        req = self._prefilling[0]
        mv = req.model_version
        t0 = time.perf_counter_ns()
        s, real, padded = req.chunks[req.pf_i]
        last = req.pf_i == len(req.chunks) - 1
        # a preempted request whose swap stage failed re-prefills its
        # host-resident prompt+generated tokens (resume_seq) through
        # this same chunk machinery; the first-token readback/emit is
        # skipped — its next token comes from the ordinary decode step
        resumed = req.resume_seq is not None
        src = req.resume_seq if resumed else req.prompt
        # write-safety invariant: every block this chunk touches is
        # PRIVATE — warm suffix chunks start past the adopted prefix,
        # and the rerun-last-chunk case's shared blocks were forked
        # copy-on-write inside the admission transaction (_admit)
        toks = np.zeros((1, padded), np.int32)
        toks[0, :real] = src[s:s + real]

        def dispatch():
            _chaos.maybe_fire("serving/prefill", tag=self.name)
            with obs.span("serve/prefill", rid=req.rid, chunk=req.pf_i,
                          of=len(req.chunks), version=req.version):
                table = self.kv.block_table(req.rid)[None]
                choices, pages = self._step_jit(
                    mv.params, self.kv.pages(), self._put(toks),
                    self._put(np.asarray([s], np.int32)),
                    self._put(table), *self._sampling_args([req], 1))
                dpages = None
                if self.draft_kv is not None and req.hit_tokens == 0:
                    # warm prefix-HIT requests skip the draft prefill
                    # with the target's (the adopted region was never
                    # prefilled here) — the draft catches up LAZILY on
                    # the row's first speculative round instead
                    # (_draft_catchup), so a warm hit keeps its spec
                    # eligibility
                    dtable = self.draft_kv.block_table(req.rid)[None]
                    _, dpages = self._draft_jit(
                        self._draft_params(), self.draft_kv.pages(),
                        self._put(toks),
                        self._put(np.asarray([s], np.int32)),
                        self._put(dtable), *self._sampling_args((), 1))
                first_tok = None
                if last and not resumed:
                    # sync-ok: the first generated token — the client's
                    # TTFT — is exactly this readback
                    first_tok = int(np.asarray(choices)[0, real - 1])
                return first_tok, pages, dpages

        first_tok, pages, dpages = self._replay_group(
            "prefill", [req], dispatch)
        self.kv.set_pages(pages)
        if dpages is not None:
            self.draft_kv.set_pages(dpages)
            req.draft_pos = s + real
        self._bump("prefill_chunks")
        req.pf_i += 1
        req.prefill_ms += (time.perf_counter_ns() - t0) / 1e6
        if not last:
            return True
        self._prefilling.popleft()
        self._register_prefix(req)
        # the admission reservation covered the PREFILL's padded chunk
        # tail (prefill_padded_end), which can exceed the generation
        # phase's exact need — return the padding-only tail blocks to
        # the pool now (per-row ledger truncate, refcount-aware: the
        # adopted prefix sits at the table HEAD and is untouched).
        # Nothing re-grows this row's tables afterwards — decode/spec
        # writes are bounded by keep (verify tops out at
        # pos + spec_k < keep, catch-up clamps to the owned capacity) —
        # so the no-mid-flight-OOM invariant keeps holding while
        # backlogged admissions see the reclaimed blocks immediately.
        spec_over = (self.spec_k + 1) if self.draft_model is not None \
            else 0
        keep = int(req.prompt.size) + req.max_new_tokens + spec_over
        self.kv.truncate(req.rid, keep)
        if self.draft_kv is not None:
            self.draft_kv.truncate(req.rid, keep)
        if resumed:
            # recompute complete: KV for [0, pos) is rebuilt (bitwise —
            # per-position KV is chunking-stable), decode picks up with
            # generated[-1] at pos exactly as if never interrupted. No
            # first-token emit, no TTFT restamp — the client already
            # has these tokens.
            req.pos = int(req.resume_seq.size)
            req.resume_seq = None
            self._active.append(req)
            return True
        req.pos = int(req.prompt.size)
        req.t_first_ns = time.perf_counter_ns()
        self._bump("tokens")
        if obs.enabled():
            obs.histogram("serve/prefill_ms", unit="ms").observe(
                req.prefill_ms)
            obs.histogram("serve/ttft_ms", unit="ms").observe(
                (req.t_first_ns - req.t_enqueue_ns) / 1e6)
            obs.counter("serve/lm_tokens").inc()
        self._active.append(req)
        self._emit(req, first_tok)
        return True

    def _draft_params(self):
        return self.draft_model.params

    def _emit(self, req, token) -> bool:
        """Append one generated token; returns True when the request is
        DONE (eos or budget) and has been finished+released."""
        req.generated.append(int(token))
        done = (req.eos_id is not None and int(token) == req.eos_id) \
            or len(req.generated) >= req.max_new_tokens
        if done:
            self._finish(req)
        return done

    def _step_all(self) -> bool:
        """One decode dispatch per active version group (admission
        order). Each dispatch pads its rows to a power-of-two bucket
        (floor 2) of the FIXED slot capacity; padded slots carry the
        null block table, so their writes land in garbage space."""
        if not self._active:
            return False
        groups = {}
        for r in self._active:
            groups.setdefault(r.version, []).append(r)
        for version, rows in list(groups.items()):
            n_elig = sum(1 for r in rows if r.temperature <= 0.0)
            if self.draft_model is not None and n_elig >= 1 \
                    and 2 * n_elig >= len(rows) and not self._prefilling:
                # a GREEDY-MAJORITY group with no prompt mid-prefill
                # rides ONE batched speculative round — greedy rows
                # draft+verify spec_k tokens, sampled rows (argmax-match
                # acceptance cannot apply) ride the same verify dispatch
                # masked to one real token. Two deliberate guards: a
                # sampled-majority group steps plain (each sampled row
                # advances 1 token per round, so a lone greedy row must
                # not tax the majority spec_k+2 dispatches per token),
                # and a multi-token spec burst must not delay a joining
                # request's interleaved prefill chunks (the PR-8 rule;
                # the resulting draft-cache lag is repaid by
                # _draft_catchup on the next round). Spec is
                # output-preserving, so tokens are bitwise the plain
                # step's either way.
                self._spec_step(version, rows)
            else:
                if self.draft_model is not None and rows:
                    # armed but not speculating this boundary (sampled
                    # majority, or prefill-interleave protection):
                    # plain step, counted so operators can see
                    # speculation capacity going unused
                    self._bump("spec_fallbacks")
                    if obs.enabled():
                        obs.counter("serve/spec_fallbacks").inc()
                self._step_group(version, rows)
        return True

    def _step_group(self, version, rows):
        n = len(rows)
        bucket = bucket_for(max(n, 2), self.max_slots)
        tokens = np.zeros((bucket, 1), np.int32)
        positions = np.zeros((bucket,), np.int32)
        tables = np.zeros((bucket, self.kv.max_blocks_per_seq), np.int32)
        for i, r in enumerate(rows):
            tokens[i, 0] = r.generated[-1]
            positions[i] = r.pos
            tables[i] = self.kv.block_table(r.rid)
        mv = rows[0].model_version
        rids = [r.rid for r in rows]

        def dispatch():
            _chaos.maybe_fire("serving/scheduler_step", tag=self.name)
            with obs.span("serve/decode_step", rids=rids, bucket=bucket,
                          version=version):
                choices, pages = self._step_jit(
                    mv.params, self.kv.pages(), self._put(tokens),
                    self._put(positions), self._put(tables),
                    *self._sampling_args(rows, bucket))
                # sync-ok: the per-step token readback — EOS detection
                # and per-client streaming both need the ids on host;
                # this is the one deliberate sync of the decode loop
                return np.asarray(choices)[:, 0], pages

        toks, pages = self._replay_group("decode", rows, dispatch)
        self.kv.set_pages(pages)
        self._bump("decode_steps")
        self._bump("tokens", n)
        for i, r in enumerate(rows):
            r.pos += 1
            r.steps += 1
            self._emit(r, toks[i])
        if obs.enabled():
            obs.counter("serve/decode_steps").inc()
            obs.counter("serve/lm_tokens").inc(n)
            obs.histogram("serve/decode_occupancy").observe(n / bucket)
            obs.gauge("serve/active_slots").set(len(self._active))

    def _draft_catchup(self, req, dparams):
        """Bring one row's draft cache level with its target cache:
        re-prefill positions ``draft_pos..pos-1`` from the tokens the
        row already holds (prompt + generated — all host-resident), in
        the prefill chunk shapes warmup compiled. Two callers leave a
        row trailing: a warm prefix HIT (its draft prefill was skipped
        along with the target's — this is the lazy re-prefill that
        restores spec eligibility, ISSUE 14 satellite) and plain decode
        steps taken while the row was spec-ineligible company or a
        prompt was mid-prefill. The tail chunk's pow-2 padding halves
        until it fits the row's OWNED draft capacity (shrunk to the
        exact generation need once its prefill-padding tail was
        truncated), so a padded write can never run past the row's
        block table."""
        seq = np.concatenate([req.prompt,
                              np.asarray(req.generated, np.int32)])
        dtable = self.draft_kv.block_table(req.rid)[None]
        cap = min(self.max_seq_len,
                  self.draft_kv.owned(req.rid) * self.draft_kv.block_size)
        while req.draft_pos < req.pos:
            real = min(self.prefill_chunk, req.pos - req.draft_pos)
            padded = _pow2_bucket(real, self.prefill_chunk)
            while req.draft_pos + padded > cap:
                padded >>= 1
            real = min(real, padded)
            toks = np.zeros((1, padded), np.int32)
            toks[0, :real] = seq[req.draft_pos:req.draft_pos + real]
            _, dpages = self._draft_jit(
                dparams, self.draft_kv.pages(), self._put(toks),
                self._put(np.asarray([req.draft_pos], np.int32)),
                self._put(dtable), *self._sampling_args((), 1))
            self.draft_kv.set_pages(dpages)
            req.draft_pos += real

    def _spec_step(self, version, rows):
        """ONE batched speculative round for a whole version group
        (ISSUE 14 — the generalization of the PR-8 solo fast path):

        1. eligible rows (greedy) that trail the draft cache catch up
           (:meth:`_draft_catchup`);
        2. ``spec_k+1`` BATCHED paged draft steps propose per-row draft
           chains — the token feed stays device-resident (each step
           consumes the previous step's choices), so the draft phase
           adds ZERO readbacks; the extra (k+1)-th step writes d_k's
           K/V so a fully-accepted round leaves no draft-cache hole
           (nn/speculative.py); ineligible rows ride the draft steps
           against the null table (their draft cache is never touched);
        3. ONE chunked verify — the same compiled paged step at
           ``S = spec_k+1`` — scores every row's ``[last, d_1..d_k]``;
        4. per-row acceptance lengths come back from the in-program
           ``batched_acceptance`` schedule in a single readback, and
           each row emits its accepted prefix + the target's own choice
           at the divergence (ineligible rows: acceptance 0 — exactly
           their plain one-token step, bitwise).

        Rollback is positional: row ``b`` advances ``pos`` by
        ``j_b + 1`` while the round wrote ``spec_k+1`` positions —
        rejected positions hold garbage that position-masked paged
        attention never reads and the next round's (or plain step's)
        writes overwrite, in BOTH pools (``draft_pos`` snaps to ``pos``
        so the pools stay in lockstep). Admission already reserved the
        ``spec_k+1`` overshoot per slot (``spec_over``), so the round's
        writes can never OOM. Output-preserving: every emitted token is
        the target's own choice at its position — the bitwise gate in
        tests/test_serving_lm.py holds per row across any batch mix."""
        k = self.spec_k
        n = len(rows)
        bucket = bucket_for(max(n, 2), self.max_slots)
        elig = np.zeros((bucket,), bool)
        last = np.zeros((bucket, 1), np.int32)
        positions = np.zeros((bucket,), np.int32)
        dpositions = np.zeros((bucket,), np.int32)
        tables = np.zeros((bucket, self.kv.max_blocks_per_seq), np.int32)
        dtables = np.zeros((bucket, self.draft_kv.max_blocks_per_seq),
                           np.int32)
        for i, r in enumerate(rows):
            elig[i] = r.temperature <= 0.0
            last[i, 0] = r.generated[-1]
            positions[i] = r.pos
            tables[i] = self.kv.block_table(r.rid)
        mv = rows[0].model_version
        rids = [r.rid for r in rows]
        dparams = self._draft_params()
        samp = self._sampling_args(rows, bucket)
        greedy = self._sampling_args((), bucket)

        def round_fn():
            _chaos.maybe_fire("serving/spec_round", tag=self.name)
            with obs.span("serve/spec_round", rids=rids, k=k,
                          bucket=bucket, version=version):
                for i, r in enumerate(rows):
                    if elig[i] and r.draft_pos < r.pos:
                        self._draft_catchup(r, dparams)
                    if elig[i]:
                        # fetched AFTER catch-up — tables are stable
                        # within a round, but keep one read order
                        dtables[i] = self.draft_kv.block_table(r.rid)
                        dpositions[i] = r.pos
                tok = self._put(last)
                last_dev = tok
                dtab_dev = self._put(dtables)
                drafts = []
                for i in range(k + 1):
                    choices, dpages = self._draft_jit(
                        dparams, self.draft_kv.pages(), tok,
                        self._put(dpositions + i), dtab_dev, *greedy)
                    self.draft_kv.set_pages(dpages)
                    tok = choices
                    if i < k:
                        drafts.append(choices)
                drafts_c = jnp.concatenate(drafts, axis=1)   # (B, k)
                chunk = jnp.concatenate([last_dev, drafts_c], axis=1)
                vchoices, pages = self._step_jit(
                    mv.params, self.kv.pages(), chunk,
                    self._put(positions), self._put(tables), *samp)
                self.kv.set_pages(pages)
                j, emit = self._accept_jit(drafts_c, vchoices,
                                           self._put(elig))
                # sync-ok: the per-round readback — acceptance lengths
                # + emitted tokens drive EOS/budget bookkeeping on host
                return jax.device_get((j, emit))

        # the replay snapshot covers BOTH pools' page handles and every
        # row's (pos, draft_pos, generated) — a transient anywhere in
        # the round (catch-up, draft burst, verify) rolls the whole
        # round back and replays it bitwise
        j, emit = self._replay_group("spec", rows, round_fn)
        self._bump("decode_steps")
        self._bump("spec_rounds")
        nrow = nacc = ntok = 0
        for i, r in enumerate(rows):
            ji = int(j[i])
            r.pos += ji + 1
            r.steps += 1
            if elig[i]:
                r.draft_pos = r.pos
                r.spec_rounds += 1
                r.spec_accepted += ji
                nrow += 1
                nacc += ji
                if obs.enabled():
                    obs.histogram("serve/spec_accepted_len").observe(ji)
            for t in emit[i, :ji + 1]:
                ntok += 1
                if self._emit(r, int(t)):
                    break
        self._bump("spec_row_rounds", nrow)
        self._bump("spec_accepted", nacc)
        self._bump("tokens", ntok)
        if obs.enabled():
            obs.counter("serve/spec_rounds").inc()
            obs.counter("serve/spec_accepted").inc(nacc)
            obs.counter("serve/lm_tokens").inc(ntok)

    # -- eviction / completion -------------------------------------------

    def _evict_expired(self):
        now = time.monotonic()
        for r in list(self._active):
            if r.expired(now):
                self._expire(r)
        for r in list(self._prefilling):
            if r.expired(now):
                self._expire(r)
        for r in list(self._backlog):
            if r.expired(now):
                self._backlog.remove(r)
                self._expire(r)

    def _expire(self, req):
        self._bump("timeouts")
        if obs.enabled():
            obs.counter("serve/timeouts").inc()
        exc = DeadlineExceeded(
            f"deadline passed after {len(req.generated)} of "
            f"{req.max_new_tokens} tokens")
        # the tokens generated before eviction are real (and bitwise
        # equal to a solo decode's prefix) — hand them to the client
        exc.partial = np.asarray(req.generated, np.int32)
        self._release(req)
        try:
            req.future.set_exception(exc)
        except Exception:
            pass

    def _finish(self, req, cancel: bool = False):
        req.t_done_ns = time.perf_counter_ns()
        self._release(req)
        if cancel:
            return
        out = np.asarray(req.generated, np.int32)
        n = out.size
        tpot = ((req.t_done_ns - req.t_first_ns) / 1e6 / (n - 1)
                if (req.t_first_ns and n > 1) else 0.0)
        req.future.version = req.version
        req.future.trace = {
            "rid": req.rid,
            "queue_wait_ms": ((req.t_admit_ns or req.t_enqueue_ns)
                              - req.t_enqueue_ns) / 1e6,
            "prefill_ms": round(req.prefill_ms, 3),
            "ttft_ms": ((req.t_first_ns - req.t_enqueue_ns) / 1e6
                        if req.t_first_ns else None),
            "tpot_ms": round(tpot, 3),
            "decode_steps": req.steps,
            "tokens": n,
            "version": req.version,
            "prefix_hit_tokens": req.hit_tokens,
            "spec_rounds": req.spec_rounds,
            "spec_accepted": req.spec_accepted,
        }
        self._bump("completed")
        if obs.enabled():
            obs.counter("serve/lm_completed").inc()
            if tpot:
                obs.histogram("serve/tpot_ms", unit="ms").observe(tpot)
            _flight.record("serve/lm_done", rid=req.rid, tokens=n,
                           steps=req.steps, version=req.version)
        try:
            req.future.set_result(out)
        except Exception:
            pass

    def _release(self, req):
        """Return every engine resource a request holds: its slot, its
        KV blocks (both caches), and any host-tier reservation a
        preemption left behind (the host pool must drain to 0 at every
        shutdown path, like the device pool). Safe to call twice."""
        if req in self._active:
            self._active.remove(req)
        if req in self._prefilling:
            self._prefilling.remove(req)
        if req.slot is not None:
            self._free_slots.append(req.slot)
            req.slot = None
        self.kv.free(req.rid)
        if self.draft_kv is not None:
            self.draft_kv.free(req.rid)
        if req.swap_handle is not None:
            self.kv_swap.discard(req.swap_handle)
            req.swap_handle = None
        req.resume_seq = None
        req.model_version = None

    # -- internals -------------------------------------------------------

    def _on_done(self, future):
        with self._cond:
            self._pending -= 1
            self._cond.notify_all()

    def _bump(self, key: str, n: int = 1):
        with self._stats_lock:
            self._stats[key] += n


def decode_scheduler_threads_alive() -> int:
    """Live scheduler threads (tests assert 0 after shutdown)."""
    return sum(1 for t in threading.enumerate()
               if t.name == THREAD_NAME and t.is_alive())
