"""The online serving engine: queue → batcher thread → compiled forward.

Request flow: ``submit(sample)`` runs admission control against a
bounded queue (full ⇒ typed :class:`~.batching.QueueFull`, the
backpressure signal) and returns a future. One batcher thread coalesces
queued requests into a micro-batch — flushing on ``max_batch`` reached
OR ``max_wait_ms`` elapsed, whichever first — pads it to a power-of-two
shape bucket (``optim.predictor.bucket_for``), reads the active model
version ONCE, dispatches the ONE compiled forward shared with
``Predictor`` (``optim.predictor.shared_forward``), and scatters row
``i`` of the result to request ``i``'s future. Per-request dispatch
over a device link is the overhead the whole dispatch-amortization
line of work exists to kill; the batcher turns 16 concurrent 1-sample
dispatches into one 16-row dispatch.

Robustness is structural, not bolted on: a malformed input fails ITS
future during assembly (``batching.assemble``) and the batch around it
still serves; a forward error fails that batch's futures and the
batcher keeps running; per-request deadlines expire in the batcher
(typed ``DeadlineExceeded``); ``shutdown()`` drains by default — stop
admitting, flush what's queued immediately (no ``max_wait_ms`` lag),
then join the thread. Hot swap rides the version registry: ``swap()``
device-loads new params on the CALLER's thread while traffic keeps
flowing, then atomically activates; because the batcher snapshots the
version per batch, every response is old-or-new, never mixed.
"""
from __future__ import annotations

import itertools
import queue
import threading
import time
from typing import Optional, Sequence, Tuple

import jax
import numpy as np

from .. import observability as obs
from ..observability import cluster as _cluster
from ..observability import flight as _flight
from ..observability import health as _health
from ..optim.predictor import bucket_for, pad_leading, shape_buckets, \
    shared_forward
from ..optim.staging import place_host_value
from ..parallel import chaos as _chaos
from ..parallel.failure import FaultPolicy, classify_failure
from .batching import (DeadlineExceeded, EngineStopped, QueueFull, Request,
                       ServeFuture, assemble)
from .registry import ModelRegistry

THREAD_NAME = "bigdl_tpu-serving-batcher"

_STAT_KEYS = ("submitted", "completed", "rejected", "timeouts", "batches",
              "batch_errors", "request_errors", "swaps",
              "transient_retries")


class ServingEngine:
    """In-process online inference over one model architecture.

    Parameters
    ----------
    model : nn.Module — defines the forward; its current params become
        version ``v0`` in the registry.
    input_shape : per-SAMPLE shape (no batch dim). When given, warmup
        precompiles every bucket at ``start()`` and assembly validates
        against it; when None, the first request of a batch sets the
        template and compiles lazily.
    max_batch : bucket ceiling — also the flush size.
    max_wait_ms : batching window; the latency the FIRST request of a
        sparse batch donates to fill the bucket (`docs/SERVING.md` for
        the p99 tradeoff).
    max_queue : admission-control bound; ``submit`` past it raises
        :class:`QueueFull`.
    default_deadline_ms : per-request deadline applied when ``submit``
        does not pass one (None = no deadline).
    stall_deadline_s : watchdog deadline for the batcher's progress
        beacon (None = the ``BIGDL_TPU_STALL_S`` default; active only
        while observability is enabled).
    mesh / placement / batch_spec : model-parallel serving. ``mesh`` is
        a ``jax.sharding.Mesh`` the engine dispatches over; ``placement``
        is the param PartitionSpec policy (``"tp"`` /
        ``"fsdp"`` / ``"replicated"`` / a spec tree / a callable —
        ``parallel.sharding.serving_param_specs``) the registry uses for
        every sharded publish; the padded batch device-puts with
        ``batch_spec`` (default ``P(("replica", "data"))`` restricted to
        the axes the mesh has — ``serving_batch_spec``). Buckets then
        floor at the batch-shard count so every shard gets whole rows.
    name : replica name — distinguishes this engine's watchdog beacon
        (``serving/batcher[<name>]``) and metrics provenance when N
        replicas serve behind a :class:`~.router.Router`.
    fault_policy : the Tier-2 retry budget for the batch dispatch —
        ONE policy surface shared with the trainer
        (``Optimizer.set_fault_policy``) and the
        :class:`~.decode_scheduler.DecodeScheduler`'s step replay: max
        CONSECUTIVE retries, exponential backoff, injectable sleep. A
        failure classified TRANSIENT (``parallel/failure.
        classify_failure``) re-dispatches the same batch; anything
        else — or an exhausted budget — fails the batch's futures and
        the batcher lives on. Default ``FaultPolicy(max_restarts=1,
        backoff_base_s=0)`` — the historical one-shot immediate retry;
        ``FaultPolicy(max_restarts=0)`` disables retry entirely.
    """

    def __init__(self, model, *, input_shape: Optional[Sequence[int]] = None,
                 input_dtype=np.float32, max_batch: int = 16,
                 max_wait_ms: float = 2.0, max_queue: int = 128,
                 default_deadline_ms: Optional[float] = None,
                 registry: Optional[ModelRegistry] = None,
                 warmup: bool = True,
                 stall_deadline_s: Optional[float] = None,
                 mesh=None, placement=None, batch_spec=None,
                 name: Optional[str] = None, tags=(),
                 fault_policy: Optional[FaultPolicy] = None):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        self.model = model
        model.ensure_initialized()
        self.input_shape = (tuple(input_shape)
                            if input_shape is not None else None)
        self.input_dtype = np.dtype(input_dtype)
        self.max_batch = int(max_batch)
        self.max_wait_ms = float(max_wait_ms)
        self.max_queue = int(max_queue)
        self.default_deadline_ms = default_deadline_ms
        self._warmup_on_start = warmup
        self._fwd = shared_forward(model)
        self.name = name
        # Router class→replica affinity labels (PriorityClass
        # replica_tags= matches any-of against these)
        self.tags = tuple(tags)
        self.beacon_name = ("serving/batcher" if name is None
                            else f"serving/batcher[{name}]")
        self.mesh = mesh
        self._batch_sharding = None
        self._bucket_floor = 1
        if mesh is not None:
            from jax.sharding import NamedSharding
            from ..parallel import sharding as _sh
            if registry is not None and placement is not None:
                raise ValueError(
                    "placement= is applied by the registry the engine "
                    "builds — with an explicit registry= it would be "
                    "silently ignored; construct the registry with "
                    "mesh/param_specs yourself, or drop one argument")
            spec = (batch_spec if batch_spec is not None
                    else _sh.serving_batch_spec(mesh))
            self._batch_sharding = NamedSharding(mesh, spec)
            self._bucket_floor = _sh.batch_shard_count(mesh, spec)
            if self._bucket_floor > self.max_batch \
                    or self.max_batch % self._bucket_floor:
                raise ValueError(
                    f"max_batch {self.max_batch} must be a multiple of the "
                    f"batch shard count {self._bucket_floor} (mesh "
                    f"{dict(mesh.shape)}, batch spec {spec}) so every "
                    "bucket splits into whole per-shard rows")
            if registry is None:
                registry = ModelRegistry(
                    mesh=mesh,
                    param_specs=_sh.serving_param_specs(
                        model.params, mesh, placement))
        self.registry = registry or ModelRegistry()
        if self.registry.current() is None:
            self.registry.publish(model.params, model.state, version="v0",
                                  activate=True)
        self._q: queue.Queue = queue.Queue(maxsize=int(max_queue))
        self._thread: Optional[threading.Thread] = None
        self._closed = False      # no new admissions; batcher drains
        self._stop = threading.Event()   # hard stop: abandon the queue
        self._pending = 0         # submitted, future not yet done
        self._cond = threading.Condition()
        self._stats = dict.fromkeys(_STAT_KEYS, 0)
        self._stats_lock = threading.Lock()
        # per-request trace ids, minted at submit(): the id flows
        # queue→assemble→dispatch→scatter so the three stage spans and
        # the future's trace dict all name the same request
        self._rids = itertools.count()
        self.fault_policy = (fault_policy if fault_policy is not None
                             else FaultPolicy(max_restarts=1,
                                              backoff_base_s=0.0))
        self.stall_deadline_s = stall_deadline_s
        self._beacon = _health.NULL_BEACON
        # serving processes join the cluster metric view too (same
        # BIGDL_TPU_METRIC_SNAP_S cadence; no-op when unset)
        self._snap_writer = _cluster.default_writer()

    # -- lifecycle -------------------------------------------------------

    def start(self):
        """Spawn the batcher (idempotent) and, when ``input_shape`` is
        known, warmup-compile every bucket shape so the first real
        request never pays an XLA compile."""
        if self._thread is not None and self._thread.is_alive():
            return self
        if self._closed:
            raise EngineStopped("engine was shut down; build a new one")
        if self._warmup_on_start and self.input_shape is not None:
            self.warmup()
        # the batcher registers with the stall watchdog: it pulses per
        # collect cycle (bounded 50ms idle poll), so silence means a
        # wedged dispatch — every queued client is stuck behind it
        self._beacon = _health.beacon(self.beacon_name,
                                      deadline_s=self.stall_deadline_s)
        self._thread = threading.Thread(
            target=self._batcher, name=THREAD_NAME, daemon=True)
        self._thread.start()
        return self

    def warmup(self):
        """Compile the forward for every bucket in
        ``shape_buckets(max_batch)`` against the active version. With the
        persistent compile cache on (``engine.maybe_enable_compilation_
        cache``, called inside the shared forward's first build), a
        restarted server warms from disk instead of XLA."""
        if self.input_shape is None:
            raise ValueError("warmup needs input_shape")
        mv = self.registry.current()
        for b in self._buckets():
            with obs.span("serve/warmup", bucket=b):
                x = self._place_batch(
                    np.zeros((b,) + self.input_shape, self.input_dtype))
                # sync-ok: warmup precompile — runs before serving starts
                jax.block_until_ready(self._fwd(mv.params, mv.state, x))
        return self

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Block until every admitted request has resolved (True) or
        ``timeout`` seconds pass (False). Does not stop the engine."""
        with self._cond:
            return self._cond.wait_for(lambda: self._pending == 0, timeout)

    def shutdown(self, drain: bool = True, timeout: float = 30.0):
        """Graceful by default: stop admitting, let the batcher flush the
        queue (immediately — the ``max_wait_ms`` window collapses once
        closed), join the thread. ``drain=False`` abandons queued
        requests: each pending future fails with :class:`EngineStopped`."""
        with self._cond:  # paired with submit's atomic check-and-enqueue
            self._closed = True
        if not drain:
            self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
            if t.is_alive():
                import logging
                logging.getLogger(__name__).warning(
                    "serving batcher did not join within %.0fs", timeout)
        self._beacon.close()
        # anything still queued (hard stop, or a wedged batcher) fails
        # typed rather than hanging its client forever
        while True:
            try:
                req = self._q.get_nowait()
            except queue.Empty:
                break
            if not req.future.cancelled():
                try:
                    req.future.set_exception(
                        EngineStopped("engine shut down before dispatch"))
                except Exception:
                    pass

    def __enter__(self):
        return self.start()

    def __exit__(self, exc_type, exc, tb):
        self.shutdown(drain=exc_type is None)
        return False

    # -- client surface --------------------------------------------------

    def submit(self, x, deadline_ms: Optional[float] = None) -> ServeFuture:
        """Enqueue ONE unbatched sample; returns the future its batch
        will resolve. Raises :class:`QueueFull` (admission control) or
        :class:`EngineStopped` (shutdown began). ``deadline_ms``
        overrides the engine default; a request whose deadline passes
        before its batch dispatches fails with
        :class:`DeadlineExceeded` and is counted in ``serve/timeouts``.

        Submitting before :meth:`start` is allowed — requests queue (and
        age against their deadlines) until the batcher comes up, so a
        server can begin admitting while warmup compiles."""
        ms = deadline_ms if deadline_ms is not None else \
            self.default_deadline_ms
        req = Request(x, deadline_s=ms / 1000.0 if ms is not None else None,
                      rid=next(self._rids))
        try:
            # closed-check and enqueue are ONE atomic step vs shutdown's
            # close (same lock): an admitted request is therefore in the
            # queue strictly before _closed flips, so the batcher's drain
            # (or shutdown's final fail-queued sweep) always sees it — a
            # check-then-put race would strand a future forever
            with self._cond:
                if self._closed:
                    raise EngineStopped("engine is shutting down")
                self._q.put_nowait(req)
                self._pending += 1
        except queue.Full:
            self._bump("rejected")
            if obs.enabled():
                obs.counter("serve/rejected").inc()
            raise QueueFull(
                f"request queue at capacity ({self.max_queue}) — shed or "
                "retry with backoff")
        req.future.add_done_callback(
            lambda f, t0=req.t_enqueue: self._on_done(f, t0))
        self._bump("submitted")
        if obs.enabled():
            obs.gauge("serve/queue_depth").set(self._q.qsize())
        return req.future

    def predict(self, x, timeout: Optional[float] = None,
                deadline_ms: Optional[float] = None):
        """Synchronous convenience: ``submit(x).result(timeout)``."""
        if self._thread is None:
            raise RuntimeError("engine not started — call start() or use "
                               "it as a context manager")
        return self.submit(x, deadline_ms=deadline_ms).result(timeout)

    def swap(self, params, state=None, version: Optional[str] = None) -> str:
        """Hot swap: device-load new params (on THIS thread — traffic
        keeps flowing) and atomically activate. The old version finishes
        the batches already cut against it; no response mixes versions.
        Returns the new version id (rollback = ``registry.activate(old)``).

        ``state=None`` (a params-only swap) INHERITS the active
        version's state — the compiled forward's state pytree must not
        change shape under it, and carrying running stats across a
        weight refresh is the sensible default."""
        if state is None:
            cur = self.registry.current()
            state = cur.state if cur is not None else self.model.state
        v = self.registry.publish(params, state, version=version,
                                  activate=False)
        self.registry.activate(v)
        self._bump("swaps")
        if obs.enabled():
            obs.instant("serve/swap", version=v)
        return v

    def stats(self) -> dict:
        with self._stats_lock:
            out = dict(self._stats)
        out["pending"] = self._pending
        out["queue_depth"] = self._q.qsize()
        out["active_version"] = self.registry.active_version
        return out

    # -- batcher ---------------------------------------------------------

    def _batcher(self):
        try:
            while not self._stop.is_set():
                self._beacon.pulse()
                if obs.enabled():
                    self._snap_writer.maybe_write()
                batch = self._collect()
                if batch:
                    self._dispatch(batch)
                elif self._closed:
                    break  # drained: closed engine with an empty queue
        except BaseException as e:  # noqa: BLE001 — post-mortem, then die
            # per-batch errors are contained in _dispatch; anything that
            # escapes is a batcher crash — every future client would
            # hang, so leave a flight-recorder bundle for the operator
            if obs.enabled():
                _flight.dump_crash_bundle(error=e, context={
                    "component": "serving/batcher",
                    "stats": self.stats()})
            raise

    def _collect(self):
        """One micro-batch: first request blocks (bounded poll so
        shutdown is prompt), then fill until ``max_batch`` or the
        ``max_wait_ms`` window ends. Once the engine is closing, the
        window collapses — drain flushes at queue speed."""
        try:
            first = self._q.get(timeout=0.05)
        except queue.Empty:
            return None
        batch = [first]
        flush_at = time.monotonic() + self.max_wait_ms / 1000.0
        while len(batch) < self.max_batch and not self._stop.is_set():
            wait = flush_at - time.monotonic()
            if self._closed:
                wait = 0.0
            try:
                if wait <= 0:
                    batch.append(self._q.get_nowait())
                else:
                    batch.append(self._q.get(timeout=wait))
            except queue.Empty:
                break
        if obs.enabled():
            obs.gauge("serve/queue_depth").set(self._q.qsize())
        return batch

    def _dispatch(self, batch):
        """Serve one micro-batch against ONE version snapshot. The
        per-request trace decomposes here: queue wait (enqueue → batch
        cut, retro-span from the request's own stamp), assemble (stack
        + validate), dispatch (pad + place + forward + readback) — each
        stage gets a span carrying the request ids and a histogram, and
        every future leaves with its ``trace`` dict attached."""
        t_cut_ns = time.perf_counter_ns()  # the batch is cut HERE
        now = time.monotonic()
        ready = []
        for r in batch:
            if r.future.cancelled():
                continue
            if r.expired(now):
                self._bump("timeouts")
                if obs.enabled():
                    obs.counter("serve/timeouts").inc()
                try:
                    r.future.set_exception(DeadlineExceeded(
                        "deadline passed while queued (batching window + "
                        "queue wait exceeded the request deadline)"))
                except Exception:
                    pass
                continue
            if not r.future.set_running_or_notify_cancel():
                continue
            ready.append(r)
        with obs.span("serve/assemble", rids=[r.rid for r in ready]):
            x, live = assemble(ready, template_shape=self.input_shape,
                               dtype=self.input_dtype)
        t_asm_ns = time.perf_counter_ns()
        if len(ready) != len(live):
            self._bump("request_errors", len(ready) - len(live))
        if x is None:
            return
        n = len(live)
        rids = [r.rid for r in live]
        assemble_ms = (t_asm_ns - t_cut_ns) / 1e6
        if obs.enabled():
            qh = obs.histogram("serve/queue_wait_ms", unit="ms")
            for r in live:
                # retro-span from the request's enqueue stamp: the wait
                # is over by the time it is measurable. One virtual
                # lane per request (tid=-(rid+1)): a batch's waits all
                # end at the cut and would otherwise fake-nest as
                # contained siblings on the batcher thread
                obs.complete("serve/queue_wait", r.t_enqueue_ns, t_cut_ns,
                             tid=-(r.rid + 1), rid=r.rid)
                qh.observe((t_cut_ns - r.t_enqueue_ns) / 1e6)
            obs.histogram("serve/assemble_ms", unit="ms").observe(
                assemble_ms)
        bucket = self._bucket_for(n)
        mv = self.registry.current()  # ONE version per batch — swap boundary
        sp = obs.span("serve/batch", bucket=bucket, n=n, version=mv.version)
        t_fwd_ns = time.perf_counter_ns()

        def forward():
            _chaos.maybe_fire("serving/engine_dispatch", tag=self.name)
            xd = self._place_batch(pad_leading(x, bucket))
            out = self._fwd(mv.params, mv.state, xd)
            # sync-ok: serving result readback — the micro-batch
            # is the pipeline unit; its clients are blocked on
            # exactly this result
            return np.asarray(out)

        pol = self.fault_policy
        try:
            with sp:
                attempt = 0
                while True:
                    try:
                        with obs.span("serve/dispatch" if attempt == 0
                                      else "serve/retry_dispatch",
                                      rids=rids, bucket=bucket,
                                      version=mv.version):
                            host = forward()
                        pol.record_success()
                        break
                    except BaseException as e:  # noqa: BLE001 — classify
                        # Tier-2 replay through the ONE shared policy
                        # surface (parallel/failure.FaultPolicy — the
                        # trainer's and the decode scheduler's): a
                        # TRANSIENT failure re-dispatches after backoff,
                        # max_restarts bounds CONSECUTIVE failures so a
                        # flaky transport is absorbed but a persistent
                        # one never head-of-line-blocks the queue
                        cls = classify_failure(e)
                        if self._stop.is_set() or not pol.should_retry(cls):
                            raise
                        pol.record_failure()
                        attempt += 1
                        self._bump("transient_retries")
                        if obs.enabled():
                            obs.counter("serve/transient_retries").inc()
                            _health.emit("serve_retry", bucket=bucket, n=n,
                                         version=mv.version, attempt=attempt,
                                         error=f"{type(e).__name__}: {e}")
                        delay = pol.backoff_s()
                        if delay > 0:
                            pol.sleep(delay)
        except BaseException as e:  # noqa: BLE001 — batch fails, batcher lives
            # THIS batch is done failing; the next batch is a fresh
            # dispatch unit and gets its own retry budget (without the
            # reset, one exhausted batch would disable the transient
            # safety net for every batch after it)
            pol.reset()
            self._bump("batch_errors")
            if obs.enabled():
                obs.counter("serve/batch_errors").inc()
            for r in live:
                try:
                    r.future.set_exception(e)
                except Exception:
                    pass
            return
        dispatch_ms = (time.perf_counter_ns() - t_fwd_ns) / 1e6
        if obs.enabled():
            obs.histogram("serve/dispatch_ms", unit="ms").observe(
                dispatch_ms)
        for i, r in enumerate(live):
            r.future.version = mv.version
            r.future.trace = {
                "rid": r.rid,
                "queue_wait_ms": (t_cut_ns - r.t_enqueue_ns) / 1e6,
                "assemble_ms": assemble_ms,
                "dispatch_ms": dispatch_ms,
                "bucket": bucket,
                "version": mv.version,
            }
            try:
                # copy, not a view: a client caching its row must not pin
                # the whole [bucket, ...] readback buffer in memory
                r.future.set_result(host[i].copy())
            except Exception:
                pass
        self._bump("batches")
        self._bump("completed", n)
        if obs.enabled():
            obs.counter("serve/batches").inc()
            obs.counter("serve/requests").inc(n)
            obs.histogram("serve/batch_occupancy").observe(n / bucket)
            _flight.record("serve/batch", n=n, bucket=bucket,
                           version=mv.version, rid_first=rids[0],
                           rid_last=rids[-1],
                           dispatch_ms=round(dispatch_ms, 3))

    # -- internals -------------------------------------------------------

    def _bucket_for(self, n: int) -> int:
        """Padded batch size for ``n`` live rows: power-of-two bucket,
        rounded UP to a multiple of the mesh batch-shard count (every
        shard must get whole rows — a mesh with a non-power-of-two data
        degree, e.g. after an elastic reshape to 3 hosts, still gets
        divisible buckets; ``max_batch`` itself is validated divisible
        at construction, so the cap is always reachable)."""
        b = max(bucket_for(n, self.max_batch), self._bucket_floor)
        f = self._bucket_floor
        if b % f:
            b = min(self.max_batch, -(-b // f) * f)
        return b

    def _buckets(self):
        """The reachable bucket set — ``shape_buckets`` mapped through
        the shard-divisibility rounding (what warmup precompiles)."""
        out = []
        for b in shape_buckets(self.max_batch):
            rb = self._bucket_for(b)
            if rb not in out:
                out.append(rb)
        return tuple(out)

    def _place_batch(self, x):
        """Host batch → device: the mesh path shards the leading dim
        with the engine's batch spec (``P(("replica", "data"))``-style);
        the single-device path keeps the staged device_put."""
        if self._batch_sharding is not None:
            return jax.device_put(x, self._batch_sharding)
        return place_host_value(x)

    def _on_done(self, future, t_enqueue):
        # latency covers SERVED requests only — rejections resolve in µs
        # and would drag the histogram's low quantiles to zero
        if obs.enabled() and not future.cancelled() \
                and future.exception() is None:
            obs.histogram("serve/latency_ms", unit="ms").observe(
                (time.monotonic() - t_enqueue) * 1000.0)
        with self._cond:
            self._pending -= 1
            self._cond.notify_all()

    def _bump(self, key: str, n: int = 1):
        with self._stats_lock:
            self._stats[key] += n


def serving_threads_alive() -> int:
    """Live batcher threads (tests assert 0 after shutdown)."""
    return sum(1 for t in threading.enumerate()
               if t.name == THREAD_NAME and t.is_alive())
