"""Fleet serving across processes (ISSUE 15).

The serving arc so far (PRs 8–14) put a mesh-sharded, prefix-aware,
chaos-hardened, speculating LM tier behind the Router — but every
replica lived in the Router's process. This module is the second tier:
replicas in OTHER processes, coordinated the way the TensorFlow system
paper splits a dataflow job across worker processes — a lightweight
control plane over files, a framed binary data plane for tensors
(``serving/transport.py``; "RPC Considered Harmful" is why control and
data are separate planes).

Three layers:

* **Membership + health** — each replica process runs a
  :class:`ReplicaAgent`: it serves its engine over a local-socket
  transport, registers in a FLEET DIRECTORY (one atomically-rewritten
  member file per agent, beaten on a cadence via
  ``parallel.failure.FileHeartbeat``), and extends the PR-7
  ``MetricSnapshotWriter`` snapshot with a ``serving`` section (queue
  depth, inflight, prefix summary, active model version) — so
  ``cluster.write_aggregate()`` merges the fleet into one view with no
  new machinery. The Router gains a :class:`RemoteReplica` adapter
  whose surface is exactly an engine's (``submit``/``registry``/
  ``cached_prefix_tokens``/``tags``/``shutdown``), so ALL the existing
  WFQ / deadline / prefix-affinity / class-tag / failover logic
  dispatches cross-process with zero changes to the dispatch contract.
  :class:`FleetMonitor` watches the member files and emits the SAME
  ``health/stall`` / ``health/stall_recovered`` events a local stall
  beacon would — a stale or dead agent is drained by the Router's
  existing machinery, and a dying scheduler's typed ``EngineStopped``
  (its ``.partial`` token prefix rides the error frame) feeds the PR-13
  ``_recover_decode`` KV-preserving failover unchanged.

* **Fleet swap** — ``Router.swap()`` already runs two-phase
  publish-then-activate against each replica's ``registry``;
  :class:`RemoteReplica` presents a registry shim that ships the new
  version's param tree over the wire (raw leaf bytes, one frame) and
  acks after the remote placement — so the two-phase contract (all
  replicas publish before any activates; version-pinned in-flight
  requests never mix) extends over the process boundary with the
  Router unmodified.

* **Disaggregated prefill/decode** — a PREFILL-specialist agent runs a
  prompt's chunked prefill and exports the finished prefix's KV blocks
  (``PagedKVCache.export_blocks``) together with the prefix cache's
  content chain keys; a DECODE-specialist adopts them
  (``adopt_serialized`` + ``PrefixCache.insert``) only after
  re-deriving the chain hash from the tokens under ITS active version
  and checking the page digest — a corrupt or version-skewed handoff
  is refused typed (:class:`KVHandoffError`). The adopted prefix is an
  ordinary prefix-cache entry, so the subsequent ``Router.submit``
  steers to the holder via prefix affinity and admission takes the
  warm-hit path — which is the PR-12 bitwise lever: disaggregated
  tokens are bitwise the monolithic scheduler's. A failed handoff
  (death mid-hop, refused adopt) degrades to a plain submit: the
  decode replica prefills itself — slower, never wrong.

Chaos sites ``fleet/agent_beat`` (agent death drills),
``fleet/transport`` (flaky fabric), and ``fleet/handoff`` (death
mid-handoff) make process failure a routine, recovered event
(docs/RESILIENCE.md "Serving faults"; ``make fleet-smoke``). Metrics
ride ``serve/fleet_*`` (docs/OBSERVABILITY.md). Run a replica process
with ``python -m bigdl_tpu.serving.fleet <config.json>``.
"""
from __future__ import annotations

import hashlib
import itertools
import json
import logging
import os
import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from .. import observability as obs
from ..observability import cluster as _cluster
from ..observability import flight as _flight
from ..observability import health as _health
from ..parallel import chaos as _chaos
from ..parallel.failure import (FileHeartbeat, TRANSIENT,
                                TransientDeviceError, classify_failure)
from .batching import (DeadlineExceeded, EngineStopped, QueueFull,
                       ServeFuture)
from .kv_cache import KVCacheOOM
from .prefix_cache import chain_keys
from .transport import (RemoteError, TransportClient, TransportClosed,
                        TransportServer, decode_tree, encode_tree,
                        pick_advertise_host)

_LOG = logging.getLogger("bigdl_tpu.serving.fleet")

MEMBER_SCHEMA = "bigdl_tpu.fleet_member.v1"
AGENT_THREAD = "bigdl_tpu-fleet-agent"
MONITOR_THREAD = "bigdl_tpu-fleet-monitor"

#: agent process exit code after an injected/organic death (the
#: supervisor's signal that this was a crash, not a clean drain)
DEATH_EXIT_CODE = 86

#: replica roles. "replica" serves the full prefill+decode path;
#: "prefill" specializes in chunked prefill + KV export; "decode"
#: specializes in decode over adopted prefixes. Roles are labels for
#: discovery/routing — every scheduler-backed agent can serve every op.
ROLES = ("replica", "prefill", "decode")


class KVHandoffError(RuntimeError):
    """A prefill→decode KV handoff the receiver REFUSED: content chain
    hash mismatch (corrupt or mis-tokenized payload), page-digest
    mismatch (corrupt pages), version skew (the prefix was built under
    a model version the receiver no longer serves), or geometry
    mismatch. Typed so the handoff client degrades to a plain submit
    instead of decoding over garbage KV."""


# -- fleet directory -------------------------------------------------------

def member_path(fleet_dir: str, name: str) -> str:
    return os.path.join(fleet_dir, f"fleet_{name}.json")


def read_member(fleet_dir: str, name: str) -> Optional[Dict]:
    doc = FileHeartbeat.read(member_path(fleet_dir, name))
    if doc is None or doc.get("schema") != MEMBER_SCHEMA:
        return None
    return doc


def discover(fleet_dir: str, role: Optional[str] = None) -> List[Dict]:
    """Every registered member's latest doc (sorted by name), optionally
    filtered by role. Half-written or foreign files are skipped."""
    if not os.path.isdir(fleet_dir):
        return []
    out = []
    for fname in sorted(os.listdir(fleet_dir)):
        if not (fname.startswith("fleet_") and fname.endswith(".json")):
            continue
        doc = FileHeartbeat.read(os.path.join(fleet_dir, fname))
        if doc is None or doc.get("schema") != MEMBER_SCHEMA:
            continue
        if role is not None and doc.get("role") != role:
            continue
        out.append(doc)
    return out


def wait_for_members(fleet_dir: str, names: Sequence[str],
                     timeout_s: float = 120.0) -> List[Dict]:
    """Block until every named agent has registered (spawned processes
    pay a jax import + warmup before their first beat); raises
    ``TimeoutError`` naming the missing members."""
    deadline = time.monotonic() + timeout_s
    docs: Dict[str, Dict] = {}
    while time.monotonic() < deadline:
        for n in names:
            if n not in docs:
                d = read_member(fleet_dir, n)
                if d is not None:
                    docs[n] = d
        if len(docs) == len(names):
            return [docs[n] for n in names]
        time.sleep(0.1)
    missing = [n for n in names if n not in docs]
    raise TimeoutError(f"fleet members never registered: {missing} "
                       f"(dir {fleet_dir})")


# -- error mapping ---------------------------------------------------------

_TYPED = {
    "QueueFull": QueueFull,
    "DeadlineExceeded": DeadlineExceeded,
    "EngineStopped": EngineStopped,
    "KVCacheOOM": KVCacheOOM,
    "KVHandoffError": KVHandoffError,
    "TransientDeviceError": TransientDeviceError,
    "ValueError": ValueError,
    "TimeoutError": TimeoutError,
}


def _rehydrate(err: RemoteError) -> BaseException:
    """A peer's typed error frame back into the matching LOCAL exception
    type — with the dead scheduler's ``.partial`` token prefix attached
    when it rode the frame — so the Router's isinstance-driven
    failover/recovery logic cannot tell remote from local failures."""
    cls = _TYPED.get(err.type_name, RuntimeError)
    exc = cls(str(err))
    if err.meta.get("has_partial") and err.arrays:
        exc.partial = np.asarray(err.arrays[0], np.int32).reshape(-1)
    return exc


# -- the replica-side agent ------------------------------------------------

class ReplicaAgent:
    """One replica process's membership + serving endpoint.

    Wraps an engine (a :class:`~.decode_scheduler.DecodeScheduler`; a
    plain :class:`~.engine.ServingEngine` serves the non-LM subset of
    ops) with: a :class:`~.transport.TransportServer` answering fleet
    ops, a ``FileHeartbeat``-beaten member file in ``fleet_dir`` (the
    router side's liveness + load signal), and a
    ``MetricSnapshotWriter`` extended with the ``serving`` section —
    the fleet's observability rides the PR-7 cluster files unchanged.

    Death discipline: a PERMANENT fault in the beat loop (the
    ``fleet/agent_beat`` chaos site), or the engine loop dying under
    us, runs :meth:`_die` — the engine's no-drain shutdown fails every
    in-flight request typed with its generated ``.partial`` (those
    error frames FLUSH over the still-open transport before the server
    closes), the member file gets a terminal ``dead: true`` beat, and
    the process exits ``DEATH_EXIT_CODE``. The router side recovers:
    partials splice through ``Router._recover_decode`` on a survivor,
    bitwise."""

    def __init__(self, engine, *, fleet_dir: str,
                 name: Optional[str] = None, role: str = "replica",
                 tags: Sequence[str] = (), beat_s: float = 0.25,
                 host: str = "127.0.0.1", port: int = 0,
                 advertise_host: Optional[str] = None,
                 snapshot_every_s: Optional[float] = None,
                 process_index: Optional[int] = None):
        if role not in ROLES:
            raise ValueError(f"role must be one of {ROLES}, got {role!r}")
        self.engine = engine
        self.fleet_dir = fleet_dir
        self.name = name or getattr(engine, "name", None) \
            or f"agent{os.getpid()}"
        self.role = role
        self.tags = tuple(tags) or tuple(getattr(engine, "tags", ()))
        self.beat_s = float(beat_s)
        self._host, self._port = host, int(port)
        # the address the MEMBER FILE carries: cross-host peers dial
        # this, not the bind address. A wildcard bind ("0.0.0.0")
        # auto-resolves to this host's outbound interface; an explicit
        # advertise_host wins (NAT/multi-homed boxes). Single-host
        # fleets keep the loopback default untouched.
        self.advertise_host = (advertise_host
                               or pick_advertise_host(host))
        self.server: Optional[TransportServer] = None
        self._hb = FileHeartbeat(member_path(fleet_dir, self.name))
        self._snap = _cluster.MetricSnapshotWriter(
            every_s=(self.beat_s if snapshot_every_s is None
                     else snapshot_every_s),
            directory=fleet_dir,
            process_index=(os.getpid() % 100000 if process_index is None
                           else process_index))
        # the snapshot's serving section reuses the beat tick's already-
        # computed section when one exists — _serving_section takes the
        # engine's stats locks, and paying that twice per tick (member
        # file + snapshot) doubles lock traffic against a hot decode
        # loop for identical data
        self._section: Optional[Dict] = None
        self._snap.add_section(
            "serving", lambda: (self._section if self._section is not None
                                else self._serving_section()))
        self._stop = threading.Event()
        self._beat_thread: Optional[threading.Thread] = None
        self._dead = False
        self._shutting_down = False
        self._finished = False
        self._died_once = threading.Lock()
        # serializes member-file/snapshot writes against the terminal
        # final/dead beat: an in-flight cadence beat landing AFTER the
        # terminal one would strip final:true — the monitor would then
        # read a cleanly-exited agent as a wedged one, the exact
        # misattribution the final flag exists to prevent
        self._beat_write = threading.Lock()
        self._started_at = time.time()
        self._handoff_ids = itertools.count()
        self.exit_code = 0

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "ReplicaAgent":
        os.makedirs(self.fleet_dir, exist_ok=True)
        self.engine.start()
        self.server = TransportServer(self._handle, host=self._host,
                                      port=self._port,
                                      name=self.name).start()
        self._hb.beat(self._member_doc())   # register before first beat
        self._beat_thread = threading.Thread(
            target=self._beat_loop, name=f"{AGENT_THREAD}[{self.name}]",
            daemon=True)
        self._beat_thread.start()
        _LOG.info("fleet agent %s (%s) serving on %s:%d, dir %s",
                  self.name, self.role, self.server.host,
                  self.server.port, self.fleet_dir)
        return self

    def run(self) -> int:
        """Drive a standalone replica process: start, serve until a
        ``shutdown`` op or death, clean up. Returns the exit code."""
        if self.server is None:
            self.start()
        self._stop.wait()
        self._finish()
        return self.exit_code

    def shutdown(self, drain: bool = True):
        """Programmatic local stop (tests / embedded agents)."""
        self._shutting_down = True
        try:
            self.engine.shutdown(drain=drain)
        finally:
            self._finish()
            t = self._beat_thread
            if t is not None and t is not threading.current_thread():
                t.join(5.0)

    def _finish(self):
        """Terminal state, exactly once: final membership beat + final
        snapshot (skipped after :meth:`_die`, which already landed its
        ``dead: true`` terminal state), stop signal, server close. Safe
        from any thread — the server close skips joining its caller."""
        with self._died_once:
            first = not self._finished
            self._finished = True
        if first and not self._dead:
            with self._beat_write:
                self._hb.beat(self._member_doc(), final=True)
                self._snap.write(final=True)
        self._stop.set()
        if self.server is not None:
            self.server.close()

    # -- membership ------------------------------------------------------

    def _serving_section(self) -> Dict:
        """The snapshot/membership ``serving`` section: the router's
        remote load/health/affinity signal, and the schema documented in
        docs/SERVING.md "Fleet serving". Pure host reads."""
        eng = self.engine
        out = {"name": self.name, "role": self.role,
               "tags": list(self.tags)}
        try:
            st = eng.stats()
            out["queue_depth"] = st.get("queue_depth", 0)
            out["inflight"] = (st.get("active", 0)
                               + st.get("prefilling", 0))
            out["pending"] = st.get("pending", 0)
            out["active_version"] = st.get("active_version")
            kv = st.get("kv") or {}
            out["kv_blocks_in_use"] = kv.get("blocks_in_use")
            pre = st.get("prefix")
            if pre:
                # the prefix SUMMARY (entries/shared blocks/max chain):
                # enough for capacity planning; the per-prompt affinity
                # probe stays an RPC because it needs the prompt
                out["prefix"] = {
                    "entries": pre.get("entries"),
                    "shared_blocks": pre.get("shared_blocks"),
                    "max_chain_blocks": pre.get("max_chain_blocks")}
        except Exception:  # noqa: BLE001 — membership must not die
            pass
        for attr in ("hit_align", "max_seq_len", "prefill_chunk"):
            v = getattr(eng, attr, None)
            if v is not None:
                out[attr] = int(v)
        kvc = getattr(eng, "kv", None)
        if kvc is not None:
            out["block_size"] = int(kvc.block_size)
        return out

    def _member_doc(self, section: Optional[Dict] = None) -> Dict:
        return {"schema": MEMBER_SCHEMA, "name": self.name,
                "role": self.role, "tags": list(self.tags),
                "host": self.advertise_host,
                "port": self.server.port if self.server else self._port,
                "started_at": self._started_at,
                "dead": self._dead,
                "serving": (self._serving_section() if section is None
                            else section)}

    def _beat_loop(self):
        """The agent's heartbeat: one member-file rewrite + one snapshot
        cadence check per tick. The ``fleet/agent_beat`` chaos seam
        fires here — a transient rule skips ONE beat (reads as a late
        beat), a wedge rule goes silent until the monitor's staleness
        threshold drains us (and rejoins on recovery), a permanent rule
        IS the agent-death drill."""
        while not self._stop.is_set():
            try:
                _chaos.maybe_fire("fleet/agent_beat", tag=self.name)
            except BaseException as e:  # noqa: BLE001 — classify
                if classify_failure(e) == TRANSIENT:
                    if obs.enabled():
                        obs.counter("serve/fleet_beat_faults").inc()
                    self._stop.wait(self.beat_s)
                    continue
                self._die(f"injected agent fault: "
                          f"{type(e).__name__}: {e}")
                return
            et = getattr(self.engine, "_thread", None)
            if et is not None and not et.is_alive() \
                    and not self._stop.is_set() \
                    and not self._shutting_down:
                # the engine loop died under us (a permanent dispatch
                # fault): its _die already failed every in-flight
                # request typed-with-partial — finish the job as a
                # whole-process death so the fleet stops routing here.
                # (a CLEANLY drained engine — the shutdown op sets
                # _shutting_down first — is not a death)
                self._die("engine loop died")
                return
            sec = self._serving_section()
            with self._beat_write:
                # re-check under the write lock: _finish/_die may have
                # landed the terminal beat while this tick was building
                # its doc — a cadence beat must never overwrite it
                if self._finished or self._dead:
                    return
                self._section = sec
                self._hb.beat(self._member_doc(sec))
                if obs.enabled():
                    obs.counter("serve/fleet_beats").inc()
                self._snap.maybe_write()
            self._stop.wait(self.beat_s)

    def _die(self, reason: str):
        """Agent death: fail in-flight typed-with-partial (the error
        frames flush over the open transport — the router's
        KV-preserving splice point), mark the member file dead, stop."""
        with self._died_once:
            if self._dead:
                return
            self._dead = True
        _LOG.error("fleet agent %s dying: %s", self.name, reason)
        _health.emit("fleet_agent_died", agent=self.name,
                     reason=reason)
        if obs.enabled():
            obs.counter("serve/fleet_agent_deaths").inc()
        try:
            # no-drain shutdown: every in-flight request fails typed
            # EngineStopped with .partial — the submit handlers' done
            # callbacks send those error frames NOW, before the server
            # closes below
            self.engine.shutdown(drain=False, timeout=10.0)
        except Exception:  # noqa: BLE001 — dying anyway
            pass
        with self._died_once:
            self._finished = True   # the dead beat IS the terminal state
        with self._beat_write:
            self._hb.beat(self._member_doc(), final=True)
            self._snap.write(final=True)
        self.exit_code = DEATH_EXIT_CODE
        self._stop.set()
        if self.server is not None:
            # safe from any thread — close skips joining its caller
            self.server.close()

    # -- op handlers -----------------------------------------------------

    def _handle(self, reply, op, meta, arrays):
        if op == "ping":
            reply({"name": self.name, "role": self.role,
                   "tags": list(self.tags)})
        elif op == "submit":
            self._op_submit(reply, meta, arrays)
        elif op == "stats":
            reply(_flight._json_safe(self.engine.stats()))
        elif op == "prefix_probe":
            probe = getattr(self.engine, "cached_prefix_tokens", None)
            n = int(probe(arrays[0])) if callable(probe) else 0
            reply({"tokens": n})
        elif op == "publish":
            # placement runs for seconds on a real model — off the
            # connection's reader thread, like the handoff ops, so
            # in-flight submits/probes on this connection keep flowing
            # through the whole swap window (errors reply typed; a
            # failed publish is the swapping caller's problem, not a
            # dying agent)
            self._spawn_op(self._op_publish, reply, meta, arrays)
        elif op == "activate":
            self.engine.registry.activate(meta["version"])
            try:
                self.engine._bump("swaps")
            except Exception:  # noqa: BLE001 — stats only
                pass
            if obs.enabled():
                obs.instant("serve/swap", version=meta["version"],
                            replica=self.name)
            reply({"version": meta["version"]})
        elif op == "retire":
            self.engine.registry.retire(meta["version"])
            reply({"version": meta["version"]})
        elif op == "set_role":
            # the controller's promotion seam: roles are discovery/
            # routing labels (every scheduler-backed agent serves every
            # op), so a decode→prefill promotion is a label flip plus
            # an immediate member-file rewrite — peers discover the new
            # duty on their next directory read, no engine restart
            role = meta["role"]
            if role not in ROLES:
                raise ValueError(f"role must be one of {ROLES}, "
                                 f"got {role!r}")
            old = self.role
            self.role = role
            if "tags" in meta:
                self.tags = tuple(meta["tags"])
            with self._beat_write:
                if not (self._finished or self._dead):
                    self._section = self._serving_section()
                    self._hb.beat(self._member_doc(self._section))
            if obs.enabled():
                obs.instant("serve/fleet_role_flip", agent=self.name,
                            from_role=old, to_role=role)
            _health.emit("fleet_role_flip", agent=self.name,
                         from_role=old, to_role=role)
            reply({"role": role, "was": old})
        elif op == "prefill_export":
            self._guard_handoff(self._export_prefix, reply, meta, arrays)
        elif op == "adopt_prefix":
            self._guard_handoff(self._adopt_prefix, reply, meta, arrays)
        elif op == "chaos_arm":
            _chaos.arm(meta["plan"])
            reply({"armed": True})
        elif op == "chaos_stats":
            reply(_chaos.stats())
        elif op == "shutdown":
            et = getattr(self.engine, "_thread", None)
            if (not self._shutting_down and not self._dead
                    and et is not None and not et.is_alive()):
                # the engine loop already died organically — the beat
                # loop's death detection (one beat_s tick of latency)
                # races a router drain RPC here. A dead engine must
                # never launder into a clean exit 0: answer typed so
                # the caller's drain moves on, then take the death
                # path (DEATH_EXIT_CODE, dead member file).
                reply(error={"type": "EngineStopped",
                             "msg": f"agent {self.name}: engine loop "
                                    "died before shutdown"})
                self._die("engine loop died (caught at shutdown)")
                return
            drain = bool(meta.get("drain", True))
            self._shutting_down = True
            self.engine.shutdown(drain=drain)
            st = self.engine.stats()
            reply({"kv_blocks_in_use": (st.get("kv") or {}).get(
                "blocks_in_use"), "stats": _flight._json_safe(
                {k: v for k, v in st.items() if k != "prefix"})})
            self._finish()
        else:
            raise ValueError(f"unknown fleet op {op!r}")

    def _op_submit(self, reply, meta, arrays):
        kw = {}
        for k in ("max_new_tokens", "deadline_ms", "temperature",
                  "top_p", "seed", "eos_id"):
            # presence-based, not None-filtered: an EXPLICIT
            # eos_id=None (disable EOS stopping — distinct from the
            # scheduler's "default" sentinel) must survive the wire,
            # or remote tokens diverge from the in-process replica's
            if k in meta:
                kw[k] = meta[k]
        fut = self.engine.submit(arrays[0], **kw)
        if obs.enabled():
            obs.counter("serve/fleet_agent_submits").inc()

        def done(f):
            exc = f.exception()
            if exc is None:
                reply(meta={"version": f.version,
                            "trace": _flight._json_safe(f.trace)},
                      arrays=[np.asarray(f.result(), np.int32)])
                return
            partial = getattr(exc, "partial", None)
            err = {"type": type(exc).__name__, "msg": str(exc)}
            if partial is not None:
                reply(meta={"has_partial": True},
                      arrays=[np.asarray(partial, np.int32).reshape(-1)],
                      error=err)
            else:
                reply(error=err)

        fut.add_done_callback(done)

    def _op_publish(self, reply, meta, arrays):
        params = decode_tree(meta["params_spec"], arrays)
        if meta.get("state_is_none", True):
            # the params-only swap contract, applied replica-side: the
            # compiled step's state pytree must not change shape
            cur = self.engine.registry.current()
            state = (cur.state if cur is not None
                     else getattr(self.engine.model, "state", None))
        else:
            state = decode_tree(meta["state_spec"], arrays)
        v = self.engine.registry.publish(
            params, state, version=meta.get("version"),
            activate=bool(meta.get("activate", False)))
        reply({"version": v})

    # -- disaggregation: prefill export / decode adopt -------------------

    def _spawn_op(self, fn, reply, meta, arrays):
        """Run a slow op on its own worker thread, answering typed on
        failure (no death discipline — for ops whose failure is the
        caller's error, not an agent fault)."""
        def run():
            try:
                fn(reply, meta, arrays)
            except BaseException as e:  # noqa: BLE001 — answer typed
                self._try_reply(reply, {"type": type(e).__name__,
                                        "msg": str(e)})

        threading.Thread(target=run,
                         name=f"{AGENT_THREAD}-op[{self.name}]",
                         daemon=True).start()

    def _guard_handoff(self, fn, reply, meta, arrays):
        """Handoff ops under the death discipline, on their OWN worker
        thread: an export may block minutes on a cold prefill, and the
        transport contract says handlers must not camp on the
        connection's reader thread (a concurrent export/stats/shutdown
        RPC would sit unread in the socket behind it — the prefill pool
        could never pipeline). A typed refusal (:class:`KVHandoffError`)
        and a transient fault answer the client and leave the agent
        alive; a PERMANENT fault (the ``fleet/handoff`` chaos site's
        death drill, or a genuinely dead device under the page fetch)
        kills THIS agent AFTER the typed error frame goes out — process
        death mid-handoff must be a routine, recovered event on the
        client side (it degrades to a plain submit), not a special
        case."""
        def run():
            try:
                fn(reply, meta, arrays)
            except KVHandoffError as e:
                self._try_reply(reply, {"type": "KVHandoffError",
                                        "msg": str(e)})
            except BaseException as e:  # noqa: BLE001 — classify
                self._try_reply(reply, {"type": type(e).__name__,
                                        "msg": str(e)})
                if classify_failure(e) != TRANSIENT:
                    self._die(f"permanent handoff fault: "
                              f"{type(e).__name__}: {e}")

        threading.Thread(target=run,
                         name=f"{AGENT_THREAD}-handoff[{self.name}]",
                         daemon=True).start()

    @staticmethod
    def _try_reply(reply, error: Dict):
        """Error-frame a handoff failure; swallow a double-reply (the
        op already answered before raising) — the client is resolved
        either way."""
        try:
            reply(error=error)
        except Exception:  # noqa: BLE001 — already replied
            pass

    def _export_prefix(self, reply, meta, arrays):
        """Prefill-specialist op: make the prompt's aligned prefix
        resident (running its chunked prefill here if it is not), then
        export the prefix-cache chain's KV pages + content keys for a
        decode specialist to adopt. The ``fleet/handoff`` chaos seam
        fires first: an injected fault presents to the client exactly
        like a specialist dying mid-handoff (degrade to plain submit,
        never block the request)."""
        _chaos.maybe_fire("fleet/handoff", tag=self.name)
        sched = self.engine
        prefix = getattr(sched, "prefix", None)
        if prefix is None:
            raise KVHandoffError(
                "prefill specialist needs a prefix cache (the export "
                "handle IS a prefix entry)")
        prompt = np.asarray(arrays[0], np.int32).reshape(-1)
        align = int(sched.hit_align)
        n = (int(prompt.size) // align) * align
        if n <= 0:
            reply({"tokens": 0})
            return
        sub = prompt[:n]
        v = sched.registry.current().version
        if sched.cached_prefix_tokens(sub) < n:
            # cold: run the aligned prefix's chunked prefill here (one
            # discarded token — the cheapest way to ride the exact
            # admission/registration path the bitwise gates pin); the
            # export keys under the version the prefill actually pinned
            fut = sched.submit(sub, max_new_tokens=1)
            # sync-ok: export waits for the prefill it is exporting
            fut.result(timeout=float(meta.get("timeout_s", 300.0)))
            v = fut.version or v
        chain = prefix.lookup(sub, v)
        bs = sched.kv.block_size
        usable = min(len(chain) * bs, n)
        usable -= usable % align
        if usable <= 0:
            reply({"tokens": 0})
            return
        ids = chain[:usable // bs]
        # pin against a concurrent eviction between lookup and export;
        # LOSING that race (an admission-path evict freed the chain
        # between the two calls) is a routine typed refusal — the
        # client degrades to a plain submit — not a dying specialist
        try:
            sched.kv.retain(ids)
        except ValueError as e:
            raise KVHandoffError(
                f"prefix evicted during export: {e}") from e
        try:
            _, layers = sched.kv.export_blocks(blocks=ids)
        finally:
            sched.kv.release(ids)
        keys = [k.hex() for k in chain_keys(prompt[:usable], bs, v)]
        out_arrays = [prompt[:usable]]
        digest = hashlib.blake2b(digest_size=16)
        nbytes = 0
        for k, vv in layers:
            for a in (k, vv):
                a = np.ascontiguousarray(a)
                digest.update(a.tobytes())
                nbytes += a.nbytes
                out_arrays.append(a)
        if obs.enabled():
            obs.counter("serve/fleet_handoff_exports").inc()
            obs.counter("serve/fleet_handoff_bytes").inc(nbytes)
        reply(meta={"tokens": usable, "version": v, "keys": keys,
                    "geometry": sched.kv.geometry(),
                    "digest": digest.hexdigest()},
              arrays=out_arrays)

    def _adopt_prefix(self, reply, meta, arrays):
        """Decode-specialist op: verify and adopt a handed-off prefix.
        The chain hash is re-derived HERE from the tokens under THIS
        replica's active version — the exported keys must match
        exactly, so a corrupt payload or a version-skewed handoff is
        refused typed before any page lands; the page digest guards the
        raw bytes themselves. On success the prefix is an ordinary
        content-addressed cache entry: the next submit of a prompt
        carrying it takes the PR-12 warm-hit path (bitwise the cold
        decode)."""
        _chaos.maybe_fire("fleet/handoff", tag=self.name)
        sched = self.engine
        prefix = getattr(sched, "prefix", None)
        try:
            if prefix is None or getattr(sched, "_quarantined", False):
                raise KVHandoffError(
                    "replica cannot adopt: prefix cache disabled or "
                    "ledger quarantined")
            tokens = np.asarray(arrays[0], np.int32).reshape(-1)
            mv = sched.registry.current()
            if meta.get("version") != mv.version:
                raise KVHandoffError(
                    f"version skew: handoff built under "
                    f"{meta.get('version')!r}, replica serves "
                    f"{mv.version!r} — refusing stale KV")
            geo = sched.kv.geometry()
            if meta.get("geometry") != geo:
                raise KVHandoffError(
                    f"geometry mismatch: {meta.get('geometry')} vs "
                    f"{geo}")
            bs = sched.kv.block_size
            want_keys = [k.hex() for k in chain_keys(tokens, bs,
                                                     mv.version)]
            if want_keys != list(meta.get("keys", ())):
                raise KVHandoffError(
                    "content chain-hash mismatch — the tokens do not "
                    "derive the exported keys under this version; "
                    "refusing corrupt handoff")
            pages = arrays[1:]
            if len(pages) != 2 * geo["n_layers"]:
                raise KVHandoffError(
                    f"expected {2 * geo['n_layers']} page arrays, got "
                    f"{len(pages)}")
            digest = hashlib.blake2b(digest_size=16)
            for a in pages:
                digest.update(np.ascontiguousarray(a).tobytes())
            if digest.hexdigest() != meta.get("digest"):
                raise KVHandoffError(
                    "page digest mismatch — KV bytes corrupted in "
                    "transit; refusing handoff")
        except KVHandoffError as e:
            if obs.enabled():
                obs.counter("serve/fleet_handoff_refused").inc()
            _health.emit("fleet_handoff_refused", agent=self.name,
                         reason=str(e))
            raise
        layers = [(pages[2 * i], pages[2 * i + 1])
                  for i in range(len(pages) // 2)]
        owner = ("handoff", next(self._handoff_ids))
        try:
            try:
                ids = sched.kv.adopt_serialized(owner, layers)
            except KVCacheOOM:
                # block pressure: reclaim unreferenced prefix entries
                # like admission does, then retry ONCE
                prefix.evict(len(layers[0][0]))
                ids = sched.kv.adopt_serialized(owner, layers)
        except KVCacheOOM as e:
            # a still-full pool is routine block pressure on a busy
            # decode replica, not a dying agent: refuse typed so the
            # client degrades to a plain submit (the replica prefills
            # itself) instead of _guard_handoff reading the OOM as a
            # permanent fault and killing the process
            if obs.enabled():
                obs.counter("serve/fleet_handoff_refused").inc()
            _health.emit("fleet_handoff_refused", agent=self.name,
                         reason=str(e))
            raise KVHandoffError(
                f"adopt refused under block pressure: {e}") from e
        try:
            prefix.insert(tokens, mv.version, ids)
        finally:
            sched.kv.free(owner)
        if obs.enabled():
            obs.counter("serve/fleet_handoff_adopts").inc()
            obs.counter("serve/fleet_handoff_blocks").inc(len(ids))
        reply({"adopted_blocks": len(ids), "tokens": int(tokens.size)})


# -- the router-side adapter -----------------------------------------------

class _RemoteVersion:
    """What ``RemoteReplica.registry.current()`` hands ``Router.swap``:
    ``state=None`` routes the state-inherit decision to the AGENT side
    (its registry holds the real active state — shipping it back and
    forth would copy the model twice per swap for nothing)."""
    __slots__ = ("version", "params", "state")

    def __init__(self, version):
        self.version = version
        self.params = None
        self.state = None


class _RemoteRegistry:
    """The registry shim ``Router.swap``'s two-phase protocol drives:
    ``publish`` ships the param tree (raw leaf bytes, one frame) and
    returns after the REMOTE placement finished — so the router's
    all-published-before-any-activates guarantee spans processes."""

    def __init__(self, rep: "RemoteReplica",
                 publish_timeout_s: float = 600.0):
        self._rep = rep
        self._timeout = publish_timeout_s

    def current(self):
        return _RemoteVersion(self._rep.active_version())

    def publish(self, params, state=None, version: Optional[str] = None,
                activate: bool = False) -> str:
        bufs: List[np.ndarray] = []
        spec = encode_tree(_np_tree(params), bufs)
        meta = {"version": version, "params_spec": spec,
                "state_is_none": state is None, "activate": activate}
        if state is not None:
            meta["state_spec"] = encode_tree(_np_tree(state), bufs)
        m, _ = self._rep._request("publish", meta, bufs,
                                  timeout=self._timeout)
        return m["version"]

    def activate(self, version: str):
        self._rep._request("activate", {"version": version},
                           timeout=self._timeout)

    def retire(self, version: str):
        self._rep._request("retire", {"version": version},
                           timeout=self._timeout)


def _np_tree(tree):
    """Pytree → host numpy leaves (the publish wire format). The fetch
    is deliberate and rides the SWAPPING caller's thread, exactly where
    the registry contract puts placement cost."""
    import jax
    return jax.tree_util.tree_map(np.asarray, tree)


class RemoteReplica:
    """An engine-shaped handle to a fleet agent in another process.

    Presents the exact surface the :class:`~.router.Router` dispatches
    against — ``submit(payload, deadline_ms=..., **kw) -> ServeFuture``,
    ``name``/``beacon_name``/``tags``, ``registry`` (the two-phase swap
    shim), ``cached_prefix_tokens`` (the affinity probe, an RPC with a
    short timeout that degrades to 0), ``start``/``shutdown``/``stats``
    — so a fleet of processes drops into the router's replica list with
    zero routing-logic changes. Failure mapping: a transient transport
    fault raises typed from ``submit`` (the router's try-next-replica
    path); a LOST connection fails in-flight futures ``EngineStopped``
    and makes later submits raise it too (the router marks the replica
    dead); a dying scheduler's error frames carry ``.partial`` (the
    router's KV-preserving splice)."""

    def __init__(self, doc: Optional[Dict] = None, *,
                 fleet_dir: Optional[str] = None,
                 name: Optional[str] = None,
                 probe_timeout_s: float = 2.0,
                 rpc_timeout_s: float = 120.0):
        if doc is None:
            if fleet_dir is None or name is None:
                raise ValueError("pass a member doc, or fleet_dir+name")
            doc = read_member(fleet_dir, name)
            if doc is None:
                raise ValueError(f"no fleet member {name!r} registered "
                                 f"in {fleet_dir}")
        self.doc = doc
        self.fleet_dir = fleet_dir
        self.name = doc["name"]
        self.role = doc.get("role", "replica")
        self.tags = tuple(doc.get("tags", ()))
        self.host, self.port = doc["host"], int(doc["port"])
        self.beacon_name = f"serving/fleet[{self.name}]"
        self.registry = _RemoteRegistry(self)
        self.model = _RemoteVersion(None)   # .state for Router.swap
        self._client = TransportClient(self.host, self.port,
                                       name=self.name)
        self._probe_timeout = float(probe_timeout_s)
        self._rpc_timeout = float(rpc_timeout_s)
        self._active_version: Optional[str] = doc.get(
            "serving", {}).get("active_version")
        self._stats: Dict[str, int] = {}

    # -- engine surface --------------------------------------------------

    def start(self) -> "RemoteReplica":
        self._client.connect()
        return self

    def submit(self, payload, deadline_ms: Optional[float] = None,
               **kw) -> ServeFuture:
        """Dispatch one request to the remote engine. The frame SEND is
        synchronous (a flaky-fabric fault raises typed right here, into
        the router's transient retry); the returned future resolves
        from the transport receiver thread when the remote answers."""
        prompt = np.asarray(payload, np.int32).reshape(-1)
        meta = {"deadline_ms": deadline_ms}
        for k in ("max_new_tokens", "temperature", "top_p", "seed",
                  "eos_id"):
            # forward exactly what the caller passed — an explicit
            # eos_id=None is a real override (disable EOS stopping),
            # not an absence; dropping it would silently re-enable the
            # remote scheduler's default and break process transparency
            if k in kw:
                meta[k] = kw[k]
        unknown = set(kw) - {"max_new_tokens", "temperature", "top_p",
                             "seed", "eos_id"}
        if unknown:
            raise ValueError(f"unsupported remote submit kwargs "
                             f"{sorted(unknown)}")
        outer = ServeFuture()
        if self._client.closed:
            raise EngineStopped(
                f"fleet transport to {self.name} is closed")
        try:
            inner = self._client.request_async("submit", meta, [prompt])
        except TransportClosed as e:
            raise EngineStopped(
                f"fleet replica {self.name} unreachable: {e}") from e
        if obs.enabled():
            obs.counter("serve/fleet_remote_submits").inc()

        def done(f):
            exc = f.exception()
            if exc is None:
                m, arrays = f.result()
                outer.version = m.get("version")
                outer.trace = m.get("trace")
                self._active_version = m.get("version") \
                    or self._active_version
                res = (np.asarray(arrays[0], np.int32).reshape(-1)
                       if arrays else np.zeros((0,), np.int32))
                try:
                    outer.set_result(res)
                except Exception:  # noqa: BLE001 — cancelled outer
                    pass
                return
            if isinstance(exc, RemoteError):
                exc = _rehydrate(exc)
            elif isinstance(exc, TransportClosed):
                exc = EngineStopped(
                    f"fleet replica {self.name} connection lost mid-"
                    f"request: {exc}")
            try:
                outer.set_exception(exc)
            except Exception:  # noqa: BLE001 — cancelled outer
                pass

        inner.add_done_callback(done)
        return outer

    def generate(self, prompt_ids, max_new_tokens: int,
                 timeout: Optional[float] = None, **kw):
        return self.submit(prompt_ids, max_new_tokens=max_new_tokens,
                           **kw).result(timeout)

    def cached_prefix_tokens(self, prompt_ids) -> int:
        """The router's prefix-affinity probe, over the wire. Degrades
        to 0 on any fault/timeout — a probe must never stall dispatch."""
        try:
            m, _ = self._request(
                "prefix_probe", arrays=[np.asarray(prompt_ids, np.int32)],
                timeout=self._probe_timeout)
            return int(m.get("tokens", 0))
        except Exception:  # noqa: BLE001 — degrade, never stall routing
            return 0

    def stats(self) -> Dict:
        m, _ = self._request("stats", timeout=self._rpc_timeout)
        return m

    def active_version(self) -> Optional[str]:
        return self._active_version

    def member(self) -> Optional[Dict]:
        """The latest membership doc (None once the file is gone)."""
        if self.fleet_dir is None:
            return None
        return read_member(self.fleet_dir, self.name)

    def reconnect(self) -> bool:
        """Re-establish a LOST transport from the latest member doc (a
        restarted agent registers a fresh port). The agent may be
        perfectly alive behind a torn connection — one transient frame
        loss must not remove a healthy, still-beating replica from the
        fleet forever. The FleetMonitor calls this when the member file
        is fresh but the client is closed; the subsequent ``not down``
        tick emits ``stall_recovered`` and the router rejoins. Returns
        True when a fresh connection is up."""
        doc = self.member() or self.doc
        if doc.get("dead") or doc.get("final"):
            return False
        try:
            cli = TransportClient(doc["host"], int(doc["port"]),
                                  name=self.name).connect()
        except OSError:
            return False
        old = self._client
        self.doc = doc
        self.host, self.port = doc["host"], int(doc["port"])
        self._client = cli
        old.close()
        return True

    def shutdown(self, drain: bool = True, timeout: float = 120.0):
        """Stop the REMOTE agent (drain by default), then drop the
        connection. Unreachable agents are already down — ignored."""
        try:
            if not self._client.closed:
                self._request("shutdown", {"drain": drain},
                              timeout=timeout)
        except Exception:  # noqa: BLE001 — the agent is gone either way
            pass
        self._client.close()

    def close(self):
        """Drop the connection WITHOUT stopping the remote agent."""
        self._client.close()

    # -- fleet ops -------------------------------------------------------

    def prefill_export(self, prompt, timeout: Optional[float] = None):
        """(meta, arrays) of the remote's exported aligned prefix."""
        return self._request(
            "prefill_export", {"timeout_s": timeout or self._rpc_timeout},
            [np.asarray(prompt, np.int32)],
            timeout=timeout or self._rpc_timeout)

    def adopt_prefix(self, meta: Dict, arrays,
                     timeout: Optional[float] = None):
        return self._request("adopt_prefix", meta, arrays,
                             timeout=timeout or self._rpc_timeout)

    def chaos_arm(self, plan: Dict):
        """Arm a chaos plan INSIDE the agent process (campaign drills)."""
        return self._request("chaos_arm", {"plan": plan},
                             timeout=self._rpc_timeout)

    def set_role(self, role: str, tags: Optional[Sequence[str]] = None):
        """Flip the remote agent's duty label (controller promotion).
        The agent rewrites its member file immediately; this handle's
        ``role``/``tags`` mirror the flip on the ack."""
        meta: Dict = {"role": role}
        if tags is not None:
            meta["tags"] = list(tags)
        m, _ = self._request("set_role", meta, timeout=self._rpc_timeout)
        self.role = m["role"]
        if tags is not None:
            self.tags = tuple(tags)
        return m

    def _request(self, op, meta=None, arrays=(), timeout=None):
        self._client.connect()
        try:
            return self._client.request(op, meta, arrays, timeout=timeout)
        except RemoteError as e:
            raise _rehydrate(e) from None

    def _bump(self, key: str, n: int = 1):
        self._stats[key] = self._stats.get(key, 0) + n


# -- file-heartbeat health monitor -----------------------------------------

class FleetMonitor:
    """Watches the fleet directory and converts membership-file
    staleness into the health events the Router already acts on.

    For each :class:`RemoteReplica`: a member file that is marked
    ``dead``, has gone stale past ``stale_s``, or whose transport
    connection dropped, emits ``health/stall`` with the replica's
    beacon name — the router DRAINS it and fails over its in-flight
    work exactly as if a local stall beacon fired; a member that beats
    again emits ``health/stall_recovered`` and rejoins. A ``final``
    (cleanly drained) member is treated as down without the alarm.
    One monitor thread per router process; pure host file reads.

    Staleness is CROSS-HOST SAFE: it is judged by beat-COUNTER progress
    against THIS OBSERVER's monotonic clock — the member's ``beat``
    counter not advancing for ``stale_s`` observer-seconds is the stall
    signal, exactly how the in-job ``failure.Heartbeat`` judges peers
    by counter progress. The member file's wall-clock ``written_at``
    stamp is never compared against the observer's wall clock, so an
    agent on a host whose clock is skewed hours off (NTP drift, a VM
    resume) cannot be false-killed while it is beating perfectly well —
    and a frozen observer clock cannot hide a genuinely wedged agent."""

    def __init__(self, replicas: Sequence[RemoteReplica], *,
                 fleet_dir: str, every_s: float = 0.25,
                 stale_s: float = 5.0):
        self.replicas = list(replicas)
        self.fleet_dir = fleet_dir
        self.every_s = float(every_s)
        self.stale_s = float(stale_s)
        self._up: Dict[str, bool] = {r.name: True for r in self.replicas}
        # per-member (last beat counter seen, observer-monotonic stamp
        # of when it last ADVANCED) — the cross-host-safe staleness
        # state; a member first seen counts as advancing right then
        self._progress: Dict[str, tuple] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _progress_age_s(self, name: str, doc: Optional[Dict],
                        now: float) -> float:
        """Observer-monotonic seconds since ``name``'s beat counter last
        advanced; ``inf`` for a missing doc (nothing to make progress)."""
        if doc is None:
            # a transient read miss (file mid-rewrite) must NOT reset
            # the staleness clock: keep the (beat, stamp) entry so the
            # next successful read continues a frozen member's age
            # instead of re-seeding it at 0. unwatch() is what forgets
            # a member for good.
            return float("inf")
        beat = doc.get("beat")
        if not isinstance(beat, (int, float)):
            return float("inf")
        last = self._progress.get(name)
        # any counter CHANGE is progress — a restarted agent's counter
        # resets to 1, and "went backwards" must read as a fresh
        # incarnation beating, not as ten minutes of silence
        if last is None or beat != last[0]:
            self._progress[name] = (beat, now)
            return 0.0
        return max(0.0, now - last[1])

    def start(self) -> "FleetMonitor":
        self._thread = threading.Thread(target=self._loop,
                                        name=MONITOR_THREAD, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(5.0)

    def watch(self, rep: RemoteReplica):
        """Start monitoring a replica that joined after start() (the
        controller's scale-up path). Idempotent by name."""
        if all(r.name != rep.name for r in self.replicas):
            self.replicas.append(rep)
        self._up.setdefault(rep.name, True)

    def unwatch(self, name: str):
        """Stop monitoring a retired replica (scale-down): its member
        file going final/stale afterwards is retirement, not a stall."""
        self.replicas = [r for r in self.replicas if r.name != name]
        self._up.pop(name, None)
        self._progress.pop(name, None)

    def _loop(self):
        while not self._stop.is_set():
            alive = 0
            for rep in list(self.replicas):
                doc = read_member(self.fleet_dir, rep.name)
                # beat-counter progress vs OUR monotonic clock — never
                # the member file's wall-clock stamp (cross-host skew
                # must not false-kill a beating agent)
                age = self._progress_age_s(rep.name, doc,
                                           time.monotonic())
                dead = bool(doc and doc.get("dead"))
                finished = bool(doc and doc.get("final") and not dead)
                if (doc is not None and not dead and not finished
                        and age <= self.stale_s and rep._client.closed):
                    # fresh beats behind a torn connection: the agent
                    # is alive — re-dial it (agent restarts land a new
                    # port; reconnect reads the latest doc) so the
                    # rejoin below can actually happen
                    try:
                        rep.reconnect()
                    except Exception:  # noqa: BLE001 — stays down
                        pass
                down = (doc is None or dead or finished
                        or age > self.stale_s
                        or rep._client.closed)
                if not down:
                    alive += 1
                was_up = self._up.get(rep.name, True)
                if down and was_up:
                    self._up[rep.name] = False
                    if not finished:
                        _health.emit(
                            "stall", component=rep.beacon_name,
                            source="fleet_monitor", age_s=round(age, 3)
                            if age != float("inf") else None,
                            dead=dead)
                        if obs.enabled():
                            obs.counter("serve/fleet_agent_drains").inc()
                elif not down and not was_up:
                    self._up[rep.name] = True
                    _health.emit("stall_recovered",
                                 component=rep.beacon_name,
                                 source="fleet_monitor")
            if obs.enabled():
                obs.gauge("serve/fleet_agents_alive").set(alive)
            self._stop.wait(self.every_s)


# -- disaggregated prefill/decode front ------------------------------------

class DisaggregatedFleet:
    """The prefill-pool/decode-pool front: long prompts prefill on a
    specialist, their KV hands off in one framed binary hop, and the
    request itself rides the ordinary Router — whose prefix-affinity
    probe steers it to the adopting replica, where admission takes the
    PR-12 warm-hit path (tokens bitwise the monolithic scheduler).

    Failure discipline: ANY handoff failure — specialist death
    mid-export (``fleet/handoff`` chaos), a refused adopt
    (:class:`KVHandoffError` — corrupt/version-skewed payloads), block
    pressure on the decode side — is counted and DEGRADED: the request
    submits normally and the decode replica runs its own prefill.
    Slower, never lost, never wrong.

    When to split pools at all: docs/SERVING.md "Fleet serving"
    (decision guide + handoff sizing math)."""

    def __init__(self, router, prefill: Sequence[RemoteReplica],
                 decode: Sequence[RemoteReplica], *,
                 min_handoff_tokens: Optional[int] = None,
                 handoff_timeout_s: float = 300.0):
        self.router = router
        self.prefill = list(prefill)
        self.decode = list(decode)
        if not self.prefill or not self.decode:
            raise ValueError("need at least one prefill and one decode "
                             "replica")
        # the alignment the specialists share: exported prefixes are
        # hit_align-aligned, so a shorter prompt cannot hand off
        self.align = int(self.prefill[0].doc.get("serving", {})
                         .get("hit_align", 8))
        self.min_handoff_tokens = (self.align if min_handoff_tokens is None
                                   else int(min_handoff_tokens))
        self.handoff_timeout_s = float(handoff_timeout_s)
        self._rr = 0
        self._stats = {"handoffs": 0, "handoff_tokens": 0,
                       "handoff_failed": 0, "handoff_refused": 0,
                       "direct": 0}
        self._lock = threading.Lock()

    def submit(self, prompt_ids, max_new_tokens: int,
               klass: str = "default", **kw) -> ServeFuture:
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        n = (int(prompt.size) // self.align) * self.align
        if n >= self.min_handoff_tokens:
            self._handoff(prompt[:n])
        else:
            self._bump("direct")
        return self.router.submit(prompt, klass=klass,
                                  max_new_tokens=max_new_tokens, **kw)

    def _handoff(self, sub: np.ndarray):
        try:
            pf = next((p for p in self.prefill if not p._client.closed),
                      None)
            if pf is None:
                raise EngineStopped("no live prefill specialist")
            t0 = time.monotonic()
            meta, arrays = pf.prefill_export(
                sub, timeout=self.handoff_timeout_s)
            if meta.get("tokens", 0) <= 0:
                self._bump("direct")
                return
            healthy = set(self.router.healthy_replicas())
            targets = [d for d in self.decode
                       if d.name in healthy and not d._client.closed]
            if not targets:
                raise EngineStopped("no live decode replica to adopt")
            with self._lock:
                self._rr += 1
                target = targets[self._rr % len(targets)]
            target.adopt_prefix(
                {"version": meta["version"], "keys": meta["keys"],
                 "geometry": meta["geometry"],
                 "digest": meta["digest"]},
                arrays, timeout=self.handoff_timeout_s)
            self._bump("handoffs")
            self._bump("handoff_tokens", int(meta["tokens"]))
            if obs.enabled():
                obs.counter("serve/fleet_handoffs").inc()
                obs.counter("serve/fleet_handoff_tokens").inc(
                    int(meta["tokens"]))
                # per-hop export→adopt wall time: the number that says
                # whether the handoff hop is paying for itself against
                # the decode replica just prefilling locally
                obs.histogram("serve/fleet_handoff_ms",
                              unit="ms").observe(
                    (time.monotonic() - t0) * 1000.0)
        except KVHandoffError as e:
            self._bump("handoff_refused")
            _LOG.warning("KV handoff refused (degrading to plain "
                         "submit): %s", e)
        except Exception as e:  # noqa: BLE001 — degrade, never fail
            self._bump("handoff_failed")
            if obs.enabled():
                obs.counter("serve/fleet_handoff_failed").inc()
            _LOG.warning("KV handoff failed (%s: %s) — request degrades "
                         "to a plain submit", type(e).__name__, e)

    # -- pool membership (controller scale/promotion) --------------------

    def add_prefill(self, rep: RemoteReplica):
        """Admit a replica to the prefill pool (promotion lands here
        AFTER the role flip + any version alignment). List replacement,
        not append: ``_handoff`` reads the pool without the lock."""
        with self._lock:
            if all(p.name != rep.name for p in self.prefill):
                self.prefill = self.prefill + [rep]

    def remove_prefill(self, name: str) -> Optional[RemoteReplica]:
        with self._lock:
            gone = next((p for p in self.prefill if p.name == name), None)
            self.prefill = [p for p in self.prefill if p.name != name]
        return gone

    def add_decode(self, rep: RemoteReplica):
        with self._lock:
            if all(d.name != rep.name for d in self.decode):
                self.decode = self.decode + [rep]

    def remove_decode(self, name: str) -> Optional[RemoteReplica]:
        with self._lock:
            gone = next((d for d in self.decode if d.name == name), None)
            self.decode = [d for d in self.decode if d.name != name]
        return gone

    def swap(self, params, state=None,
             version: Optional[str] = None) -> str:
        """Fleet swap covering BOTH pools. ``Router.swap`` two-phases
        only ITS replicas (the decode pool) — prefill specialists are
        not in the router's dispatch list, and one left behind on the
        old version would version-skew-refuse EVERY handoff from then
        on: safe (each degrades to a plain submit, counted in
        ``serve/fleet_handoff_refused``) but the pool silently stops
        paying for itself. Order: publish to the prefill pool first
        (specialists keep exporting the OLD version — decode replicas
        still on it adopt fine), two-phase the decode pool through the
        router, then activate the specialists. The only skew window is
        one export already in flight around the flip, and the refusal
        path makes that a degraded submit, never a wrong token."""
        published = []
        v = version or f"dv{id(self) & 0xffff}.{next(_swap_ids)}"
        try:
            for p in self.prefill:
                # state=None rides the wire as state_is_none: the AGENT
                # side inherits its active version's state (the
                # params-only swap contract, applied replica-side)
                p.registry.publish(params, state, version=v,
                                   activate=False)
                published.append(p)
        except BaseException:
            for p in published:
                try:
                    p.registry.retire(v)
                except Exception:  # noqa: BLE001 — best-effort unwind
                    pass
            raise
        self.router.swap(params, state=state, version=v)
        for p in self.prefill:
            p.registry.activate(v)
        return v

    def stats(self) -> Dict:
        with self._lock:
            return dict(self._stats)

    def _bump(self, key: str, n: int = 1):
        with self._lock:
            self._stats[key] = self._stats.get(key, 0) + n


#: DisaggregatedFleet.swap version-id stream (process-local)
_swap_ids = itertools.count(1)


def warm_replica(source: RemoteReplica, target: RemoteReplica,
                 prompts, *, timeout_s: float = 300.0) -> Dict:
    """Warm a (re)joining replica's prefix cache from a peer: for each
    prompt, export ``source``'s aligned prefix chain and adopt it on
    ``target`` through the ordinary content-key-verified handoff — the
    new replica's first requests for warmed prompts take the PR-12
    warm-hit path instead of each paying a cold prefill.

    The host tier (ISSUE 18) is what makes the SOURCE side cheap: a
    chain the source evicted under block pressure lives on in its host
    pool, so the export's lookup REFILLS it (a second-chance hit)
    rather than re-running the prefill — warming a peer from a busy
    replica costs swap-ins, not recompute. Failure discipline is
    per-prompt degrade, the fleet's usual: a refused adopt (version
    skew, block pressure on the target) or a dying export skips THAT
    prompt and moves on — warming is an optimization pass, it must
    never take a joining replica down.

    Returns ``{"warmed", "tokens", "skipped", "failed"}`` counts.
    Administrative path (replica join/rebalance) — not a hot loop."""
    out = {"warmed": 0, "tokens": 0, "skipped": 0, "failed": 0}
    for p in prompts:
        try:
            meta, arrays = source.prefill_export(p, timeout=timeout_s)
            if meta.get("tokens", 0) <= 0:
                out["skipped"] += 1      # shorter than the alignment
                continue
            target.adopt_prefix(
                {"version": meta["version"], "keys": meta["keys"],
                 "geometry": meta["geometry"],
                 "digest": meta["digest"]},
                arrays, timeout=timeout_s)
            out["warmed"] += 1
            out["tokens"] += int(meta["tokens"])
        except Exception as e:  # noqa: BLE001 — per-prompt degrade
            out["failed"] += 1
            _LOG.warning("warm_replica: prompt skipped (%s: %s)",
                         type(e).__name__, e)
    if obs.enabled():
        obs.counter("serve/fleet_warm_prompts").inc(out["warmed"])
        obs.counter("serve/fleet_warm_tokens").inc(out["tokens"])
    return out


def fleet_threads_alive() -> int:
    """Live agent/monitor threads (tests assert 0 after shutdown)."""
    return sum(1 for t in threading.enumerate() if t.is_alive()
               and (t.name.startswith(AGENT_THREAD)
                    or t.name == MONITOR_THREAD))


# -- standalone replica process driver -------------------------------------

def agent_from_config(cfg: Dict) -> ReplicaAgent:
    """Build a scheduler-backed agent from a config dict::

        {"fleet_dir": ..., "name": "r0", "role": "replica",
         "tags": ["f32"], "beat_s": 0.25, "process_index": 1,
         "observability": true,
         "host": "0.0.0.0", "port": 0,            # bind address
         "advertise_host": "10.0.0.7",            # optional override
         "model": {...TransformerLM kwargs...},
         "params_path": "/path/params.pkl",       # optional np pytree
         "scheduler": {...DecodeScheduler kwargs...},
         "chaos": {...chaos plan...}}             # optional

    ``host`` is the BIND address (``"0.0.0.0"`` for cross-host fleets);
    the member file advertises ``advertise_host`` — auto-detected from
    the outbound interface on a wildcard bind — so peers on other hosts
    sharing the membership directory dial a reachable address, never
    ``localhost``. ``params_path`` (a pickled numpy param tree, written
    by the parent) pins every process to ONE param set regardless of
    ambient RNG history — the fleet's bitwise gates depend on it."""
    from ..models.transformer_lm import TransformerLM
    from .decode_scheduler import DecodeScheduler

    if cfg.get("observability", False):
        obs.enable()
    model = TransformerLM(**cfg.get("model", {}))
    model.ensure_initialized()
    if cfg.get("params_path"):
        import pickle
        import jax.numpy as jnp
        import jax
        with open(cfg["params_path"], "rb") as f:
            host = pickle.load(f)
        model.params = jax.tree_util.tree_map(jnp.asarray, host)
    sched_kw = dict(cfg.get("scheduler", {}))
    sched_kw.setdefault("name", cfg.get("name"))
    sched_kw.setdefault("tags", cfg.get("tags", ()))
    sched = DecodeScheduler(model, **sched_kw)
    if cfg.get("chaos"):
        _chaos.arm(cfg["chaos"])
    return ReplicaAgent(
        sched, fleet_dir=cfg["fleet_dir"], name=cfg.get("name"),
        role=cfg.get("role", "replica"), tags=cfg.get("tags", ()),
        beat_s=cfg.get("beat_s", 0.25),
        host=cfg.get("host", "127.0.0.1"),
        port=cfg.get("port", 0),
        advertise_host=cfg.get("advertise_host"),
        process_index=cfg.get("process_index"))


def main(argv=None) -> int:
    import sys
    argv = sys.argv if argv is None else argv
    if len(argv) != 2:
        print("usage: python -m bigdl_tpu.serving.fleet <config.json>",
              file=sys.stderr)
        return 2
    with open(argv[1]) as f:
        cfg = json.load(f)
    agent = agent_from_config(cfg)
    agent.start()
    return agent.run()


if __name__ == "__main__":  # pragma: no cover — subprocess entry
    raise SystemExit(main())
