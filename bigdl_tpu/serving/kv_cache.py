"""Paged KV cache: fixed-size HBM blocks + per-request block tables.

Dense per-request KV caches fragment HBM under heterogeneous sequence
lengths: a (B, kvH, Tmax, D) cache reserves Tmax positions for every
row, so a 32-token request pins the same memory as a 2048-token one and
the batch dimension must be rebuilt (recompile + realloc) whenever the
request mix changes. The paged layout (vLLM's PagedAttention scheme)
pools ALL cache memory into ``num_blocks`` fixed-size blocks of
``block_size`` token positions each, per layer:

    k_pages, v_pages : (num_blocks, kvH, block_size, D)

and gives each request a BLOCK TABLE — logical block ``i`` of its
sequence lives at physical page ``table[i]``. Requests allocate blocks
one at a time as they grow and return them on completion/eviction, so
the only unusable memory is the tail of each request's last block
(< block_size tokens): internal fragmentation is bounded and external
fragmentation is zero by construction. The attention side
(``nn.Attention.decode_paged``) scatters new K/V through the table and
attends over the gathered logical view.

Block 0 is the reserved NULL block: unallocated table entries and the
padded slots of a partially-filled decode bucket all point there, so a
padded row's writes land in garbage space that no real row ever reads.

Accounting is exported live (``serve/kv_*`` gauges/counters — see
docs/OBSERVABILITY.md) and the block ledger is the engine's admission
authority: a request is only admitted when its worst-case block need
(prompt + max_new_tokens + speculative overshoot) fits the free list,
so a decode step can never fail mid-flight on cache exhaustion.

GEMM M-class note (the continuous-batching bitwise gate): XLA CPU
lowers total-row-count-1 matmuls to a gemv kernel whose accumulation
differs in the last ulp from the gemm used for >= 2 rows; all >= 2-row
shapes agree bitwise row-for-row (measured, tests/test_serving_lm.py).
The decode scheduler therefore never dispatches a 1-row program — the
active-row bucket floor is 2 — which is what makes a request's tokens
bitwise-identical whether it decodes alone or mid-swarm.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import observability as obs


class KVCacheOOM(RuntimeError):
    """The free list cannot cover a requested allocation. Typed so the
    scheduler's admission control can defer (keep the request queued)
    rather than fail it."""


def blocks_for_tokens(tokens: int, block_size: int) -> int:
    """Blocks needed to hold ``tokens`` positions (ceil division)."""
    return -(-int(tokens) // int(block_size))


class PagedKVCache:
    """Pooled block storage + the host-side block ledger for one model.

    Pages are functional jax arrays: the compiled decode step takes the
    current pages as inputs and returns updated ones; the scheduler
    stores the new handles back via :meth:`set_pages`. The ledger
    (free list, per-owner block lists) is plain host state guarded by a
    lock — allocation never touches the device.
    """

    def __init__(self, model, *, num_blocks: int, block_size: int = 16,
                 max_blocks_per_seq: int, dtype=jnp.float32,
                 metric_prefix: str = "serve/kv",
                 sharding=None):
        if num_blocks < 2:
            raise ValueError(f"num_blocks must be >= 2 (block 0 is the "
                             f"reserved null block), got {num_blocks}")
        if block_size < 2 or (block_size & (block_size - 1)):
            # power of two keeps the prompt-bucket math exact (prompt
            # buckets are pow2 >= block_size, so padded prefill always
            # fills whole blocks) and the //, % in the scatter cheap
            raise ValueError(f"block_size must be a power of two >= 2, "
                             f"got {block_size}")
        if max_blocks_per_seq < 1:
            raise ValueError("max_blocks_per_seq must be >= 1")
        attn = model.blocks[0].attn
        # the gauge/counter namespace — a second cache in one engine
        # (the speculative draft's) must not overwrite the target's
        # ledger telemetry
        self.metric_prefix = metric_prefix
        self.block_size = int(block_size)
        self.num_blocks = int(num_blocks)
        self.max_blocks_per_seq = int(max_blocks_per_seq)
        self.max_seq_len = self.max_blocks_per_seq * self.block_size
        kvh = attn._kvh()
        d = model.hidden_size // attn.num_heads

        def _zeros():
            z = jnp.zeros((num_blocks, kvh, block_size, d), dtype)
            # mesh-sharded serving: the pooled pages live on the mesh
            # (kvH split over the model axis when it divides — the
            # decode-path HBM lever under tensor parallelism); the
            # compiled step's functional update keeps the placement
            return z if sharding is None else jax.device_put(z, sharding)
        self._pages = [(_zeros(), _zeros()) for _ in model.blocks]
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))
        self._owned: Dict[object, List[int]] = {}
        self._high_water = 0
        self._lock = threading.Lock()
        self._set_gauges()

    # -- device pages ----------------------------------------------------

    def pages(self):
        """The per-layer [(k_pages, v_pages), ...] pytree the compiled
        decode step reads AND replaces (functional update)."""
        return self._pages

    def set_pages(self, new_pages):
        self._pages = new_pages

    # -- ledger ----------------------------------------------------------

    def blocks_free(self) -> int:
        with self._lock:
            return len(self._free)

    def blocks_in_use(self) -> int:
        with self._lock:
            return sum(len(b) for b in self._owned.values())

    def owned(self, owner) -> int:
        """Blocks currently held by ``owner`` (0 when unknown)."""
        with self._lock:
            return len(self._owned.get(owner, ()))

    def can_allocate(self, n_blocks: int) -> bool:
        with self._lock:
            return n_blocks <= len(self._free)

    def ensure_capacity(self, owner, upto_tokens: int):
        """Grow ``owner``'s allocation so positions ``0..upto_tokens-1``
        fit. Raises :class:`KVCacheOOM` (allocating NOTHING) when the
        free list can't cover the growth, and ``ValueError`` past the
        table width — admission control checks both up front."""
        need = blocks_for_tokens(upto_tokens, self.block_size)
        if need > self.max_blocks_per_seq:
            raise ValueError(
                f"{upto_tokens} tokens need {need} blocks > "
                f"max_blocks_per_seq {self.max_blocks_per_seq} "
                f"(max_seq_len {self.max_seq_len})")
        with self._lock:
            have = self._owned.setdefault(owner, [])
            grow = need - len(have)
            if grow <= 0:
                return
            if grow > len(self._free):
                if not have:    # don't leave an empty ledger entry behind
                    self._owned.pop(owner, None)
                raise KVCacheOOM(
                    f"need {grow} blocks, {len(self._free)} free "
                    f"(in use {sum(len(b) for b in self._owned.values())}"
                    f"/{self.num_blocks - 1})")
            for _ in range(grow):
                have.append(self._free.pop())
            in_use = sum(len(b) for b in self._owned.values())
            self._high_water = max(self._high_water, in_use)
        if obs.enabled():
            obs.counter(f"{self.metric_prefix}_allocs").inc(grow)
        self._set_gauges()

    def free(self, owner) -> int:
        """Return every block ``owner`` holds to the free list (the
        completion/eviction path). Returns the count freed; unknown
        owners free 0 (idempotent — double-eviction is a no-op)."""
        with self._lock:
            blocks = self._owned.pop(owner, [])
            # LIFO reuse keeps the hot end of the pool dense
            self._free.extend(reversed(blocks))
        if blocks and obs.enabled():
            obs.counter(f"{self.metric_prefix}_frees").inc(len(blocks))
        self._set_gauges()
        return len(blocks)

    def block_table(self, owner) -> np.ndarray:
        """``owner``'s (max_blocks_per_seq,) int32 physical-block table,
        null-block(0)-padded past its allocation."""
        out = np.zeros((self.max_blocks_per_seq,), np.int32)
        with self._lock:
            blocks = self._owned.get(owner, ())
            out[:len(blocks)] = blocks
        return out

    def null_table(self) -> np.ndarray:
        """The all-null table a padded decode slot carries: every write
        lands in the reserved garbage block."""
        return np.zeros((self.max_blocks_per_seq,), np.int32)

    # -- defrag ----------------------------------------------------------

    def frag_blocks(self) -> int:
        """Address-space spread: the number of free holes below the
        highest allocated physical id — 0 when the allocation is
        perfectly packed at the low end of the pool (ids are 1-based;
        packed = ids 1..n). After enough churn the live blocks scatter
        across the pool; :meth:`defrag` repacks them."""
        with self._lock:
            ids = [b for blocks in self._owned.values() for b in blocks]
            if not ids:
                return 0
            return max(ids) - len(ids)

    def defrag(self) -> int:
        """Repack live blocks into the lowest physical ids: device-copy
        each out-of-place block's K/V pages down and rewrite the owning
        tables. Returns the number of blocks moved (``serve/kv_defrag_
        moves``). Run at a step boundary — tables handed to an in-flight
        dispatch must not be rewritten under it."""
        with self._lock:
            live = sorted(b for blocks in self._owned.values()
                          for b in blocks)
            n = len(live)
            targets = set(range(1, n + 1))
            moves = []          # (src, dst) pairs
            free_targets = sorted(targets - set(live))
            for src in sorted(b for b in live if b > n):
                moves.append((src, free_targets.pop(0)))
            if not moves:
                return 0
            remap = dict(moves)
            srcs = jnp.asarray([s for s, _ in moves], jnp.int32)
            dsts = jnp.asarray([d for _, d in moves], jnp.int32)
            self._pages = [
                (k.at[dsts].set(k[srcs]), v.at[dsts].set(v[srcs]))
                for k, v in self._pages]
            for blocks in self._owned.values():
                for i, b in enumerate(blocks):
                    blocks[i] = remap.get(b, b)
            self._free = list(range(self.num_blocks - 1, n, -1))
        if obs.enabled():
            obs.counter(f"{self.metric_prefix}_defrag_moves").inc(len(moves))
        self._set_gauges()
        return len(moves)

    # -- telemetry -------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            in_use = sum(len(b) for b in self._owned.values())
            return {
                "blocks_total": self.num_blocks - 1,  # null excluded
                "blocks_in_use": in_use,
                "blocks_free": len(self._free),
                "owners": len(self._owned),
                "high_water": self._high_water,
                "block_size": self.block_size,
                "max_blocks_per_seq": self.max_blocks_per_seq,
            }

    def _set_gauges(self):
        if not obs.enabled():
            return
        s = self.stats()
        pre = self.metric_prefix
        obs.gauge(f"{pre}_blocks_total").set(s["blocks_total"])
        obs.gauge(f"{pre}_blocks_in_use").set(s["blocks_in_use"])
        obs.gauge(f"{pre}_blocks_free").set(s["blocks_free"])
        obs.gauge(f"{pre}_high_water").set(s["high_water"])
        obs.gauge(f"{pre}_frag_blocks").set(self.frag_blocks())
